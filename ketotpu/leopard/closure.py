"""The Leopard closure index: flattened set-containment as sorted pairs.

The tuple graph's *set-containment* relation — node ``(ns, obj, rel)``
contains node ``(s_ns, s_obj, s_rel)`` whenever a tuple's subject is that
SubjectSet — is transitively closed here into two flat pair families:

* **set pairs** ``(ancestor_node, descendant_node, min_hops)`` — the
  closure of the containment edges themselves (no identity pairs);
* **element pairs** ``(set_node, element_subject, min_hops)`` — the
  headline ``(set_id, element_id)`` index: every subject (by vocab
  subject id, SubjectIDs and SubjectSets alike — the oracle's direct
  check matches both) reachable from a node through any number of
  containment hops, with the fewest hops recorded.

Both closures are built **vectorized on the host**: containment edges are
repeatedly self-joined (frontier doubling — min-plus matrix squaring, so
``ceil(log2(diameter))`` rounds) with numpy ``searchsorted``/``repeat``
CSR expansion and packed-int64 ``lexsort`` dedup, the same idiom
``delta.build_snapshot_cols`` uses.  No per-tuple Python loops.

Hop counts make check interception *depth-exact*: a pair at ``h`` hops is
found by the reference engine iff the remaining depth budget is at least
``h + 2`` (one level to enter the relation, one to match the subject —
see ``CheckEngine._check_is_allowed``'s depth guards).  A hit below that
budget simply declines, falling through to the normal device walk.

Exactness envelope.  Closure verdicts are the BFS-complete answer, which
is exactly the upper end of the engine's documented arbitration band
(any schedule's IS verdicts lie between the sequential-DFS run and the
closure).  Nodes where that band could disagree with the closure are
*tainted* and never intercepted: relations carrying a subject-set
rewrite (closure only models direct containment), nodes whose tuple
count reaches ``max_width`` (the oracle truncates there), and — by a
backward pass over the set closure — every node that can reach a tainted
one.

Incremental maintenance mirrors the delta-overlay contract
(`engine/delta.py`): additions **append** closure pairs (exact cross
products of known ancestors x known reachable elements, kept in small
delta dicts on top of the immutable base arrays), deletions **mark the
affected set ids dirty** (the node plus all its ancestors) so queries
touching them decline to the host oracle; anything the delta cannot
represent — an unknown node, a vocab miss, thresholds exceeded — asks
the engine for a (cheap, vectorized) rebuild instead of guessing.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ketotpu.api.types import RelationTuple, SubjectSet

# Containment chains of h hops need h + 2 depth budget in the reference
# engine (each _check_* level spends one unit; the final traverser match
# happens one level below the last expansion).
DEPTH_SLACK = 2

# Fused-dispatch probe row modes (engine/fused.py): prep_fused_checks
# resolves everything that needs host dict state (taint/dirty sets, the
# delta pair dict, the rewrite-eligibility test) into one int32 mode per
# row; the device finishes the clean rows with its in-program binary
# search.  The split is bit-identical to answer_checks by construction —
# see prep_fused_checks for the per-mode argument.
LM_NONE = 0  # ineligible: the index must not answer this row
LM_PROBE = 1  # clean, no delta pair: device formula answers
LM_ALLOW = 2  # pre-answered allow (delta pair within the depth budget)
LM_DENY = 3  # pre-answered deny (unknown node, rewrite-free relation)
LM_HIT_ONLY = 4  # delta pair beyond budget: answer only on base hit+depth

_EMPTY32 = np.empty(0, np.int32)


def _dedup_min(src: np.ndarray, dst: np.ndarray, hop: np.ndarray):
    """Dedup (src, dst) pairs keeping the minimum hop; sorted by packed key."""
    if len(src) == 0:
        return _EMPTY32, _EMPTY32, _EMPTY32
    packed = (src.astype(np.int64) << 32) | dst.astype(np.int64)
    # lexsort: last key is primary -> sorted by packed, ties by hop
    # ascending, so the first row of each key carries the min hop.
    order = np.lexsort((hop, packed))
    p = packed[order]
    first = np.ones(len(p), bool)
    first[1:] = p[1:] != p[:-1]
    keep = order[first]
    return (
        src[keep].astype(np.int32),
        dst[keep].astype(np.int32),
        hop[keep].astype(np.int32),
    )


def _compose(
    l_src: np.ndarray, l_dst: np.ndarray, l_hop: np.ndarray,
    r_src: np.ndarray, r_dst: np.ndarray, r_hop: np.ndarray,
):
    """Sparse relational join: (a->b, h1) x (b->c, h2) => (a->c, h1+h2).

    The right side must be sorted by ``r_src``.  Pure numpy CSR
    expansion: searchsorted for each left dst's run, repeat + arange for
    the flattened gather.
    """
    if len(l_src) == 0 or len(r_src) == 0:
        return _EMPTY32, _EMPTY32, _EMPTY32
    lo = np.searchsorted(r_src, l_dst, side="left")
    hi = np.searchsorted(r_src, l_dst, side="right")
    cnt = hi - lo
    total = int(cnt.sum())
    if total == 0:
        return _EMPTY32, _EMPTY32, _EMPTY32
    out_src = np.repeat(l_src, cnt)
    out_hop = np.repeat(l_hop, cnt)
    starts = np.repeat(lo, cnt)
    offs = np.arange(total, dtype=np.int64) - np.repeat(
        np.cumsum(cnt) - cnt, cnt
    )
    idx = starts + offs
    return out_src, r_dst[idx], out_hop + r_hop[idx]


class ClosureTooLarge(Exception):
    """Closure exceeded leopard.max_pairs — index disabled until shrunk."""


class ClosureIndex:
    """Immutable base pair arrays + bounded mutable delta on top.

    All ids are the engine vocab's dense int32 ids; node identity is the
    packed int64 key ``((ns * R + rel) << 32) | obj`` with ``R`` frozen
    at build time (the vocab is append-only, so ids never move — a
    relation id >= R simply cannot appear in an indexed tuple).
    """

    def __init__(
        self,
        *,
        max_pairs: int = 4_000_000,
        rebuild_delta_pairs: int = 4096,
        rebuild_dirty_sets: int = 512,
        max_width: int = 100,
    ):
        self.max_pairs = int(max_pairs)
        self.rebuild_delta_pairs = int(rebuild_delta_pairs)
        self.rebuild_dirty_sets = int(rebuild_dirty_sets)
        self.max_width = int(max_width)
        self.build_s = 0.0
        self.builds = 0
        self.fallbacks = 0  # queries/listings declined (dirty/tainted)
        self._reset_empty()

    # ------------------------------------------------------------- build

    def _reset_empty(self) -> None:
        self.R = 1
        self.nodes = np.empty(0, np.int64)  # sorted packed node keys
        self.n_nodes = 0
        # set closure, sorted by (src, dst)
        self.set_src = _EMPTY32
        self.set_dst = _EMPTY32
        self.set_hop = _EMPTY32
        # the same pairs re-ordered by (dst, src) for ancestor lookups
        self.rset_dst = _EMPTY32
        self.rset_src = _EMPTY32
        self.rset_hop = _EMPTY32
        # element closure: packed (set << 32 | elt) sorted, plus hops;
        # elt_set/elt_e are the unpacked views for slicing/enumeration
        self.elt_packed = np.empty(0, np.int64)
        self.elt_set = _EMPTY32
        self.elt_e = _EMPTY32
        self.elt_hop = _EMPTY32
        # per-elt ordering for reverse (ListObjects) lookups
        self.relt_e = _EMPTY32
        self.relt_set = _EMPTY32
        self.tainted = np.empty(0, bool)
        self._rewrite_his: Set[int] = set()
        self._reset_delta()

    def _reset_delta(self) -> None:
        self.dirty: Set[int] = set()
        # delta closures: exact additions since build (min-hop values)
        self._d_elt: Dict[Tuple[int, int], int] = {}  # (set, e) -> hop
        self._d_elt_by_set: Dict[int, Dict[int, int]] = {}
        self._d_elt_by_e: Dict[int, Dict[int, int]] = {}
        self._d_set_by_src: Dict[int, Dict[int, int]] = {}
        self._d_set_by_dst: Dict[int, Dict[int, int]] = {}
        self._d_taint: Set[int] = set()
        self._d_node_tuples: Dict[int, int] = {}

    @property
    def pairs(self) -> int:
        return int(len(self.elt_packed)) + len(self._d_elt)

    @property
    def dirty_sets(self) -> int:
        return len(self.dirty)

    def stats(self) -> Dict[str, float]:
        return {
            "pairs": float(self.pairs),
            "set_pairs": float(len(self.set_src)),
            "nodes": float(self.n_nodes),
            "dirty_sets": float(self.dirty_sets),
            "delta_pairs": float(len(self._d_elt)),
            "build_s": self.build_s,
            "builds": float(self.builds),
            "fallbacks": float(self.fallbacks),
        }

    def build_from_cols(self, cols, manager) -> None:
        """Vectorized full (re)build from the engine's column cache.

        Raises :class:`ClosureTooLarge` when the closure would exceed
        ``max_pairs``; the caller should then disable the index (queries
        fall back to the normal paths) rather than serve a truncation.
        """
        t0 = time.perf_counter()
        self._reset_empty()
        vocab = cols.vocab
        self.R = max(len(vocab.relations), 1)
        R = np.int64(self.R)

        live = np.flatnonzero(cols.alive[: cols.n])
        if len(live):
            ns = cols.ns[live].astype(np.int64)
            rel = cols.rel[live].astype(np.int64)
            obj = cols.obj[live].astype(np.int64)
            packed = ((ns * R + rel) << 32) | obj
            self.nodes = np.unique(packed)
            self.n_nodes = int(len(self.nodes))
            node_of_row = np.searchsorted(self.nodes, packed).astype(np.int32)

            # every live row is a direct member (the oracle's direct
            # check matches SubjectSet subjects by equality too)
            d_node = node_of_row
            d_subj = cols.subj[live]

            # containment edges: rows whose subject is a SubjectSet AND
            # whose target node has tuples of its own (an edge into an
            # empty node contributes no members; tuples appearing there
            # later arrive via the changelog and re-key the node table)
            is_set = cols.is_set[live] == 1
            e_rows = np.flatnonzero(is_set)
            if len(e_rows):
                t_ns = cols.s_ns[live][e_rows].astype(np.int64)
                t_rel = cols.s_rel[live][e_rows].astype(np.int64)
                t_obj = cols.s_obj[live][e_rows].astype(np.int64)
                t_packed = ((t_ns * R + t_rel) << 32) | t_obj
                pos = np.searchsorted(self.nodes, t_packed)
                pos_c = np.minimum(pos, self.n_nodes - 1)
                known = self.nodes[pos_c] == t_packed
                e_src = d_node[e_rows[known]]
                e_dst = pos_c[known].astype(np.int32)
                e_hop = np.ones(len(e_src), np.int32)
            else:
                e_src = e_dst = e_hop = _EMPTY32

            # --- set closure: frontier doubling (min-plus squaring) ---
            src, dst, hop = _dedup_min(e_src, e_dst, e_hop)
            keep = src != dst
            src, dst, hop = src[keep], dst[keep], hop[keep]
            for _ in range(64):
                if len(src) > self.max_pairs:
                    self._reset_empty()
                    raise ClosureTooLarge(
                        f"set closure exceeds max_pairs={self.max_pairs}"
                    )
                n_src, n_dst, n_hop = _compose(src, dst, hop, src, dst, hop)
                m_src, m_dst, m_hop = _dedup_min(
                    np.concatenate([src, n_src]),
                    np.concatenate([dst, n_dst]),
                    np.concatenate([hop, n_hop]),
                )
                keep = m_src != m_dst  # min-hop paths are cycle-free
                m_src, m_dst, m_hop = m_src[keep], m_dst[keep], m_hop[keep]
                if len(m_src) == len(src) and np.array_equal(m_hop, hop):
                    break
                src, dst, hop = m_src, m_dst, m_hop
            self.set_src, self.set_dst, self.set_hop = src, dst, hop
            r_order = np.lexsort((src, dst))
            self.rset_dst = dst[r_order]
            self.rset_src = src[r_order]
            self.rset_hop = hop[r_order]

            # --- element closure: direct members + closure-extended ---
            d_order = np.argsort(d_node, kind="stable")
            x_src, x_e, x_hop = _compose(
                src, dst, hop,
                d_node[d_order], d_subj[d_order],
                np.zeros(len(d_order), np.int32),
            )
            elt_set, elt_e, elt_hop = _dedup_min(
                np.concatenate([d_node, x_src]),
                np.concatenate([d_subj, x_e]),
                np.concatenate([np.zeros(len(d_node), np.int32), x_hop]),
            )
            if len(elt_set) > self.max_pairs:
                self._reset_empty()
                raise ClosureTooLarge(
                    f"element closure exceeds max_pairs={self.max_pairs}"
                )
            self.elt_set, self.elt_e, self.elt_hop = elt_set, elt_e, elt_hop
            self.elt_packed = (
                (elt_set.astype(np.int64) << 32) | elt_e.astype(np.int64)
            )
            re_order = np.lexsort((elt_set, elt_e))
            self.relt_e = elt_e[re_order]
            self.relt_set = elt_set[re_order]

            # --- taint: where closure semantics could exceed the
            # engine's arbitration band ---
            self._rewrite_his = self._rewrite_his_from(manager, vocab)
            node_hi = (self.nodes >> 32).astype(np.int64)
            t0m = np.isin(
                node_hi,
                np.fromiter(self._rewrite_his, np.int64, len(self._rewrite_his)),
            ) if self._rewrite_his else np.zeros(self.n_nodes, bool)
            counts = np.bincount(node_of_row, minlength=self.n_nodes)
            t0m |= counts >= self.max_width
            tainted = t0m.copy()
            if len(src):
                tainted[src[t0m[dst]]] = True
            self.tainted = tainted
        self.build_s = time.perf_counter() - t0
        self.builds += 1

    @staticmethod
    def _rewrite_his_from(manager, vocab) -> Set[int]:
        his: Set[int] = set()
        if manager is None:
            return his
        R = max(len(vocab.relations), 1)
        try:
            namespaces = manager.namespaces()
        except Exception:
            return his
        for ns in namespaces:
            nsc = vocab.namespaces.lookup(ns.name)
            if nsc < 0:
                continue
            for rel in ns.relations or []:
                if rel.subject_set_rewrite is None:
                    continue
                relc = vocab.relations.lookup(rel.name)
                if relc >= 0:
                    his.add(nsc * R + relc)
        return his

    # ----------------------------------------------------------- lookups

    def node_id(self, nsc: int, objc: int, relc: int) -> int:
        """Dense node id for vocab ids, or -1 when the node has no tuples."""
        if nsc < 0 or objc < 0 or relc < 0 or relc >= self.R:
            return -1
        key = np.int64((np.int64(nsc) * self.R + relc) << 32 | objc)
        pos = int(np.searchsorted(self.nodes, key))
        if pos < self.n_nodes and self.nodes[pos] == key:
            return pos
        return -1

    def node_ids_np(
        self, q_ns: np.ndarray, q_obj: np.ndarray, q_rel: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Vectorized node lookup: (node_ids, node_hi) with -1 misses.

        ``node_hi`` is ``ns * R + rel`` whenever both ids are indexable
        (even if the object is unknown) — the rewrite-eligibility test
        for unknown nodes needs it.
        """
        n = len(q_ns)
        hi_ok = (q_ns >= 0) & (q_rel >= 0) & (q_rel < self.R)
        node_hi = np.where(
            hi_ok, q_ns.astype(np.int64) * self.R + q_rel, np.int64(-1)
        )
        nodes = np.full(n, -1, np.int32)
        valid = hi_ok & (q_obj >= 0)
        if self.n_nodes and valid.any():
            keys = (node_hi[valid] << 32) | q_obj[valid].astype(np.int64)
            pos = np.searchsorted(self.nodes, keys)
            pos_c = np.minimum(pos, self.n_nodes - 1)
            hit = self.nodes[pos_c] == keys
            nodes[valid] = np.where(hit, pos_c, -1).astype(np.int32)
        return nodes, node_hi

    def node_range(self, nsc: int, relc: int) -> Tuple[int, int]:
        """Node-id range [lo, hi) for every object under (ns, rel) —
        node keys sort by (hi, obj), so the range is contiguous."""
        if nsc < 0 or relc < 0 or relc >= self.R:
            return 0, 0
        hi_key = np.int64(nsc) * self.R + relc
        lo = int(np.searchsorted(self.nodes, hi_key << 32))
        hi = int(np.searchsorted(self.nodes, (hi_key + 1) << 32))
        return lo, hi

    def _ancestors(self, node: int) -> Dict[int, int]:
        """All sets containing ``node`` (transitively), node itself at 0."""
        anc = {node: 0}
        lo = int(np.searchsorted(self.rset_dst, node, side="left"))
        hi = int(np.searchsorted(self.rset_dst, node, side="right"))
        for a, h in zip(
            self.rset_src[lo:hi].tolist(), self.rset_hop[lo:hi].tolist()
        ):
            anc[a] = min(anc.get(a, h), h)
        for a, h in self._d_set_by_dst.get(node, {}).items():
            anc[a] = min(anc.get(a, h), h)
        return anc

    def _descendants(self, node: int) -> Dict[int, int]:
        desc = {node: 0}
        lo = int(np.searchsorted(self.set_src, node, side="left"))
        hi = int(np.searchsorted(self.set_src, node, side="right"))
        for d, h in zip(
            self.set_dst[lo:hi].tolist(), self.set_hop[lo:hi].tolist()
        ):
            desc[d] = min(desc.get(d, h), h)
        for d, h in self._d_set_by_src.get(node, {}).items():
            desc[d] = min(desc.get(d, h), h)
        return desc

    def _elements_of(self, node: int) -> Dict[int, int]:
        """elt id -> min hops, merging base slice and delta."""
        key_lo = np.int64(node) << 32
        lo = int(np.searchsorted(self.elt_packed, key_lo))
        hi = int(np.searchsorted(self.elt_packed, key_lo + (1 << 32)))
        out = dict(zip(
            self.elt_e[lo:hi].tolist(), self.elt_hop[lo:hi].tolist()
        ))
        for e, h in self._d_elt_by_set.get(node, {}).items():
            out[e] = min(out.get(e, h), h)
        return out

    def _is_tainted(self, node: int) -> bool:
        return bool(self.tainted[node]) or node in self._d_taint

    def answer_checks(
        self,
        nodes: np.ndarray,
        subjects: np.ndarray,
        node_hi: np.ndarray,
        rest_depth: int,
        probed: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Batched membership verdicts: (allowed, answered) bool arrays.

        ``nodes`` is int32 node ids (-1 = node unknown to the index);
        ``node_hi`` is the packed ``ns * R + rel`` per query (for the
        rewrite-eligibility test of unknown nodes); ``probed`` optionally
        carries precomputed whole-batch (hit, hop) arrays from the device
        probe (leopard/device.py) — bit-identical to the host search.  A
        query is answered iff its verdict is provably what the engine
        would produce:

        * unknown node (or unknown ns/obj/rel strings), relation
          rewrite-free -> False (nothing indexable there);
        * known clean node, pair hit at hops h with h + 2 <= rest_depth
          -> True;
        * known clean node, pair miss (base and delta) -> False;
        * everything else (tainted, dirty, hit beyond the depth budget)
          declines and the query continues down the normal path.
        """
        n = len(nodes)
        allowed = np.zeros(n, bool)
        answered = np.zeros(n, bool)
        if n == 0:
            return allowed, answered
        known = nodes >= 0
        # unknown node: no tuples => deny, unless a rewrite could reach
        # members anyway (node_hi = -1 means the namespace or relation
        # string is not even interned, so no rewrite can exist for it)
        if self._rewrite_his:
            rw = np.isin(
                node_hi,
                np.fromiter(
                    self._rewrite_his, np.int64, len(self._rewrite_his)
                ),
            )
        else:
            rw = np.zeros(n, bool)
        answered |= ~known & ~rw

        if known.any() and self.n_nodes:
            kn = np.flatnonzero(known)
            node_k = nodes[kn]
            clean = ~self.tainted[node_k]
            if self._d_taint or self.dirty:
                bad = self._d_taint | self.dirty
                clean &= ~np.isin(node_k, np.fromiter(bad, np.int64, len(bad)))
            if self.dirty:
                # observability: checks that had to decline because a
                # deletion dirtied the set they touch
                darr = np.fromiter(self.dirty, np.int64, len(self.dirty))
                self.fallbacks += int(np.isin(node_k, darr).sum())
            if probed is not None:
                hit = probed[0][kn].copy()
                hop = probed[1][kn]
            else:
                keys = (node_k.astype(np.int64) << 32) | subjects[kn].astype(
                    np.int64
                )
                pos = np.searchsorted(self.elt_packed, keys)
                pos_c = np.minimum(pos, max(len(self.elt_packed) - 1, 0))
                hit = (
                    (self.elt_packed[pos_c] == keys)
                    if len(self.elt_packed)
                    else np.zeros(len(keys), bool)
                )
                hop = np.where(
                    hit,
                    self.elt_hop[pos_c] if len(self.elt_hop) else 0,
                    0,
                )
            ok_depth = hop + DEPTH_SLACK <= rest_depth
            if self._d_elt:
                # delta can add pairs or improve hops on base hits
                for j in np.flatnonzero(clean & ~(hit & ok_depth)).tolist():
                    dh = self._d_elt.get(
                        (int(node_k[j]), int(subjects[kn[j]]))
                    )
                    if dh is not None:
                        hit[j] = True
                        ok_depth[j] = dh + DEPTH_SLACK <= rest_depth
            ans_k = clean & (ok_depth | ~hit)
            answered[kn] = ans_k
            allowed[kn] = ans_k & hit
        return allowed, answered

    def prep_fused_checks(
        self,
        nodes: np.ndarray,
        subjects: np.ndarray,
        node_hi: np.ndarray,
        rest_depth: int,
    ) -> np.ndarray:
        """Host half of ``answer_checks`` for the fused wave cascade:
        int32 probe modes (LM_*), one per row.  Everything that needs
        dict state resolves here; the device finishes LM_PROBE /
        LM_HIT_ONLY rows with the in-program binary search over the
        shipped pairs.  Mode-by-mode equivalence with answer_checks:

        * LM_DENY — unknown node, rewrite-free relation: answer_checks
          denies unconditionally, so the device can too.
        * LM_PROBE — clean node, no delta pair: the device computes the
          exact base formula ``ans = ok_depth | ~hit, allow = ans & hit``.
        * LM_ALLOW — delta pair within the depth budget: answer_checks
          allows whether or not the base probe hits (a base hit within
          budget allows directly; otherwise the delta supplies the hit
          with an in-budget hop), so the verdict is device-independent.
        * LM_HIT_ONLY — delta pair beyond the budget: answer_checks
          answers only when the base probe hits within budget (otherwise
          the delta forces ``hit`` with a too-deep hop and the row
          declines), which is exactly ``ans = allow = hit & ok_depth``.
        * LM_NONE — tainted/dirty node, or unknown node with a reachable
          rewrite: answer_checks declines, the device must not answer.

        The dirty-set decline counter increments here with the same
        coverage as answer_checks (all known rows at probe time).
        """
        n = len(nodes)
        lmode = np.zeros(n, np.int32)
        if n == 0:
            return lmode
        known = nodes >= 0
        if self._rewrite_his:
            rw = np.isin(
                node_hi,
                np.fromiter(
                    self._rewrite_his, np.int64, len(self._rewrite_his)
                ),
            )
        else:
            rw = np.zeros(n, bool)
        lmode[~known & ~rw] = LM_DENY
        if known.any() and self.n_nodes:
            kn = np.flatnonzero(known)
            node_k = nodes[kn]
            clean = ~self.tainted[node_k]
            if self._d_taint or self.dirty:
                bad = self._d_taint | self.dirty
                clean &= ~np.isin(
                    node_k, np.fromiter(bad, np.int64, len(bad))
                )
            if self.dirty:
                darr = np.fromiter(self.dirty, np.int64, len(self.dirty))
                self.fallbacks += int(np.isin(node_k, darr).sum())
            mode_k = np.where(clean, LM_PROBE, LM_NONE).astype(np.int32)
            if self._d_elt:
                for j in np.flatnonzero(clean).tolist():
                    dh = self._d_elt.get(
                        (int(node_k[j]), int(subjects[kn[j]]))
                    )
                    if dh is not None:
                        mode_k[j] = (
                            LM_ALLOW
                            if dh + DEPTH_SLACK <= rest_depth
                            else LM_HIT_ONLY
                        )
            lmode[kn] = mode_k
        return lmode

    # ----------------------------------------------------- incremental

    def apply_changes(self, changes: List[Tuple[int, RelationTuple]]) -> bool:
        """Fold a changelog slice into the delta; False => rebuild me.

        Additions append exact closure pairs; deletions mark the tuple's
        node and all its ancestors dirty (overlay-exactness contract).
        """
        if self.n_nodes == 0 and changes:
            return False
        vocab_budget = self.rebuild_delta_pairs
        for op, t in changes:
            n = self._node_of_tuple(t)
            if n < 0:
                return False
            if op < 0:
                self._mark_dirty(n)
                if len(self.dirty) > self.rebuild_dirty_sets:
                    return False
                continue
            if not self._apply_add(n, t, vocab_budget):
                return False
            if len(self._d_elt) > vocab_budget:
                return False
        return True

    def _node_of_tuple(self, t: RelationTuple) -> int:
        v = self._vocab
        if v is None:
            return -1
        nsc = v.namespaces.lookup(t.namespace)
        objc = v.objects.lookup(t.object)
        relc = v.relations.lookup(t.relation)
        return self.node_id(nsc, objc, relc)

    # the engine folds changes into TupleColumns (interning) before
    # handing them to us, so the vocab is authoritative by then
    _vocab = None

    def bind_vocab(self, vocab) -> None:
        self._vocab = vocab

    def _mark_dirty(self, node: int) -> None:
        for a in self._ancestors(node):
            self.dirty.add(a)

    def _apply_add(self, n: int, t: RelationTuple, budget: int) -> bool:
        v = self._vocab
        sid = v.subjects.lookup(t.subject.unique_id())
        if sid < 0:
            return False
        anc = self._ancestors(n)
        # width taint: the node's fanout may now cross the oracle's
        # truncation threshold — taint it and everything reaching it
        cnt = self._d_node_tuples.get(n, 0) + 1
        self._d_node_tuples[n] = cnt
        base_cnt = self._base_node_count(n)
        if base_cnt + cnt >= self.max_width:
            self._d_taint.update(anc)

        # the tuple's subject is a direct member of n (and transitively
        # of every ancestor)
        if len(anc) > budget:
            return False
        for a, ha in anc.items():
            self._put_elt(a, sid, ha)

        if isinstance(t.subject, SubjectSet):
            m = self.node_id(
                v.namespaces.lookup(t.subject.namespace),
                v.objects.lookup(t.subject.object),
                v.relations.lookup(t.subject.relation),
            )
            if m < 0:
                # edge into a node with no tuples: nothing reachable yet,
                # but a later add there would arrive as an unknown-node
                # change and force a rebuild — nothing to record now
                return True
            if m == n:
                return True  # self-edge: no new reachability
            # NOTE: m in anc (the edge closes a cycle) is NOT a no-op —
            # n then gains m's whole closure.  Every genuinely new pair
            # still factors as anc_old(n) x closure_old(m): a shortest
            # path through the new edge uses it exactly once, so the
            # product below covers cycles with no special casing (the
            # _put_* min-hop guards drop the already-present pairs).
            if self._is_tainted(m):
                self._d_taint.update(anc)
            desc = self._descendants(m)
            elems = self._elements_of(m)
            if len(anc) * (len(desc) + len(elems)) > 4 * budget:
                return False
            for a, ha in anc.items():
                for d, hd in desc.items():
                    self._put_set(a, d, ha + 1 + hd)
                for e, he in elems.items():
                    self._put_elt(a, e, ha + 1 + he)
        return True

    def _base_node_count(self, node: int) -> int:
        key_lo = np.int64(node) << 32
        lo = int(np.searchsorted(self.elt_packed, key_lo))
        hi = int(np.searchsorted(self.elt_packed, key_lo + (1 << 32)))
        # base elements at hop 0 are exactly the node's own tuples
        return int((self.elt_hop[lo:hi] == 0).sum())

    def _put_elt(self, s: int, e: int, h: int) -> None:
        key = (s, e)
        cur = self._d_elt.get(key)
        if cur is not None and cur <= h:
            return
        # never shadow a base pair that already has an equal-or-better hop
        if cur is None and len(self.elt_packed):
            packed = np.int64(s) << 32 | np.int64(e)
            pos = int(np.searchsorted(self.elt_packed, packed))
            if (
                pos < len(self.elt_packed)
                and self.elt_packed[pos] == packed
                and self.elt_hop[pos] <= h
            ):
                return
        self._d_elt[key] = h
        self._d_elt_by_set.setdefault(s, {})[e] = h
        self._d_elt_by_e.setdefault(e, {})[s] = h

    def _put_set(self, a: int, d: int, h: int) -> None:
        if a == d:
            return
        cur = self._d_set_by_src.get(a, {}).get(d)
        if cur is not None and cur <= h:
            return
        self._d_set_by_src.setdefault(a, {})[d] = h
        self._d_set_by_dst.setdefault(d, {})[a] = h

    # ------------------------------------------------------- enumeration

    def list_elements(self, node: int) -> Optional[List[int]]:
        """Element ids reachable from ``node``; None => caller must use
        the host oracle (node dirty).  Unknown nodes are exactly empty."""
        if node < 0:
            return []
        if node in self.dirty:
            self.fallbacks += 1
            return None
        return sorted(self._elements_of(node).keys())

    def list_sets_of(
        self, elt: int, lo_node: int, hi_node: int
    ) -> Optional[List[int]]:
        """Node ids in [lo_node, hi_node) whose closure contains ``elt``;
        None => a candidate is dirty and the host oracle must decide.

        Deletions only shrink reachability, so nodes *outside* the
        candidate set stay correct even while others are dirty — only a
        dirty candidate forces the oracle.
        """
        if elt < 0:
            return []
        lo = int(np.searchsorted(self.relt_e, elt, side="left"))
        hi = int(np.searchsorted(self.relt_e, elt, side="right"))
        cand = set(self.relt_set[lo:hi].tolist())
        cand.update(self._d_elt_by_e.get(elt, {}).keys())
        cand = {c for c in cand if lo_node <= c < hi_node}
        if self.dirty and cand & self.dirty:
            self.fallbacks += 1
            return None
        return sorted(cand)

    def node_obj(self, node: int) -> int:
        """Object vocab id of a dense node id."""
        return int(self.nodes[node] & 0xFFFFFFFF)
