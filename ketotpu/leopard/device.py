"""HBM residency for the Leopard pairs + the device binary-search probe.

The packed ``(set_id << 32 | element_id)`` int64 array ships to the
accelerator next to the snapshot CSR (`engine/tpu.py` installs it right
after the base device arrays), and batched membership verdicts are a
single ``jnp.searchsorted`` over the sorted pairs — one binary search
per query instead of an iterative frontier walk.

Compile-variant discipline matches the rest of the engine: query blocks
are padded to power-of-two buckets (`tpu._bucket`) AND the shipped pair
arrays are padded to power-of-two buckets with a +inf key sentinel, so
the jit sees one variant per (pairs_bucket, query_bucket) pair — a
closure rebuild whose pair count lands in the same bucket reuses the
compiled probe.  (JIT-audit finding: before the pad, `pairs.shape[0]`
was a raw compile axis and every incremental rebuild recompiled the
probe ON THE SERVING PATH — the `leopard_probe` AFTER-WARM warning
class.)  Device probing is worth the dispatch overhead for large
batches; small batches stay on the host numpy path (`closure.py`), which
returns bit-identical verdicts.  Any device failure degrades to the host
path (never to a wrong answer).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ketotpu import compilewatch

try:  # pragma: no cover - exercised wherever jax is present
    import jax
    import jax.numpy as jnp

    _HAS_JAX = True
except Exception:  # pragma: no cover
    jax = None
    jnp = None
    _HAS_JAX = False

# below this many probes the host searchsorted wins against a device
# round-trip (dominated by dispatch latency, not the log2(pairs) search)
DEVICE_PROBE_MIN = 2048


#: pairs-array pad sentinel: sorts after every real packed key (set and
#: element ids are non-negative int32, so real keys are < 2**62) and can
#: never equal one, keeping the searchsorted hit test exact on padding
_PAIR_PAD = np.iinfo(np.int64).max


def _pair_bucket(n: int, floor: int = 1024) -> int:
    """Power-of-two pad size for the shipped pairs: the probe's compile
    signature then changes only when the closure doubles, not on every
    incremental rebuild."""
    b = floor
    while b < n:
        b <<= 1
    return b


def ship_pairs(index) -> Optional[dict]:
    """Device-put the closure pair arrays (padded to a power-of-two
    bucket); None when jax is unavailable or the index is empty."""
    if not _HAS_JAX or index is None or len(index.elt_packed) == 0:
        return None
    try:
        n = len(index.elt_packed)
        cap = _pair_bucket(n)
        pairs = np.full(cap, _PAIR_PAD, np.int64)
        pairs[:n] = index.elt_packed
        hops = np.zeros(cap, index.elt_hop.dtype)
        hops[:n] = index.elt_hop
        return {
            "pairs": jax.device_put(pairs),
            "hops": jax.device_put(hops),
        }
    except Exception:
        return None


if _HAS_JAX:

    @jax.jit
    def _probe(pairs, hops, keys):
        idx = jnp.searchsorted(pairs, keys)
        idx = jnp.clip(idx, 0, pairs.shape[0] - 1)
        hit = pairs[idx] == keys
        return hit, jnp.where(hit, hops[idx], 0)


def probe_pairs(
    dev: Optional[dict], keys: np.ndarray, pad_to: int
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Batched (hit, hop) via the device pairs; None => use host path."""
    if dev is None or not _HAS_JAX or len(keys) < DEVICE_PROBE_MIN:
        return None
    try:
        padded = np.full(pad_to, -1, np.int64)
        padded[: len(keys)] = keys
        with compilewatch.scope(
            "leopard_probe",
            lambda: f"pairs={dev['pairs'].shape[0]} pad={pad_to}",
        ):
            hit, hop = _probe(dev["pairs"], dev["hops"], padded)
        hit = np.asarray(hit)[: len(keys)]
        hop = np.asarray(hop)[: len(keys)]
        return hit, hop
    except Exception:
        return None
