"""HBM residency for the Leopard pairs + the device binary-search probe.

The packed ``(set_id << 32 | element_id)`` int64 array ships to the
accelerator next to the snapshot CSR (`engine/tpu.py` installs it right
after the base device arrays), and batched membership verdicts are a
single ``jnp.searchsorted`` over the sorted pairs — one binary search
per query instead of an iterative frontier walk.

Compile-variant discipline matches the rest of the engine: query blocks
are padded to power-of-two buckets (`tpu._bucket`) AND the shipped pair
arrays are padded to power-of-two buckets with a +inf key sentinel, so
the jit sees one variant per (pairs_bucket, query_bucket) pair — a
closure rebuild whose pair count lands in the same bucket reuses the
compiled probe.  (JIT-audit finding: before the pad, `pairs.shape[0]`
was a raw compile axis and every incremental rebuild recompiled the
probe ON THE SERVING PATH — the `leopard_probe` AFTER-WARM warning
class.)  Device probing is worth the dispatch overhead for large
batches; small batches stay on the host numpy path (`closure.py`), which
returns bit-identical verdicts.  Any device failure degrades to the host
path (never to a wrong answer).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ketotpu import compilewatch

try:  # pragma: no cover - exercised wherever jax is present
    import jax
    import jax.numpy as jnp

    _HAS_JAX = True
except Exception:  # pragma: no cover
    jax = None
    jnp = None
    _HAS_JAX = False

# below this many probes the host searchsorted wins against a device
# round-trip (dominated by dispatch latency, not the log2(pairs) search)
DEVICE_PROBE_MIN = 2048


#: pairs-column pad sentinel: sorts after every real id (set and element
#: ids are non-negative int32 well below the ceiling) and can never equal
#: one, keeping the binary-search hit test exact on padding.  The pairs
#: ship as TWO sorted int32 columns (set, element) rather than the host's
#: packed int64 keys: with jax's default x64-disabled config a device_put
#: int64 array silently truncates to int32, which both destroys the pad
#: sentinel (int64 max -> -1, sorted FIRST) and overflows the
#: ``set << 32 | element`` packing itself.
_PAIR_PAD = np.iinfo(np.int32).max


def _pair_bucket(n: int, floor: int = 1024) -> int:
    """Power-of-two pad size for the shipped pairs: the probe's compile
    signature then changes only when the closure doubles, not on every
    incremental rebuild."""
    b = floor
    while b < n:
        b <<= 1
    return b


def ship_pairs(index) -> Optional[dict]:
    """Device-put the closure pair columns (padded to a power-of-two
    bucket); None when jax is unavailable or the index is empty.  The
    host's sorted packed int64 keys split into two int32 columns with the
    same lexicographic order (the packing IS the lexicographic order of
    its halves), so a two-column binary search visits the same positions
    the host searchsorted does."""
    if not _HAS_JAX or index is None or len(index.elt_packed) == 0:
        return None
    try:
        n = len(index.elt_packed)
        cap = _pair_bucket(n)
        sets = np.full(cap, _PAIR_PAD, np.int32)
        elts = np.full(cap, _PAIR_PAD, np.int32)
        sets[:n] = (index.elt_packed >> 32).astype(np.int32)
        elts[:n] = (index.elt_packed & 0x7FFFFFFF).astype(np.int32)
        hops = np.zeros(cap, np.int32)
        hops[:n] = index.elt_hop
        return {
            "sets": jax.device_put(sets),
            "elts": jax.device_put(elts),
            "hops": jax.device_put(hops),
        }
    except Exception:
        return None


if _HAS_JAX:

    def probe_in_program(sets, elts, hops, q_set, q_elt):
        """Traced (non-jitted) probe body: one lexicographic binary
        search per query over the two sorted int32 pair columns
        (equivalent to the host's searchsorted over the packed int64
        keys, which jax's default x64-disabled config cannot represent
        on device).  The fused wave cascade (engine/fused.py) inlines
        this as its tier-0 phase — the probe then compiles INTO the wave
        program instead of costing its own dispatch — and the standalone
        ``_probe`` below jits the same body for the unfused path, so
        both paths share one definition and stay bit-identical.  A query
        set id of -1 (ineligible row) can never match: real ids are
        non-negative and padding is ``_PAIR_PAD``.  The unrolled step
        count is derived from the (static) padded capacity, so the
        compiled search is exact for any occupancy."""
        cap = sets.shape[0]
        steps = max(int(cap).bit_length(), 1)
        lo = jnp.zeros(q_set.shape, jnp.int32)
        hi = jnp.full(q_set.shape, cap, jnp.int32)
        for _ in range(steps):
            mid = (lo + hi) >> 1
            ms, me = sets[mid], elts[mid]
            less = (ms < q_set) | ((ms == q_set) & (me < q_elt))
            lo = jnp.where(less, mid + 1, lo)
            hi = jnp.where(less, hi, mid)
        idx = jnp.clip(lo, 0, cap - 1)
        hit = (sets[idx] == q_set) & (elts[idx] == q_elt)
        return hit, jnp.where(hit, hops[idx], 0)

    _probe = jax.jit(probe_in_program)


def probe_pairs(
    dev: Optional[dict], keys: np.ndarray, pad_to: int
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Batched (hit, hop) via the device pairs; None => use host path.
    ``keys`` is the host's packed int64 array (-1 = must-miss row); the
    halves split into int32 columns for the device search."""
    if dev is None or not _HAS_JAX or len(keys) < DEVICE_PROBE_MIN:
        return None
    try:
        q_set = np.full(pad_to, -1, np.int32)
        q_elt = np.full(pad_to, -1, np.int32)
        q_set[: len(keys)] = (keys >> 32).astype(np.int32)
        q_elt[: len(keys)] = (keys & 0x7FFFFFFF).astype(np.int32)
        # a -1 key's high half is -1 (arithmetic shift), keeping the
        # must-miss contract: no real set id is negative
        q_elt[: len(keys)][keys < 0] = -1
        with compilewatch.scope(
            "leopard_probe",
            lambda: f"pairs={dev['sets'].shape[0]} pad={pad_to}",
        ):
            hit, hop = _probe(
                dev["sets"], dev["elts"], dev["hops"], q_set, q_elt
            )
        hit = np.asarray(hit)[: len(keys)]
        hop = np.asarray(hop)[: len(keys)]
        return hit, hop
    except Exception:
        return None
