"""Host-oracle enumeration for the listing APIs + shared pagination.

The closure index answers ``ListObjects`` / ``ListSubjects`` from sorted
pairs; this module is the other half of the overlay-exactness contract —
the enumeration that reads the **live store** and is therefore always
correct, used as

* the fallback when the index declines (dirty set ids after deletions,
  index disabled/stale, oracle-only engine kind), and
* the parity reference the property tests compare the index against.

Semantics are the closure's: a subject reaches an object iff there is a
chain of set-containment hops (tuple subjects that are SubjectSets) from
the object's ``(namespace, object, relation)`` node to a tuple carrying
that subject.  Cycles are handled with a visited set; results are
deterministic (lexicographic) so pagination is stable and identical
between the index path and this one.

Pagination is Keto-style: an opaque ``page_token`` ("" = first page)
that encodes the position after the last returned item; clients treat it
as a black box and pass it back verbatim.
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Sequence, Tuple

from ketotpu.api.types import (
    RelationQuery,
    Subject,
    SubjectID,
    SubjectSet,
    subject_from_string,
)

# mirrors storage/memory.py's DEFAULT_PAGE_SIZE (x_keto_read_max_page parity)
DEFAULT_PAGE_SIZE = 100
_SCAN_PAGE = 1000
# generous cycle/blowup guard for the BFS (the index path never walks)
_MAX_VISITED = 1_000_000


def paginate(
    keys: Sequence[str], page_token: str, page_size: int
) -> Tuple[List[str], str]:
    """Slice a lexicographically sorted key list Keto-style.

    The token is the last key of the previous page; the next page starts
    strictly after it, so the scheme stays stable under concurrent
    inserts (an unknown token is simply a lower bound, never an error).
    """
    if page_size <= 0:
        page_size = DEFAULT_PAGE_SIZE
    start = bisect.bisect_right(keys, page_token) if page_token else 0
    page = list(keys[start: start + page_size])
    next_token = page[-1] if start + page_size < len(keys) else ""
    return page, next_token


def host_list_subjects(
    store, namespace: str, object: str, relation: str
) -> Dict[str, Subject]:
    """All subjects reaching ``namespace:object#relation``, keyed by
    ``unique_id()`` — forward BFS over the live store's containment
    edges, collecting every tuple subject along the way."""
    out: Dict[str, Subject] = {}
    seen = {(namespace, object, relation)}
    stack = [(namespace, object, relation)]
    while stack:
        ns, obj, rel = stack.pop()
        token = ""
        while True:
            tuples, token = store.get_relation_tuples(
                RelationQuery(namespace=ns, object=obj, relation=rel),
                page_token=token,
                page_size=_SCAN_PAGE,
            )
            for t in tuples:
                out[t.subject.unique_id()] = t.subject
                if isinstance(t.subject, SubjectSet):
                    key = (
                        t.subject.namespace,
                        t.subject.object,
                        t.subject.relation,
                    )
                    if key not in seen and len(seen) < _MAX_VISITED:
                        seen.add(key)
                        stack.append(key)
            if not token:
                break
    return out


def host_list_objects(
    store, namespace: str, relation: str, subject: Subject
) -> List[str]:
    """All objects o with ``namespace:o#relation`` reaching ``subject`` —
    reverse BFS from the subject through the store's by-subject index
    (containment chains traverse nodes of *any* relation)."""
    out = set()
    seen = set()
    frontier: List[Subject] = [subject]
    while frontier:
        s = frontier.pop()
        uid = s.unique_id()
        if uid in seen or len(seen) >= _MAX_VISITED:
            continue
        seen.add(uid)
        token = ""
        while True:
            tuples, token = store.get_relation_tuples(
                RelationQuery().with_subject(s),
                page_token=token,
                page_size=_SCAN_PAGE,
            )
            for t in tuples:
                if t.namespace == namespace and t.relation == relation:
                    out.add(t.object)
                frontier.append(
                    SubjectSet(t.namespace, t.object, t.relation)
                )
            if not token:
                break
    return sorted(out)


class HostListEngine:
    """Listing engine over the live store only (oracle engine kind, and
    the degraded mode of the device engine).  Duck-type-compatible with
    ``DeviceCheckEngine.list_objects`` / ``list_subjects`` and
    ``server.workers.RemoteListEngine``."""

    def __init__(self, store):
        self.store = store

    def list_objects(
        self,
        namespace: str,
        relation: str,
        subject: Subject,
        *,
        page_size: int = 0,
        page_token: str = "",
    ) -> Tuple[List[str], str]:
        objs = host_list_objects(self.store, namespace, relation, subject)
        return paginate(objs, page_token, page_size)

    def list_subjects(
        self,
        namespace: str,
        object: str,
        relation: str,
        *,
        page_size: int = 0,
        page_token: str = "",
    ) -> Tuple[List[Subject], str]:
        by_uid = host_list_subjects(self.store, namespace, object, relation)
        keys, next_token = paginate(
            sorted(by_uid.keys()), page_token, page_size
        )
        return [by_uid[k] for k in keys], next_token


def subject_from_uid(uid: str) -> Optional[Subject]:
    """Decode a vocab ``unique_id()`` string back into a Subject."""
    if uid.startswith("id:"):
        return SubjectID(id=uid[3:])
    if uid.startswith("set:"):
        return subject_from_string(uid[4:])
    return None
