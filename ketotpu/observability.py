"""Observability: span events, metrics, structured logging.

The reference wires OpenTelemetry + Prometheus + logrus through every layer
(SURVEY §5.1/§5.5).  This module is the dependency-free equivalent:

* **Events** — the semconv span-event vocabulary of `x/events/events.go:14-20`
  (``PermissionsChecked``, ``PermissionsExpanded``, ``RelationtuplesCreated/
  Deleted/Changed``), emitted through ``Tracer.event`` at the same call sites
  (check engine, expand engine, transact handler).
* **Metrics** — a threadsafe counter/histogram registry with Prometheus text
  exposition, served at ``/metrics/prometheus`` on every router and on the
  dedicated metrics port (`registry_default.go:170-182`, `daemon.go:551-566`).
  The device engine records per-batch gauges the SURVEY asks for (batches,
  fallbacks, retries, snapshot rebuilds).
* **Tracer** — span context manager: wall-time histograms per span name plus
  an event sink; ``ketoctx.WithTracerWrapper`` parity = constructor injection
  of a custom Tracer into the Registry.
* **Logger** — stdlib logging with a structured key=value formatter (logrusx
  analog), per-request request logs in the REST router.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterable, List, Optional, Tuple

# -- span events (x/events/events.go:14-20) ---------------------------------

PERMISSIONS_CHECKED = "PermissionsChecked"
PERMISSIONS_EXPANDED = "PermissionsExpanded"
RELATIONTUPLES_CREATED = "RelationtuplesCreated"
RELATIONTUPLES_DELETED = "RelationtuplesDeleted"
RELATIONTUPLES_CHANGED = "RelationtuplesChanged"

_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
            1.0, 2.5, 5.0, 10.0)

#: public histogram bucket bounds (seconds) — the SLO engine snaps its
#: latency target onto one of these so "fraction under target" is exact
BUCKETS = _BUCKETS


# -- W3C trace context (traceparent) -----------------------------------------

def parse_traceparent(value: Optional[str]) -> Optional[Tuple[str, str]]:
    """``00-<32hex traceid>-<16hex spanid>-<flags>`` -> (trace_id, span_id).

    Returns None for anything malformed — a bad header must never fail the
    request, it just starts a fresh trace.
    """
    if not value:
        return None
    parts = value.strip().lower().split("-")
    if len(parts) < 4:
        return None
    _, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


class Metrics:
    """Prometheus-style registry: counters + histograms, text exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        self._hists: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], List] = {}
        self._gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        self._help: Dict[str, str] = {}

    def counter(self, name: str, value: float = 1.0, help: str = "", **labels):
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            self._counters[key] = self._counters.get(key, 0.0) + value

    def observe(self, name: str, value: float, help: str = "", **labels):
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = [[0] * (len(_BUCKETS) + 1), 0.0, 0]
            buckets, _, _ = h
            for i, ub in enumerate(_BUCKETS):
                if value <= ub:
                    buckets[i] += 1
                    break
            else:
                buckets[-1] += 1
            h[1] += value
            h[2] += 1

    def gauge(self, name: str, value: float, help: str = "", **labels):
        """Set (not accumulate) the latest value — device-engine state like
        rebuild counts is owned by the engine and sampled at scrape time."""
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            if help:
                self._help.setdefault(name, help)
            self._gauges[key] = value

    def get_counter(self, name: str, **labels) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._counters.get(key, 0.0)

    def counter_total(self, name: str, **match) -> float:
        """Sum over every series of ``name`` whose labels include ``match``
        — unlike :meth:`get_counter` this does not require knowing the
        full label set, so readers survive a series gaining a label."""
        want = set(match.items())
        with self._lock:
            return sum(
                v for (n, labels), v in self._counters.items()
                if n == name and want.issubset(labels)
            )

    def get_gauge(self, name: str, **labels) -> float:
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            return self._gauges.get(key, 0.0)

    def histogram_values(self, name: str) -> Dict[Tuple[Tuple[str, str], ...], Tuple[float, int]]:
        """{label-tuple: (sum, count)} for every series of ``name`` — the
        scrape surface bench.py uses to publish stage/phase breakdowns."""
        with self._lock:
            return {
                labels: (h[1], h[2])
                for (n, labels), h in self._hists.items()
                if n == name
            }

    def histogram_buckets(
        self, name: str
    ) -> Dict[Tuple[Tuple[str, str], ...], Tuple[List[int], float, int]]:
        """{label-tuple: (per-bucket counts incl. +Inf, sum, count)} for
        every series of ``name``.  Bucket bounds are :data:`BUCKETS`; the
        SLO engine reads cumulative-under-target counts off this."""
        with self._lock:
            return {
                labels: (list(h[0]), h[1], h[2])
                for (n, labels), h in self._hists.items()
                if n == name
            }

    @staticmethod
    def _escape_label(value: str) -> str:
        # text format 0.0.4: label values escape backslash, quote, newline
        return (
            str(value)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )

    @classmethod
    def _fmt_labels(cls, labels: Iterable[Tuple[str, str]], extra: str = "") -> str:
        parts = [f'{k}="{cls._escape_label(v)}"' for k, v in labels]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def exposition(self) -> str:
        """Prometheus text format 0.0.4."""
        lines: List[str] = []
        with self._lock:
            names = sorted(
                {n for n, _ in self._counters}
                | {n for n, _ in self._hists}
                | {n for n, _ in self._gauges}
            )
            for name in names:
                if name in self._help:
                    lines.append(f"# HELP {name} {self._help[name]}")
                ctr_items = [(k, v) for k, v in self._counters.items() if k[0] == name]
                if ctr_items:
                    lines.append(f"# TYPE {name} counter")
                    for (n, labels), v in sorted(ctr_items):
                        fv = int(v) if float(v).is_integer() else v
                        lines.append(f"{name}{self._fmt_labels(labels)} {fv}")
                gauge_items = [
                    (k, v) for k, v in self._gauges.items() if k[0] == name
                ]
                if gauge_items:
                    lines.append(f"# TYPE {name} gauge")
                    for (n, labels), v in sorted(gauge_items):
                        fv = int(v) if float(v).is_integer() else v
                        lines.append(f"{name}{self._fmt_labels(labels)} {fv}")
                hist_items = [(k, v) for k, v in self._hists.items() if k[0] == name]
                if hist_items:
                    lines.append(f"# TYPE {name} histogram")
                    for (n, labels), (buckets, total, count) in sorted(hist_items):
                        acc = 0
                        for i, ub in enumerate(_BUCKETS):
                            acc += buckets[i]
                            le = self._fmt_labels(labels, f'le="{ub}"')
                            lines.append(f"{name}_bucket{le} {acc}")
                        acc += buckets[-1]
                        le = self._fmt_labels(labels, 'le="+Inf"')
                        lines.append(f"{name}_bucket{le} {acc}")
                        lab = self._fmt_labels(labels)
                        lines.append(f"{name}_sum{lab} {total}")
                        lines.append(f"{name}_count{lab} {count}")
        return "\n".join(lines) + "\n"


class Tracer:
    """Span timings + events; inject a subclass for custom exporters
    (the ketoctx.WithTracerWrapper seam, `ketoctx/options.go:42-45`)."""

    def __init__(self, metrics: Optional[Metrics] = None,
                 logger: Optional[logging.Logger] = None):
        self.metrics = metrics
        self.logger = logger

    @contextmanager
    def span(self, name: str, _parent: Optional[str] = None, **attrs):
        """``_parent`` is an incoming W3C ``traceparent`` header; the base
        tracer has no trace ids so it only times — exporters adopt it."""
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            dt = time.perf_counter() - t0
            if self.metrics is not None:
                self.metrics.observe(
                    "keto_span_duration_seconds", dt,
                    help="span wall time", span=name,
                )

    def current_traceparent(self) -> Optional[str]:
        """traceparent for the innermost open span on this thread (None when
        the tracer keeps no ids) — injected into the worker wire protocol so
        OTLP traces stitch across the process boundary."""
        return None

    def event(self, name: str, **attrs):
        """Span-event emission (x/events/events.go AddEvent sites)."""
        if self.metrics is not None:
            self.metrics.counter(
                "keto_events_total", 1, help="span events emitted", event=name
            )
        if self.logger is not None and self.logger.isEnabledFor(logging.DEBUG):
            kv = " ".join(f"{k}={v}" for k, v in attrs.items())
            self.logger.debug("event %s %s", name, kv)


class _KVFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        base = super().format(record)
        fields = getattr(record, "fields", None)
        if fields:
            kv = " ".join(f"{k}={v}" for k, v in sorted(fields.items()))
            return f"{base} {kv}"
        return base


def make_logger(name: str = "ketotpu", level: str = "info") -> logging.Logger:
    """Structured logger (logrusx analog): level from config, kv fields via
    ``logger.info(..., extra={"fields": {...}})``."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(
            _KVFormatter("%(asctime)s %(levelname)s %(name)s %(message)s")
        )
        logger.addHandler(h)
        logger.propagate = False
    logger.setLevel(getattr(logging, level.upper(), logging.INFO))
    return logger
