"""Ory Permission Language (OPL): lexer, parser, type checker.

OPL is a TypeScript subset: ``class X implements Namespace { related / permits }``.
Parsing produces namespace definitions with userset-rewrite ASTs
(union/intersection/exclusion, computed-userset, tuple-to-userset) with the
same semantics as the reference implementation (`internal/schema/`).
"""

from ketotpu.opl.ast import (
    Child,
    ComputedSubjectSet,
    InvertResult,
    Namespace,
    Operator,
    Relation,
    RelationType,
    SubjectSetRewrite,
    TupleToSubjectSet,
    as_rewrite,
)
from ketotpu.opl.parser import ParseError, parse

__all__ = [
    "Child",
    "ComputedSubjectSet",
    "InvertResult",
    "Namespace",
    "Operator",
    "ParseError",
    "Relation",
    "RelationType",
    "SubjectSetRewrite",
    "TupleToSubjectSet",
    "as_rewrite",
    "parse",
]
