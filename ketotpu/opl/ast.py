"""Namespace configuration AST.

Parity with the reference AST (`internal/namespace/ast/ast_definitions.go:8-71`
and `internal/namespace/definitions.go:15-34`): a namespace has relations, a
relation has subject types and an optional subject-set rewrite tree of n-ary
or/and nodes over computed-subject-set, tuple-to-subject-set, and
invert-result leaves.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional, Union


class Operator(enum.IntEnum):
    OR = 0
    AND = 1

    def __str__(self) -> str:
        return "or" if self is Operator.OR else "and"


@dataclass(frozen=True)
class RelationType:
    """A permitted subject type: a namespace, or a subject set ns#relation."""

    namespace: str
    relation: str = ""  # optional; non-empty means SubjectSet<namespace, relation>


@dataclass(frozen=True)
class ComputedSubjectSet:
    """Rewrite to the same object's ``relation`` userset."""

    relation: str


@dataclass(frozen=True)
class TupleToSubjectSet:
    """Indirect via tuples: for each subject set S of obj#relation, check
    S.object#computed_subject_set_relation."""

    relation: str
    computed_subject_set_relation: str


@dataclass
class InvertResult:
    """Inverts the check result of the child (the ``!`` operator)."""

    child: "Child"


@dataclass
class SubjectSetRewrite:
    """N-ary or/and over rewrite children."""

    operation: Operator = Operator.OR
    children: List["Child"] = field(default_factory=list)


Child = Union[SubjectSetRewrite, ComputedSubjectSet, TupleToSubjectSet, InvertResult]


def as_rewrite(child: Child) -> SubjectSetRewrite:
    """Wrap a child in a top-level rewrite (relations always hold a rewrite
    root, even for a single parsed child) — ast_definitions.go:62-71."""
    if isinstance(child, SubjectSetRewrite):
        return child
    return SubjectSetRewrite(operation=Operator.OR, children=[child])


@dataclass
class Relation:
    name: str
    types: List[RelationType] = field(default_factory=list)
    subject_set_rewrite: Optional[SubjectSetRewrite] = None


@dataclass
class Namespace:
    name: str
    relations: List[Relation] = field(default_factory=list)

    def relation(self, name: str) -> Optional[Relation]:
        for r in self.relations:
            if r.name == name:
                return r
        return None


# -- JSON (debug / config surface) -----------------------------------------


def child_to_json(c: Child) -> dict:
    if isinstance(c, SubjectSetRewrite):
        return {
            "operator": str(c.operation),
            "children": [child_to_json(ch) for ch in c.children],
        }
    if isinstance(c, ComputedSubjectSet):
        return {"relation": c.relation}
    if isinstance(c, TupleToSubjectSet):
        return {
            "relation": c.relation,
            "computed_subject_set_relation": c.computed_subject_set_relation,
        }
    if isinstance(c, InvertResult):
        return {"inverted": child_to_json(c.child)}
    raise TypeError(f"unknown rewrite child {type(c)!r}")


def relation_to_json(r: Relation) -> dict:
    d: dict = {"name": r.name}
    if r.types:
        d["types"] = [
            {"namespace": t.namespace, **({"relation": t.relation} if t.relation else {})}
            for t in r.types
        ]
    if r.subject_set_rewrite is not None:
        d["rewrite"] = child_to_json(r.subject_set_rewrite)
    return d
