"""OPL tokenizer.

A straightforward scanner producing the same token taxonomy as the reference
lexer (`internal/schema/lexer.go:40-89`): identifiers, string literals,
comments, keywords (class/implements/this/ctx), multi-rune operators
(``=>``, ``||``, ``&&``) before single-rune ones, and an error token carrying
the message on invalid input.  Implemented as a generator instead of the
reference's goroutine/channel state machine — same stream, idiomatic Python.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Iterator


class ItemType(enum.Enum):
    ERROR = "error"
    EOF = "eof"
    IDENTIFIER = "identifier"
    COMMENT = "comment"
    STRING_LITERAL = "string literal"
    # keywords
    KEYWORD_CLASS = "class"
    KEYWORD_IMPLEMENTS = "implements"
    KEYWORD_THIS = "this"
    KEYWORD_CTX = "ctx"
    # operators
    OPERATOR_AND = "&&"
    OPERATOR_OR = "||"
    OPERATOR_NOT = "!"
    OPERATOR_ASSIGN = "="
    OPERATOR_ARROW = "=>"
    OPERATOR_DOT = "."
    OPERATOR_COLON = ":"
    OPERATOR_COMMA = ","
    # misc
    SEMICOLON = ";"
    TYPE_UNION = "|"
    # brackets
    PAREN_LEFT = "("
    PAREN_RIGHT = ")"
    BRACE_LEFT = "{"
    BRACE_RIGHT = "}"
    BRACKET_LEFT = "["
    BRACKET_RIGHT = "]"
    ANGLED_LEFT = "<"
    ANGLED_RIGHT = ">"


@dataclass(frozen=True)
class Item:
    typ: ItemType
    val: str
    start: int
    end: int

    def __str__(self) -> str:
        if self.typ is ItemType.ERROR:
            return "error: " + self.val
        if self.typ is ItemType.EOF:
            return "EOF"
        if self.typ in (ItemType.IDENTIFIER, ItemType.STRING_LITERAL):
            v = self.val if len(self.val) <= 10 else self.val[:10] + "..."
            return f"'{v}'"
        return self.val


_SPACES = "\t\n\v\f\r "
_DIGITS = "0123456789"
_LETTERS = "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ_"

_MULTI_RUNE = [("=>", ItemType.OPERATOR_ARROW),
               ("||", ItemType.OPERATOR_OR),
               ("&&", ItemType.OPERATOR_AND)]

_ONE_RUNE = {
    ":": ItemType.OPERATOR_COLON,
    ".": ItemType.OPERATOR_DOT,
    "(": ItemType.PAREN_LEFT,
    ")": ItemType.PAREN_RIGHT,
    "[": ItemType.BRACKET_LEFT,
    "]": ItemType.BRACKET_RIGHT,
    "{": ItemType.BRACE_LEFT,
    "}": ItemType.BRACE_RIGHT,
    "<": ItemType.ANGLED_LEFT,
    ">": ItemType.ANGLED_RIGHT,
    "=": ItemType.OPERATOR_ASSIGN,
    ",": ItemType.OPERATOR_COMMA,
    ";": ItemType.SEMICOLON,
    "|": ItemType.TYPE_UNION,
    "!": ItemType.OPERATOR_NOT,
}

_KEYWORDS = {
    "class": ItemType.KEYWORD_CLASS,
    "implements": ItemType.KEYWORD_IMPLEMENTS,
    "this": ItemType.KEYWORD_THIS,
    "ctx": ItemType.KEYWORD_CTX,
}


def tokenize(source: str) -> Iterator[Item]:
    """Yield tokens; terminates with exactly one EOF or ERROR item."""
    pos = 0
    n = len(source)
    while True:
        while pos < n and source[pos] in _SPACES:
            pos += 1
        if pos >= n:
            yield Item(ItemType.EOF, "", pos, pos)
            return
        start = pos

        matched = False
        for tok, typ in _MULTI_RUNE:
            if source.startswith(tok, pos):
                pos += len(tok)
                yield Item(typ, tok, start, pos)
                matched = True
                break
        if matched:
            continue

        if source.startswith("//", pos):
            end = source.find("\n", pos)
            end = n if end == -1 else end
            yield Item(ItemType.COMMENT, source[pos:end], start, end)
            pos = end
            continue
        if source.startswith("/*", pos):
            end = source.find("*/", pos + 2)
            if end == -1:
                yield Item(ItemType.ERROR, "unclosed comment", start, n)
                return
            yield Item(ItemType.COMMENT, source[pos:end + 2], start, end + 2)
            pos = end + 2
            continue

        c = source[pos]
        if c in _ONE_RUNE:
            pos += 1
            yield Item(_ONE_RUNE[c], c, start, pos)
            continue

        if c in "'\"":
            end = source.find(c, pos + 1)
            if end == -1:
                yield Item(ItemType.ERROR, "unclosed string literal", start, n)
                return
            yield Item(ItemType.STRING_LITERAL, source[pos + 1:end], pos + 1, end)
            pos = end + 1
            continue

        if c in _LETTERS:
            pos += 1
            while pos < n and source[pos] in _LETTERS + _DIGITS:
                pos += 1
            word = source[start:pos]
            yield Item(_KEYWORDS.get(word, ItemType.IDENTIFIER), word, start, pos)
            continue

        yield Item(ItemType.ERROR, f"unexpected token {c}", start, pos + 1)
        return


def tokenize_non_comment(source: str) -> Iterator[Item]:
    for item in tokenize(source):
        if item.typ is not ItemType.COMMENT:
            yield item
