"""OPL recursive-descent parser and post-parse type checker.

Grammar and behavioral parity with the reference parser
(`internal/schema/parser.go:27-537`, `typechecks.go:44-130`,
`limits.go:6-14`):

* ``class Name implements Namespace { related: {...}  permits = {...} }``
* ``related`` entries declare subject types: ``rel: Ns[]``,
  ``rel: (A | B)[]``, ``rel: SubjectSet<Ns, "relation">[]``, ``rel: Array<A | B>``
* ``permits`` entries compile boolean expressions over
  ``this.related.X.includes(ctx.subject)`` (computed subject set),
  ``this.related.X.traverse((s) => s.permits.Y(ctx))`` /
  ``...traverse((s) => s.related.Y.includes(ctx.subject))`` (tuple to subject
  set), ``this.permits.Y(ctx)`` (computed subject set), combined with
  ``&&``/``||``/``!`` and parentheses, into an n-ary rewrite AST.
* Expression nesting is capped at 10 (`limits.go:13`); binary chains are
  simplified to n-ary nodes (`parser.go:519-537`).
* Type checks run only when parsing produced no errors: referenced namespaces
  and relations must exist; tuple-to-subject-set targets are checked
  recursively through subject-set types to depth 10 (`limits.go:8`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from ketotpu.opl.ast import (
    Child,
    ComputedSubjectSet,
    InvertResult,
    Namespace,
    Operator,
    Relation,
    RelationType,
    SubjectSetRewrite,
    TupleToSubjectSet,
    as_rewrite,
)
from ketotpu.opl.lexer import Item, ItemType, tokenize_non_comment

# Maximum number of nested '(' and '!' in a single 'permits' expression.
EXPRESSION_NESTING_MAX_DEPTH = 10

# Maximum recursion when type-checking SubjectSet<Ns, "rel"> chains.
TUPLE_TO_SUBJECT_SET_TYPECHECK_MAX_DEPTH = 10


@dataclass
class SourcePosition:
    line: int
    column: int

    def to_json(self) -> dict:
        # json tags are "Line" and "column" in the reference
        # (ketoapi/public_api_definitions.go:257-258).
        return {"Line": self.line, "column": self.column}


class ParseError(Exception):
    def __init__(self, msg: str, item: Item, source: str):
        super().__init__(msg)
        self.msg = msg
        self.item = item
        self.source = source

    def _to_src_pos(self, pos: int) -> SourcePosition:
        # Mirrors parse_errors.go:104-117 (column resets to 0 after newline).
        line, col = 1, 0
        for c in self.source:
            col += 1
            pos -= 1
            if pos <= 0:
                break
            if c == "\n":
                line += 1
                col = 0
        return SourcePosition(line, col)

    @property
    def start(self) -> SourcePosition:
        return self._to_src_pos(self.item.start)

    @property
    def end(self) -> SourcePosition:
        return self._to_src_pos(self.item.end)

    def to_json(self) -> dict:
        return {
            "message": self.msg,
            "start": self.start.to_json(),
            "end": self.end.to_json(),
        }

    def __str__(self) -> str:
        start, end = self.start, self.end
        rows = self.source.split("\n")
        out = [f"error from {start.line}:{start.column} to {end.line}:{end.column}: {self.msg}", ""]
        if len(rows) < start.line:
            out.append("meta error: could not find source position in input")
            return "\n".join(out) + "\n"
        start_line_idx = max(start.line - 2, 0)
        error_line_idx = max(start.line - 1, 0)
        for line in range(start_line_idx, error_line_idx + 1):
            out.append(f"{line:4d} | {rows[line]}")
        marker = []
        for i, r in enumerate(rows[error_line_idx]):
            if start.column == i:
                marker.append("^")
            elif start.column <= i <= end.column - 1:
                marker.append("~")
            elif r.isspace():
                marker.append(r)
            else:
                marker.append(" ")
        out.append("     | " + "".join(marker))
        if error_line_idx + 1 < len(rows):
            out.append(f"{error_line_idx + 1:4d} | {rows[error_line_idx + 1]}")
            out.append("")
        return "\n".join(out) + "\n"


class _Capture:
    """Capture slot for `_match`: NAME takes identifier/string-literal values,
    ANY takes any next item."""

    __slots__ = ("kind", "item")

    def __init__(self, kind: str):
        self.kind = kind
        self.item: Optional[Item] = None

    @property
    def val(self) -> str:
        assert self.item is not None
        return self.item.val


def _name() -> _Capture:
    return _Capture("name")


def _any() -> _Capture:
    return _Capture("any")


def _optional(*tokens: str) -> Callable:
    """Optionally match the token sequence: if the first token is present it is
    consumed and the rest must follow (parser.go:91-109)."""

    def matcher(p: "_Parser") -> bool:
        if not tokens:
            return True
        if p._peek().val == tokens[0]:
            p._next()
            for token in tokens[1:]:
                i = p._next()
                if i.val != token:
                    p._add_fatal(i, f'expected "{token}", got "{i.val}"')
                    return False
        return True

    return matcher


class _Parser:
    def __init__(self, source: str):
        self.source = source
        self._tokens = tokenize_non_comment(source)
        self._last_item: Optional[Item] = None
        self._lookahead: Optional[Item] = None
        self.namespaces: List[Namespace] = []
        self.namespace: Optional[Namespace] = None
        self.errors: List[ParseError] = []
        self.fatal = False
        self.checks: List[Callable[["_Parser"], None]] = []

    # -- token stream -------------------------------------------------------

    def _next(self) -> Item:
        if self._lookahead is not None:
            item, self._lookahead = self._lookahead, None
            return item
        # After the stream ends (EOF or ERROR), keep returning the final item,
        # like the reference lexer keeps emitting items after termination.
        item = next(self._tokens, self._last_item)
        assert item is not None
        self._last_item = item
        return item

    def _peek(self) -> Item:
        if self._lookahead is None:
            self._lookahead = self._next()
        return self._lookahead

    # -- error bookkeeping --------------------------------------------------

    def _add_err(self, item: Item, msg: str) -> None:
        self.errors.append(ParseError(msg, item, self.source))

    def _add_fatal(self, item: Item, msg: str) -> None:
        self._add_err(item, msg)
        self.fatal = True

    def _add_check(self, check: Callable[["_Parser"], None]) -> None:
        self.checks.append(check)

    # -- matching machinery (parser.go:111-168) -----------------------------

    def _match(self, *tokens) -> bool:
        if self.fatal:
            return False
        for token in tokens:
            if isinstance(token, str):
                i = self._next()
                if i.val != token:
                    self._add_fatal(i, f'expected "{token}", got "{i.val}"')
                    return False
            elif isinstance(token, _Capture):
                i = self._next()
                if token.kind == "name" and i.typ not in (
                    ItemType.IDENTIFIER,
                    ItemType.STRING_LITERAL,
                ):
                    self._add_fatal(i, f"expected identifier, got {i.typ.value}")
                    return False
                token.item = i
            elif callable(token):
                if not token(self):
                    return False
            else:  # pragma: no cover
                raise TypeError(f"unexpected match token {token!r}")
        return True

    def _match_if(self, typ: ItemType, *tokens) -> bool:
        if self.fatal:
            return False
        if self._peek().typ is not typ:
            return False
        return self._match(*tokens)

    # -- grammar ------------------------------------------------------------

    def parse(self) -> Tuple[List[Namespace], List[ParseError]]:
        while not self.fatal:
            item = self._next()
            if item.typ is ItemType.EOF:
                break
            elif item.typ is ItemType.ERROR:
                self._add_fatal(item, f"fatal: {item.val}")
            elif item.typ is ItemType.KEYWORD_CLASS:
                self._parse_class()

        if not self.errors:
            for check in self.checks:
                check(self)

        return self.namespaces, self.errors

    def _parse_class(self) -> None:
        name = _name()
        self._match(name, "implements", "Namespace", "{")
        if self.fatal:
            return
        self.namespace = Namespace(name=name.val)

        while not self.fatal:
            item = self._next()
            if item.typ is ItemType.BRACE_RIGHT:
                self.namespaces.append(self.namespace)
                return
            elif item.val == "related":
                self._parse_related()
            elif item.val == "permits":
                self._parse_permits()
            elif item.typ is ItemType.SEMICOLON:
                continue
            else:
                self._add_fatal(item, f"expected 'permits' or 'related', got \"{item.val}\"")
                return

    def _parse_related(self) -> None:
        self._match(":", "{")
        while not self.fatal:
            item = self._next()
            if item.typ is ItemType.SEMICOLON:
                continue
            elif item.typ is ItemType.BRACE_RIGHT:
                return
            elif item.typ in (ItemType.IDENTIFIER, ItemType.STRING_LITERAL):
                relation = item.val
                types: List[RelationType] = []
                self._match(":")

                t = self._next()
                if t.val == "Array":
                    self._match("<")
                    types.extend(self._parse_type_union(ItemType.ANGLED_RIGHT))
                elif t.val == "SubjectSet":
                    types.append(self._match_subject_set())
                    self._match("[", "]", _optional(","))
                elif t.typ is ItemType.PAREN_LEFT:
                    types.extend(self._parse_type_union(ItemType.PAREN_RIGHT))
                    self._match("[", "]", _optional(","))
                else:
                    types.append(RelationType(namespace=t.val))
                    self._add_check(_check_namespace_exists(t))
                    self._match("[", "]", _optional(","))

                if self.namespace is not None:
                    self.namespace.relations.append(Relation(name=relation, types=types))
            else:
                self._add_fatal(
                    item, f"expected identifier or '}}', got {item.typ.value} \"{item.val}\""
                )
                return

    def _match_subject_set(self) -> RelationType:
        namespace, relation = _any(), _any()
        self._match("<", namespace, ",", relation, ">")
        if namespace.item is not None and relation.item is not None:
            self._add_check(_check_namespace_has_relation(namespace.item, relation.item))
            return RelationType(namespace=namespace.val, relation=relation.val)
        return RelationType(namespace="", relation="")

    def _parse_type_union(self, end_type: ItemType) -> List[RelationType]:
        types: List[RelationType] = []
        while not self.fatal:
            identifier = _any()
            self._match(identifier)
            if identifier.item is None:
                return types
            if identifier.val == "SubjectSet":
                types.append(self._match_subject_set())
            else:
                types.append(RelationType(namespace=identifier.val))
                self._add_check(_check_namespace_exists(identifier.item))
            item = self._next()
            if item.typ is end_type:
                return types
            elif item.typ is ItemType.TYPE_UNION:
                continue
            else:
                self._add_fatal(item, f"expected '|', got \"{item.val}\"")
        return types

    def _parse_permits(self) -> None:
        self._match("=", "{")
        while not self.fatal:
            item = self._next()
            if item.typ is ItemType.BRACE_RIGHT:
                return
            elif item.typ in (ItemType.IDENTIFIER, ItemType.STRING_LITERAL):
                permission = item.val
                self._match(
                    ":", "(", "ctx", _optional(":", "Context"), ")",
                    _optional(":", "boolean"), "=>",
                )
                rewrite = simplify_expression(
                    self._parse_permission_expressions(
                        ItemType.OPERATOR_COMMA, EXPRESSION_NESTING_MAX_DEPTH
                    )
                )
                if rewrite is None:
                    return
                if self.namespace is not None:
                    self.namespace.relations.append(
                        Relation(name=permission, subject_set_rewrite=rewrite)
                    )
            else:
                self._add_fatal(
                    item, f"expected identifier or '}}', got {item.typ.value} \"{item.val}\""
                )
                return

    def _parse_permission_expressions(
        self, final_type: ItemType, depth: int
    ) -> Optional[SubjectSetRewrite]:
        if depth <= 0:
            self._add_fatal(
                self._peek(),
                "expression nested too deeply; maximal nesting depth is "
                f"{EXPRESSION_NESTING_MAX_DEPTH}",
            )
            return None

        root: Optional[SubjectSetRewrite] = None
        # Only expect an expression at the beginning and after a binary operator.
        expect_expression = True

        while not self.fatal:
            item = self._peek()

            if item.typ is ItemType.PAREN_LEFT:
                self._next()
                child = self._parse_permission_expressions(ItemType.PAREN_RIGHT, depth - 1)
                if child is None:
                    return None
                root = _add_child(root, child)
                expect_expression = False

            elif item.typ is final_type:
                self._next()
                return root

            elif item.typ is ItemType.BRACE_RIGHT:
                # Leave '}' for _parse_permits to consume.
                return root

            elif item.typ in (ItemType.OPERATOR_AND, ItemType.OPERATOR_OR):
                self._next()
                # A binary operator before the first expression is invalid.
                if root is None:
                    return None
                root = SubjectSetRewrite(
                    operation=(
                        Operator.AND if item.typ is ItemType.OPERATOR_AND else Operator.OR
                    ),
                    children=[root],
                )
                expect_expression = True

            elif item.typ is ItemType.OPERATOR_NOT:
                self._next()
                child = self._parse_not_expression(depth - 1)
                if child is None:
                    return None
                root = _add_child(root, child)
                expect_expression = False

            else:
                if not expect_expression:
                    self._add_fatal(item, "did not expect another expression")
                    return None
                child = self._parse_permission_expression()
                if child is None:
                    return None
                root = _add_child(root, child)
                # Deliberate parity quirk: the reference re-arms
                # expectExpression after a plain expression (parser.go:373),
                # so two adjacent plain expressions do not error.
                expect_expression = True
        return None

    def _parse_not_expression(self, depth: int) -> Optional[Child]:
        if depth <= 0:
            self._add_fatal(
                self._peek(),
                "expression nested too deeply; maximal nesting depth is "
                f"{EXPRESSION_NESTING_MAX_DEPTH}",
            )
            return None

        if self._peek().typ is ItemType.PAREN_LEFT:
            self._next()
            child: Optional[Child] = self._parse_permission_expressions(
                ItemType.PAREN_RIGHT, depth - 1
            )
        else:
            child = self._parse_permission_expression()
        if child is None:
            return None
        return InvertResult(child=child)

    def _match_property_access(self, prop) -> bool:
        return self._match_if(ItemType.BRACKET_LEFT, "[", prop, "]") or self._match(".", prop)

    def _parse_permission_expression(self) -> Optional[Child]:
        verb, name = _any(), _any()

        if not self._match("this", ".", verb):
            return None
        if not self._match_property_access(name):
            return None

        if verb.val == "related":
            if not self._match("."):
                return None
            item = self._next()
            if item.val == "traverse":
                return self._parse_tuple_to_subject_set(name.item)
            elif item.val == "includes":
                return self._parse_computed_subject_set(name.item)
            else:
                self._add_fatal(item, f"expected 'traverse' or 'includes', got \"{item.val}\"")
                return None

        elif verb.val == "permits":
            if not self._match("(", "ctx", ")"):
                return None
            assert self.namespace is not None
            self._add_check(
                _check_current_namespace_has_relation(self.namespace.name, name.item)
            )
            return ComputedSubjectSet(relation=name.val)

        else:
            self._add_fatal(
                verb.item, f"expected 'related' or 'permits', got \"{verb.val}\""
            )
            return None

    def _parse_tuple_to_subject_set(self, relation: Item) -> Optional[Child]:
        arg, verb = _any(), _any()
        subject_set_rel = _name()

        if not self._match("("):
            return None
        if not (self._match_if(ItemType.PAREN_LEFT, "(", arg, ")") or self._match(arg)):
            return None
        self._match("=>", arg.val, ".", verb)
        if self.fatal:
            return None

        if verb.val == "related":
            if not self._match_property_access(subject_set_rel):
                return None
            self._match(
                ".", "includes", "(", "ctx", ".", "subject",
                _optional(","), ")", _optional(","), ")",
            )
            assert self.namespace is not None
            self._add_check(
                _check_all_relation_types_have_relation(
                    self.namespace.name, relation, subject_set_rel.val
                )
            )
        elif verb.val == "permits":
            if not self._match_property_access(subject_set_rel):
                return None
            self._match("(", "ctx", ")", ")")
            assert self.namespace is not None
            self._add_check(
                _check_all_relation_types_have_relation(
                    self.namespace.name, relation, subject_set_rel.val
                )
            )
        else:
            self._add_fatal(verb.item, f"expected 'related' or 'permits', got \"{verb.val}\"")
            return None

        assert self.namespace is not None
        self._add_check(_check_current_namespace_has_relation(self.namespace.name, relation))
        return TupleToSubjectSet(
            relation=relation.val, computed_subject_set_relation=subject_set_rel.val
        )

    def _parse_computed_subject_set(self, relation: Item) -> Optional[Child]:
        if not self._match("(", "ctx", ".", "subject", ")"):
            return None
        assert self.namespace is not None
        self._add_check(_check_current_namespace_has_relation(self.namespace.name, relation))
        return ComputedSubjectSet(relation=relation.val)


def _add_child(root: Optional[SubjectSetRewrite], child: Child) -> SubjectSetRewrite:
    if root is None:
        return as_rewrite(child)
    root.children.append(child)
    return root


def simplify_expression(root: Optional[SubjectSetRewrite]) -> Optional[SubjectSetRewrite]:
    """Merge binary chains of the same operator into n-ary nodes
    (parser.go:519-537)."""
    if root is None:
        return None
    new_children: List[Child] = []
    for child in root.children:
        if isinstance(child, SubjectSetRewrite) and child.operation == root.operation:
            simplify_expression(child)
            new_children.extend(child.children)
        else:
            new_children.append(child)
    root.children = new_children
    return root


# -- type checks (typechecks.go:44-130) -------------------------------------


def _find_namespace(namespaces: List[Namespace], name: str) -> Optional[Namespace]:
    for n in namespaces:
        if n.name == name:
            return n
    return None


def _find_relation(namespaces: List[Namespace], namespace: str, relation: str):
    n = _find_namespace(namespaces, namespace)
    if n is None:
        return None
    return n.relation(relation)


def _check_namespace_exists(namespace: Item):
    def check(p: _Parser) -> None:
        if _find_namespace(p.namespaces, namespace.val) is None:
            p._add_err(namespace, f'namespace "{namespace.val}" was not declared')

    return check


def _check_namespace_has_relation(namespace: Item, relation: Item):
    def check(p: _Parser) -> None:
        n = _find_namespace(p.namespaces, namespace.val)
        if n is None:
            p._add_err(namespace, f'namespace "{namespace.val}" was not declared')
            return
        if n.relation(relation.val) is None:
            p._add_err(
                relation,
                f'namespace "{namespace.val}" did not declare relation "{relation.val}"',
            )

    return check


def _check_current_namespace_has_relation(namespace_name: str, relation: Item):
    def check(p: _Parser) -> None:
        n = _find_namespace(p.namespaces, namespace_name)
        if n is None:
            p._add_err(relation, f'namespace "{namespace_name}" was not declared')
            return
        if n.relation(relation.val) is None:
            p._add_err(
                relation,
                f'namespace "{namespace_name}" did not declare relation "{relation.val}"',
            )

    return check


def _check_all_relation_types_have_relation(
    namespace_name: str, relation_type: Item, relation: str
):
    def check(p: _Parser) -> None:
        _recursive_types_check(
            p,
            relation_type,
            namespace_name,
            relation_type.val,
            relation,
            TUPLE_TO_SUBJECT_SET_TYPECHECK_MAX_DEPTH,
        )

    return check


def _recursive_types_check(
    p: _Parser, item: Item, namespace: str, relation_type: str, relation: str, depth: int
) -> None:
    if depth < 0:
        p._add_err(item, "could not typecheck deeply nested SubjectSet further")
        return
    r = _find_relation(p.namespaces, namespace, relation_type)
    if r is None:
        p._add_err(
            item, f'relation "{relation_type}" was not declared in namespace "{namespace}"'
        )
        return
    for t in r.types:
        if t.relation == "":
            if _find_relation(p.namespaces, t.namespace, relation) is None:
                p._add_err(
                    item,
                    f'relation "{relation}" was not declared in namespace "{t.namespace}"',
                )
        else:
            # The type is itself a subject set: recursively check that it
            # (eventually) declares the required relation.
            _recursive_types_check(p, item, t.namespace, t.relation, relation, depth - 1)


def parse(source: str) -> Tuple[List[Namespace], List[ParseError]]:
    """Parse OPL source into namespaces; returns (namespaces, errors)."""
    return _Parser(source).parse()
