"""OTLP/HTTP trace export for the homegrown tracer.

The reference traces every layer through otelx (OpenTelemetry wired in
`internal/driver/registry_default.go:151-168`, instrumented SQL in
`persistence/sql/pop_connection.go:26-31`).  The repo's `Tracer`
(observability.py) keeps the same span/event call sites but records only
local histograms; this module adds the missing *export* half without new
dependencies: an `OTLPTracer` subclass that builds OTLP/JSON trace
payloads by hand and ships them to a collector's ``/v1/traces`` endpoint
over HTTP on a background flusher thread.

Call sites are unchanged — the registry swaps the tracer in when
``tracing.provider: otlp`` is configured (`ketoctx.WithTracerWrapper``
still wraps whatever tracer the registry builds, so embedders compose).

Wire format: OTLP 1.x JSON (`opentelemetry-proto` ExportTraceServiceRequest
with camelCase keys and hex-encoded ids), the encoding every OTLP/HTTP
collector accepts alongside protobuf.  Export failures increment a
counter and drop the batch — tracing must never take serving down.
"""

from __future__ import annotations

import json
import secrets
import threading
import time
import urllib.request
from contextlib import contextmanager
from typing import Dict, List, Optional

from ketotpu.observability import (
    Metrics,
    Tracer,
    format_traceparent,
    parse_traceparent,
)


def _attr(key: str, value) -> Dict:
    if isinstance(value, bool):
        return {"key": key, "value": {"boolValue": value}}
    if isinstance(value, int):
        return {"key": key, "value": {"intValue": str(value)}}
    if isinstance(value, float):
        return {"key": key, "value": {"doubleValue": value}}
    return {"key": key, "value": {"stringValue": str(value)}}


class OTLPTracer(Tracer):
    """Tracer with OTLP/HTTP-JSON export.

    Spans nest through a thread-local stack (children link to the
    enclosing span and share its trace id); events attach to the current
    span, or emit as zero-duration spans when none is open.
    """

    def __init__(
        self,
        endpoint: str,
        *,
        metrics: Optional[Metrics] = None,
        logger=None,
        service_name: str = "keto-tpu",
        flush_interval: float = 2.0,
        max_batch: int = 512,
        max_queue: int = 8192,
    ):
        super().__init__(metrics, logger)
        self.endpoint = endpoint.rstrip("/")
        if not self.endpoint.endswith("/v1/traces"):
            self.endpoint += "/v1/traces"
        self.service_name = service_name
        self.flush_interval = flush_interval
        self.max_batch = max_batch
        self.max_queue = max_queue
        self.exported = 0
        self.dropped = 0
        self.export_errors = 0
        self._q: List[Dict] = []
        self._qlock = threading.Lock()
        self._local = threading.local()
        self._wake = threading.Event()
        self._closed = False
        self._flusher = threading.Thread(
            target=self._run, name="otlp-flusher", daemon=True
        )
        self._flusher.start()

    # -- tracer surface (call sites unchanged) ------------------------------

    @contextmanager
    def span(self, name: str, _parent: Optional[str] = None, **attrs):
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        parent = stack[-1] if stack else None
        # remote parent (W3C traceparent) only seeds a root span — an open
        # local span already owns the trace on this thread
        remote = parse_traceparent(_parent) if parent is None else None
        if parent is not None:
            trace_id = parent["traceId"]
        elif remote is not None:
            trace_id = remote[0]
        else:
            trace_id = secrets.token_hex(16)
        rec = {
            "traceId": trace_id,
            "spanId": secrets.token_hex(8),
            "name": name,
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(time.time_ns()),
            "attributes": [_attr(k, v) for k, v in attrs.items()],
            "events": [],
        }
        if parent is not None:
            rec["parentSpanId"] = parent["spanId"]
        elif remote is not None:
            rec["parentSpanId"] = remote[1]
        stack.append(rec)
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            stack.pop()
            rec["endTimeUnixNano"] = str(time.time_ns())
            self._enqueue(rec)
            # keep the local histogram behavior (observability.py)
            if self.metrics is not None:
                self.metrics.observe(
                    "keto_span_duration_seconds",
                    time.perf_counter() - t0,
                    help="span wall time", span=name,
                )

    def current_traceparent(self) -> Optional[str]:
        stack = getattr(self._local, "stack", None)
        if not stack:
            return None
        top = stack[-1]
        return format_traceparent(top["traceId"], top["spanId"])

    def event(self, name: str, **attrs):
        super().event(name, **attrs)
        stack = getattr(self._local, "stack", None)
        if stack:
            stack[-1]["events"].append({
                "name": name,
                "timeUnixNano": str(time.time_ns()),
                "attributes": [_attr(k, v) for k, v in attrs.items()],
            })
            return
        now = str(time.time_ns())
        self._enqueue({
            "traceId": secrets.token_hex(16),
            "spanId": secrets.token_hex(8),
            "name": name,
            "kind": 1,
            "startTimeUnixNano": now,
            "endTimeUnixNano": now,
            "attributes": [_attr(k, v) for k, v in attrs.items()],
            "events": [],
        })

    def export_trace(self, entry: Dict) -> None:
        """Ship one promoted trace-store entry (ketotpu/tracing.py) through
        the ordinary flush path: the closing ``rpc.<op>`` span becomes the
        root, every buffered stage/remote span a child, all sharing the
        entry's trace id.  Epoch-second span stamps convert to unix nanos."""
        tid = entry.get("trace_id")
        spans = entry.get("spans") or []
        if not tid or not spans:
            return
        root_sid = secrets.token_hex(8)
        skip_keys = {"name", "pid", "t0", "t1", "ms"}
        for i, s in enumerate(spans):
            is_root = i == len(spans) - 1
            attrs = [
                _attr(k, v) for k, v in s.items() if k not in skip_keys
            ]
            attrs.append(_attr("pid", s.get("pid", 0)))
            if is_root:
                attrs.append(
                    _attr("promoted", ",".join(entry.get("promoted", [])))
                )
                for k, v in (entry.get("info") or {}).items():
                    if isinstance(v, (str, int, float, bool)):
                        attrs.append(_attr(k, v))
            rec = {
                "traceId": tid,
                "spanId": root_sid if is_root else secrets.token_hex(8),
                "name": str(s.get("name", "span")),
                "kind": 1,
                "startTimeUnixNano": str(int(float(s.get("t0", 0.0)) * 1e9)),
                "endTimeUnixNano": str(int(float(s.get("t1", 0.0)) * 1e9)),
                "attributes": attrs,
                "events": [],
            }
            if not is_root:
                rec["parentSpanId"] = root_sid
            self._enqueue(rec)

    # -- batching / export ---------------------------------------------------

    def _enqueue(self, rec: Dict) -> None:
        with self._qlock:
            if len(self._q) >= self.max_queue:
                self.dropped += 1
                return
            self._q.append(rec)
            full = len(self._q) >= self.max_batch
        if full:
            self._wake.set()

    def _run(self) -> None:
        while not self._closed:
            self._wake.wait(self.flush_interval)
            self._wake.clear()
            self.flush()

    def flush(self) -> None:
        """Ship everything queued; safe to call from tests/shutdown."""
        with self._qlock:
            batch, self._q = self._q, []
        if not batch:
            return
        payload = {
            "resourceSpans": [{
                "resource": {
                    "attributes": [_attr("service.name", self.service_name)],
                },
                "scopeSpans": [{
                    "scope": {"name": "ketotpu"},
                    "spans": batch,
                }],
            }]
        }
        req = urllib.request.Request(
            self.endpoint,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            urllib.request.urlopen(req, timeout=5).read()
            self.exported += len(batch)
        except Exception:  # noqa: BLE001 - export must never break serving
            self.export_errors += 1
            if self.metrics is not None:
                self.metrics.counter(
                    "keto_otlp_export_errors_total", 1,
                    help="failed OTLP trace exports",
                )

    def close(self) -> None:
        self._closed = True
        self._wake.set()
        self.flush()
