"""Multi-chip parallelism for the check engine.

The reference scales out as stateless replicas over a shared SQL database
(SURVEY §2 checklist: no collectives, no multi-process runtime exist there).
Here scale-out is a first-class device-mesh design:

* **query data-parallelism** (`shard_fast_check`, `shard_general_check`): the
  batch axis of checks is sharded over the mesh, the tuple graph is
  replicated — every device runs its query shard with zero cross-device
  traffic.  This is the throughput axis (BatchCheck, BASELINE config #4).
* **graph sharding** (`graphshard.sharded_check`): tuples partitioned by
  (namespace, object) hash across the mesh; each BFS level does local CSR
  gathers, routes cross-shard children with `lax.all_to_all` over ICI, and
  psum-merges the monotone found-bits — the capacity axis for graphs beyond
  one chip's HBM (BASELINE config #5).
"""

import jax

if not hasattr(jax, "shard_map"):
    # jax < 0.5 ships shard_map under experimental only (and spells the
    # replication-check knob check_rep); every mesh program here calls
    # jax.shard_map(..., check_vma=...), so adapt it once at import
    from jax.experimental.shard_map import shard_map as _shard_map

    def _compat_shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )

    jax.shard_map = _compat_shard_map

from ketotpu.parallel.graphshard import (
    build_sharded_snapshot,
    sharded_check,
    sharded_general_check,
)
from ketotpu.parallel.mesh import make_mesh, shard_fast_check, shard_general_check
from ketotpu.parallel.meshengine import MeshCheckEngine
from ketotpu.parallel.peerlink import HostLink, host_of

__all__ = [
    "HostLink",
    "MeshCheckEngine",
    "build_sharded_snapshot",
    "host_of",
    "make_mesh",
    "shard_general_check",
    "shard_fast_check",
    "sharded_check",
    "sharded_general_check",
]
