"""Multi-chip parallelism for the check engine.

The reference scales out as stateless replicas over a shared SQL database
(SURVEY §2 checklist: no collectives, no multi-process runtime exist there).
Here scale-out is a first-class device-mesh design:

* **query data-parallelism** (`shard_batch_check`): the batch axis of checks
  is sharded over the mesh, the tuple graph is replicated — every device runs
  the full wavefront interpreter on its query shard with zero cross-device
  traffic.  This is the throughput axis (BatchCheck, BASELINE config #4).
* **graph sharding** (parallel/graphshard.py): membership and CSR rows
  partitioned by node hash across a second mesh axis with psum-combined
  probes over ICI — the capacity axis for graphs beyond one chip's HBM
  (BASELINE config #5).
"""

from ketotpu.parallel.mesh import make_mesh, shard_batch_check

__all__ = ["make_mesh", "shard_batch_check"]
