"""Graph-sharded batch checks: the CSR partitioned across the device mesh.

BASELINE config #5: a 10M-tuple graph object-sharded over a mesh, with
cross-namespace subject-set / tuple-to-userset hops routed over ICI.  The
reference has no analog — it scales out with stateless replicas over one SQL
database (SURVEY §2 parallelism checklist); this layout is the TPU-native
replacement.

Partitioning: a tuple row lives on shard ``hash(namespace, object) % n``
(hashtab's mix, salt 0).  Keying by (namespace, object) — not the full node
key — keeps every relation of an object co-resident, so

* direct membership probes,
* the batched computed-subject-set shortcut (same object, other relation),
* tuple-to-userset via-rows (same object, via relation)

are all shard-local.  Only *children* can cross shards: subject-set
expansion targets and TTU computed targets.  Each BFS level therefore runs

    expand (local gathers)  →  all-to-all (route children to owners)
    →  pack (dedup on arrival)  →  psum (merge found/over bits)

inside one `jax.shard_map`, with `fastpath.expand_phase(sharded=True)`
providing exact EXISTS-bit semantics across shards: expansion children carry
a forced membership probe executed by their owner on arrival, and
width-truncated children ship as probe-only items (depth 0) so the
pre-truncation EXISTS check of `engine.go:131-139` survives sharding.

The all-to-all uses fixed per-destination buckets (capacity = arena / n per
peer); bucket overflow sets the affected queries' ``q_over`` bits — the same
monotone overflow contract as the single-chip engine.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ketotpu import compilewatch
from ketotpu.engine import fastpath as fp
from ketotpu.engine import hashtab
from ketotpu.engine.snapshot import Snapshot
from ketotpu.storage.memory import InMemoryTupleStore
from ketotpu.storage.namespaces import NamespaceManager
from ketotpu.engine.vocab import Vocab


def shard_of_np(ns_ids: np.ndarray, obj_ids: np.ndarray, n_shards: int) -> np.ndarray:
    """Owner shard of (namespace, object) — host side."""
    h = hashtab._mix_np(
        np.asarray(ns_ids, np.int64), np.asarray(obj_ids, np.int64),
        hashtab._SALTS[0],
    )
    return (h % np.uint32(n_shards)).astype(np.int32)


def shard_of_device(ns_ids, obj_ids, n_shards: int):
    h = hashtab.mix_device(ns_ids, obj_ids, jnp.uint32(hashtab._SALTS[0]))
    return (h % jnp.uint32(n_shards)).astype(jnp.int32)


def build_sharded_snapshot(
    store: InMemoryTupleStore,
    manager: Optional[NamespaceManager],
    n_shards: int,
    vocab: Optional[Vocab] = None,
    cols=None,
    replicate: Optional[Dict[Tuple[int, int], Sequence[int]]] = None,
) -> Tuple[List[Snapshot], Dict[str, np.ndarray]]:
    """Partition the store by owner shard and build one snapshot per shard.

    All shards share one vocabulary (ids are global) and are padded to
    common array shapes, so the stacked dict (leading axis = shard) can be
    fed through `shard_map` with the graph partitioned on that axis.

    Partitioning is a vectorized mask over the engine's column mirror
    (``cols``, engine/delta.TupleColumns — passed by the mesh engine so a
    rebuild reuses its freshly synced mirror; built here otherwise), not a
    per-tuple Python loop: each shard's snapshot projects through the same
    `build_snapshot_cols` numpy path as the single-chip engine.

    ``replicate`` maps hot (ns_id, obj_id) keys to extra shards that get a
    COPY of those rows on top of their hash-owned partition.  The hash
    owner always keeps its rows (replication copies, never moves), so
    child routing by hash stays correct; a replicated root query may be
    assigned to any of its replicas via `sharded_check`'s ``assign``
    column.  Replica copies pad into the existing max-shard shapes in the
    common case, so publishing a replica map usually keeps the stacked
    signature — and the jit cache — warm.
    """
    from ketotpu.engine import delta as dl

    vocab = vocab if vocab is not None else Vocab()
    if cols is None:
        exporter = getattr(store, "export_columns", None)
        store_vocab = getattr(store, "vocab", None)
        if exporter is not None and (
            store_vocab is vocab or len(vocab.subjects) == 0
        ):
            carr, alive, tail, _head = exporter()
            cols = dl.TupleColumns.from_arrays(store_vocab, carr, alive)
            for t in tail:
                cols.apply(1, t)
            vocab = store_vocab
        else:
            cols = dl.TupleColumns(vocab)
            for t in store.all_tuples():
                cols.apply(1, t)

    live = np.flatnonzero(cols.alive[: cols.n])
    shard = shard_of_np(cols.ns[live], cols.obj[live], n_shards)
    extra = [np.zeros(0, np.int64)] * n_shards
    if replicate:
        packed = (
            np.asarray(cols.ns[live], np.int64) << 32
        ) | (np.asarray(cols.obj[live], np.int64) & 0xFFFFFFFF)
        for (ns_id, obj_id), shards_for in replicate.items():
            key = (np.int64(ns_id) << 32) | (np.int64(obj_id) & 0xFFFFFFFF)
            rows = live[packed == key]
            if rows.size == 0:
                continue
            for s in shards_for:
                extra[int(s)] = np.concatenate([extra[int(s)], rows])
    version = getattr(store, "version", -1)
    snaps: List[Snapshot] = []
    for s in range(n_shards):
        keep = np.zeros(cols.n, bool)
        keep[live[shard == s]] = True
        keep[extra[s]] = True
        snaps.append(
            dl.build_snapshot_cols(
                cols.masked(keep), manager, version=version
            )
        )

    # pad every per-shard array to the maximum shape, then stack
    keys = snaps[0].arrays().keys()
    stacked: Dict[str, np.ndarray] = {}
    for k in keys:
        arrs = [np.asarray(s.arrays()[k]) for s in snaps]
        shape = tuple(max(a.shape[i] for a in arrs) for i in range(arrs[0].ndim))
        padded = []
        for a in arrs:
            pad = [(0, shape[i] - a.shape[i]) for i in range(a.ndim)]
            fill = 0 if k.endswith("ptr") else (False if a.dtype == bool else -1)
            b = np.pad(a, pad, constant_values=fill)
            if k.endswith("ptr") and a.shape[0] < shape[0]:
                b[a.shape[0]:] = a[-1]  # CSR tail rows stay empty
            padded.append(b)
        stacked[k] = np.stack(padded)
    return snaps, stacked


def _route(children: Dict, n: int, cap: int, q_over, axis: str):
    """Bucket children by owner shard and all-to-all them to owners.

    ``cap`` slots per destination peer; overflow marks q_over (monotone).
    """
    Q = q_over.shape[0]
    dest = shard_of_device(children["ns"], children["obj"], n)
    alive = children["qid"] >= 0
    dest = jnp.where(alive, dest, n)  # dead rows sort last

    # stable sort by destination, then slot within each dest bucket
    A = dest.shape[0]
    order = jnp.argsort(dest * (A + 1) + jnp.arange(A, dtype=jnp.int32))
    dsorted = dest[order]
    # position within the destination run
    pos_in_run = jnp.arange(A, dtype=jnp.int32) - jnp.searchsorted(
        dsorted, dsorted, side="left"
    )
    over_b = (dsorted < n) & (pos_in_run >= cap)
    srt = {k: v[order] for k, v in children.items()}
    q_over = q_over.at[jnp.clip(srt["qid"], 0, Q - 1)].max(over_b & (srt["qid"] >= 0))

    slot = jnp.where(dsorted < n, dsorted * cap + jnp.clip(pos_in_run, 0, cap - 1), n * cap)
    slot = jnp.where(over_b, n * cap, slot)

    def bucketize(col, fill):
        return (
            jnp.full((n * cap,), fill, col.dtype)
            .at[slot]
            .set(jnp.where(over_b | (dsorted >= n), fill, col), mode="drop")
        )

    send = jnp.stack(
        [
            bucketize(srt["qid"], -1),
            bucketize(srt["ns"], -1),
            bucketize(srt["obj"], -1),
            bucketize(srt["rel"], -1),
            bucketize(srt["d"], 0),
            bucketize(srt["skip"].astype(jnp.int32), 1),
            bucketize(srt["force"].astype(jnp.int32), 0),
        ],
        axis=1,
    ).reshape(n, cap, 7)
    recv = jax.lax.all_to_all(send, axis, 0, 0, tiled=False)
    recv = recv.reshape(n * cap, 7)
    out = dict(
        qid=recv[:, 0],
        ns=recv[:, 1],
        obj=recv[:, 2],
        rel=recv[:, 3],
        d=recv[:, 4],
        skip=recv[:, 5].astype(bool),
        force=recv[:, 6].astype(bool),
    )
    return out, q_over


def sharded_general_check(
    stacked_g: Dict[str, np.ndarray],
    qpack: np.ndarray,
    mesh: Mesh,
    *,
    axis: str = "shard",
    sizes,
    fast_b: int,
    fast_sched,
    max_width: int = 100,
    vcap: int = 4096,
):
    """General (AND/NOT) checks against the SHARDED graph — no replica.

    The fused algebra program runs on every shard over the full
    (replicated) query block with per-task work owner-masked and merged
    (algebra.run_general_packed's ``shard`` mode): the (ns, obj)
    partitioning keeps all of a task's reads shard-local, children land
    on their owners via the program's merge collectives, and pure-OR
    fast leaves ride the same all_to_all-routed BFS as `sharded_check`.
    Per-device GRAPH memory scales down with mesh size (VERDICT r4 #5);
    only the per-batch skeleton working set is replicated.

    ``sizes``/``fast_sched`` are GLOBAL shapes (the whole batch's
    skeleton lives on every shard).  Returns (codes uint8[Q], occ
    int32[n, L]) with codes replicated-identical across shards.
    """
    with compilewatch.scope(
        "sharded_general",
        lambda: f"Q={qpack.shape[1]} n={mesh.devices.size} "
                f"sizes={tuple(sizes)}",
    ):
        return _sharded_general_run(
            stacked_g, jnp.asarray(qpack, jnp.int32),
            mesh=mesh, axis=axis,
            sizes=tuple(sizes), fast_b=int(fast_b),
            fast_sched=tuple(fast_sched), max_width=max_width, vcap=vcap,
        )


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "axis", "sizes", "fast_b", "fast_sched", "max_width", "vcap",
    ),
)
def _sharded_general_run(
    g, qp, *, mesh: Mesh, axis, sizes, fast_b, fast_sched, max_width, vcap
):
    # module-level jit: the cache must hit across serving dispatches (a
    # per-call closure would retrace + recompile the fused sharded
    # program for every general batch)
    from ketotpu.engine import algebra as alg

    def local(g, qp):
        g = jax.tree_util.tree_map(lambda a: a[0], g)
        codes, occ = alg.run_general_packed(
            g, qp, sizes=sizes, fast_b=fast_b, fast_sched=fast_sched,
            max_width=max_width, vcap=vcap,
            shard=(axis, mesh.devices.size),
        )
        return codes, occ[None, :]

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(axis), g), P()),
        out_specs=(P(), P(axis)),
        check_vma=False,
    )(g, qp)


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "axis", "n", "cap", "frontier", "arena", "max_width",
        "max_depth",
    ),
)
def _sharded_fast_run(
    g, q_ns, q_obj, q_rel, q_subj, q_depth, act, assign, *,
    mesh: Mesh, axis, n, cap, frontier, arena, max_width, max_depth
):
    # module-level jit: the per-call closure this replaces produced a new
    # function object each dispatch, retracing + recompiling the sharded
    # program on every wave — the root cause of the mesh engine's
    # always-cold serving behavior noted in PR 8
    def local(g, q_ns, q_obj, q_rel, q_subj, q_depth, act, assign):
        # P(axis) leaves a leading block dim of 1 on this shard's slice
        g = jax.tree_util.tree_map(lambda a: a[0], g)
        NS, R = g["f_direct_ok"].shape
        me = jax.lax.axis_index(axis)
        # root activation follows the host-provided assignment column —
        # the hash owner by default, a least-loaded replica for hot keys
        mine = assign == me
        s = fp._init_state(
            q_ns, q_obj, q_rel, q_subj, q_depth, act & mine,
            frontier=frontier,
        )
        for _ in range(max_depth):
            children, q_found, q_over, q_dirty = fp.expand_phase(
                g, s, arena=arena, max_width=max_width
            )
            # children always route to their HASH owner (replication
            # copies rows, never moves them, so the owner has them)
            children, q_over = _route(children, n, cap, q_over, axis)
            # merge found bits across shards before packing so arrived
            # children of already-found queries die immediately
            q_found = (
                jax.lax.psum(q_found.astype(jnp.int32), axis) > 0
            )
            # ns_dim/rel_dim unlock the linear hash-scatter dedup — the
            # sort fallback was the dominant per-level cost on shards
            nxt, q_over = fp.pack_phase(
                children, q_found, q_over, frontier=frontier,
                ns_dim=NS, rel_dim=R,
            )
            s = dict(nxt, q_found=q_found, q_over=q_over,
                     q_dirty=q_dirty, q_subj=s["q_subj"])
        q_found = jax.lax.psum(s["q_found"].astype(jnp.int32), axis) > 0
        q_over = jax.lax.psum(s["q_over"].astype(jnp.int32), axis) > 0
        # a dirty hit on ANY shard voids that query's device verdict
        # (unless found: found-bits are overlay-exact and monotone)
        q_dirty = jax.lax.psum(s["q_dirty"].astype(jnp.int32), axis) > 0
        return q_found, q_over, q_dirty

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(
            jax.tree_util.tree_map(lambda _: P(axis), g),
            P(), P(), P(), P(), P(), P(), P(),
        ),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )(g, q_ns, q_obj, q_rel, q_subj, q_depth, act, assign)


def sharded_check(
    stacked_g: Dict[str, np.ndarray],
    queries: Sequence[np.ndarray],
    mesh: Mesh,
    *,
    axis: str = "shard",
    frontier: int = 2048,
    arena: int = 8192,
    max_depth: int = 5,
    max_width: int = 100,
    active=None,
    assign=None,
) -> fp.FastResult:
    """Check a replicated query batch against the sharded graph.

    Queries are visible to every shard; each root item activates only on
    the shard named by its ``assign`` slot (the hash owner when ``assign``
    is None — replica routing passes an explicit column so hot keys can
    activate on a least-loaded replica instead).  Found/overflow bits are
    psum-merged every level so short-circuit masking works across shards.
    """
    n = mesh.devices.size
    q_ns, q_obj, q_rel, q_subj, q_depth = (
        jnp.asarray(a, jnp.int32) for a in queries
    )
    Q = q_ns.shape[0]
    act = (
        jnp.ones((Q,), bool) if active is None else jnp.asarray(active, bool)
    )
    if assign is None:
        assign = shard_of_np(
            np.clip(np.asarray(queries[0], np.int64), 0, None),
            np.clip(np.asarray(queries[1], np.int64), 0, None), n,
        )
    assign = jnp.asarray(assign, jnp.int32)
    cap = max(arena // max(n, 1), 8)

    with compilewatch.scope(
        "sharded_check",
        lambda: f"Q={Q} n={n} frontier={frontier} arena={arena}",
    ):
        found, over, dirty = _sharded_fast_run(
            stacked_g, q_ns, q_obj, q_rel, q_subj, q_depth, act, assign,
            mesh=mesh, axis=axis, n=n, cap=cap,
            frontier=frontier, arena=arena, max_width=max_width,
            max_depth=max_depth,
        )
    return fp.FastResult(found=found, over=over, dirty=dirty)
