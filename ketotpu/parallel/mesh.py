"""Mesh construction and query-data-parallel batch checks.

The graph pytree is replicated to every device, the query batch is split
on the ``data`` mesh axis, and each device runs the fused program on its
own slice (`shard_fast_check` the pure-OR BFS, `shard_general_check` the
AND/NOT algebra program).  No collectives are needed on this axis —
permission checks are independent — so throughput scales linearly over
ICI-connected chips and across DCN hosts alike.  (Graph-sharded
execution, where per-device MEMORY also scales down, lives in
parallel/graphshard.py.)
"""

from __future__ import annotations

import functools
from typing import Dict, Sequence, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ketotpu.engine import fastpath as fp


def make_mesh(
    n_devices: Optional[int] = None, axis: str = "data", devices=None
) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` available devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def shard_fast_check(
    g: Dict[str, jax.Array],
    queries: Sequence[np.ndarray],
    mesh: Mesh,
    *,
    axis: str = "data",
    frontier: int = 2048,
    arena: int = 8192,
    max_depth: int = 5,
    max_width: int = 100,
    active=None,
) -> fp.FastResult:
    """Query-data-parallel BFS fast path: graph replicated, batch sharded.

    Checks are independent, so no collectives run on this axis — throughput
    scales linearly over ICI-connected chips and DCN hosts alike.  The batch
    length must divide by the mesh size (pad with -1 ids).
    """
    n = mesh.devices.size
    q = queries[0].shape[0]
    if q % n:
        raise ValueError(f"batch {q} not divisible by mesh size {n}")
    arrs = tuple(jnp.asarray(a, jnp.int32) for a in queries)
    act = (
        jnp.ones((q,), bool) if active is None else jnp.asarray(active, bool)
    )

    @functools.partial(
        jax.jit, static_argnames=("frontier", "arena", "max_width", "max_depth")
    )
    def run(g, q_ns, q_obj, q_rel, q_subj, q_depth, act, *, frontier, arena,
            max_width, max_depth):
        def local(g, q_ns, q_obj, q_rel, q_subj, q_depth, act):
            s = fp._init_state(
                q_ns, q_obj, q_rel, q_subj, q_depth, act, frontier=frontier
            )
            for _ in range(max_depth):
                s = fp.step_impl(
                    g, s, frontier=frontier, arena=arena, max_width=max_width
                )
            return s["q_found"], s["q_over"]

        return jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(
                jax.tree_util.tree_map(lambda _: P(), g),
                P(axis), P(axis), P(axis), P(axis), P(axis), P(axis),
            ),
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        )(g, q_ns, q_obj, q_rel, q_subj, q_depth, act)

    found, over = run(
        g, *arrs, act,
        frontier=frontier, arena=arena, max_width=max_width, max_depth=max_depth,
    )
    return fp.FastResult(found=found, over=over)


def shard_general_check(
    g: Dict[str, jax.Array],
    qpack: np.ndarray,
    mesh: Mesh,
    *,
    axis: str = "data",
    sizes,
    fast_b: int,
    fast_sched,
    max_width: int = 100,
    vcap: int = 4096,
):
    """Query-data-parallel AND/NOT checks: the fused algebra program
    (engine/algebra.py) under shard_map — graph replicated, the packed
    query block split on the mesh axis, one fused dispatch per device,
    zero collectives (checks are independent).  This is the mesh
    engine's general tier (VERDICT r3 #5: the host oracle is only the
    final fallback now); ``sizes``/``fast_sched`` are per-DEVICE shapes.

    Returns (codes uint8[Q], occ int32[n_devices, L]) — occ rows are
    per-device occupancy vectors (sum them for the engine's EMAs).
    """
    from ketotpu.engine import algebra as alg

    n = mesh.devices.size
    q = qpack.shape[1]
    if q % n:
        raise ValueError(f"batch {q} not divisible by mesh size {n}")

    @functools.partial(
        jax.jit,
        static_argnames=("sizes", "fast_b", "fast_sched", "max_width", "vcap"),
    )
    def run(g, qp, *, sizes, fast_b, fast_sched, max_width, vcap):
        def local(g, qp):
            codes, occ = alg.run_general_packed(
                g, qp, sizes=sizes, fast_b=fast_b, fast_sched=fast_sched,
                max_width=max_width, vcap=vcap,
            )
            return codes, occ[None, :]

        return jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P(), g),
                      P(None, axis)),
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        )(g, qp)

    return run(
        g, jnp.asarray(qpack, jnp.int32),
        sizes=tuple(sizes), fast_b=int(fast_b),
        fast_sched=tuple(fast_sched), max_width=max_width, vcap=vcap,
    )
