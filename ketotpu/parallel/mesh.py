"""Mesh construction and query-data-parallel batch checks.

`shard_batch_check` runs the device interpreter under `jax.shard_map`: the
graph pytree is replicated to every device, the query batch is split on the
``data`` mesh axis, and each device steps the wavefront interpreter on its
own shard (the host loop advances all devices together; a device whose shard
resolved early no-ops until the slowest shard finishes).  No collectives are
needed on this axis — permission checks are independent — so throughput
scales linearly over ICI-connected chips and across DCN hosts alike.
"""

from __future__ import annotations

import functools
from typing import Dict, Sequence, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ketotpu.engine import device as dev
from ketotpu.engine import fastpath as fp


def make_mesh(
    n_devices: Optional[int] = None, axis: str = "data", devices=None
) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` available devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def shard_fast_check(
    g: Dict[str, jax.Array],
    queries: Sequence[np.ndarray],
    mesh: Mesh,
    *,
    axis: str = "data",
    frontier: int = 2048,
    arena: int = 8192,
    max_depth: int = 5,
    max_width: int = 100,
    active=None,
) -> fp.FastResult:
    """Query-data-parallel BFS fast path: graph replicated, batch sharded.

    Checks are independent, so no collectives run on this axis — throughput
    scales linearly over ICI-connected chips and DCN hosts alike.  The batch
    length must divide by the mesh size (pad with -1 ids).
    """
    n = mesh.devices.size
    q = queries[0].shape[0]
    if q % n:
        raise ValueError(f"batch {q} not divisible by mesh size {n}")
    arrs = tuple(jnp.asarray(a, jnp.int32) for a in queries)
    act = (
        jnp.ones((q,), bool) if active is None else jnp.asarray(active, bool)
    )

    @functools.partial(
        jax.jit, static_argnames=("frontier", "arena", "max_width", "max_depth")
    )
    def run(g, q_ns, q_obj, q_rel, q_subj, q_depth, act, *, frontier, arena,
            max_width, max_depth):
        def local(g, q_ns, q_obj, q_rel, q_subj, q_depth, act):
            s = fp._init_state(
                q_ns, q_obj, q_rel, q_subj, q_depth, act, frontier=frontier
            )
            for _ in range(max_depth):
                s = fp.step_impl(
                    g, s, frontier=frontier, arena=arena, max_width=max_width
                )
            return s["q_found"], s["q_over"]

        return jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(
                jax.tree_util.tree_map(lambda _: P(), g),
                P(axis), P(axis), P(axis), P(axis), P(axis), P(axis),
            ),
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        )(g, q_ns, q_obj, q_rel, q_subj, q_depth, act)

    found, over = run(
        g, *arrs, act,
        frontier=frontier, arena=arena, max_width=max_width, max_depth=max_depth,
    )
    return fp.FastResult(found=found, over=over)


def shard_general_check(
    g: Dict[str, jax.Array],
    qpack: np.ndarray,
    mesh: Mesh,
    *,
    axis: str = "data",
    sizes,
    fast_b: int,
    fast_sched,
    max_width: int = 100,
    vcap: int = 4096,
):
    """Query-data-parallel AND/NOT checks: the fused algebra program
    (engine/algebra.py) under shard_map — graph replicated, the packed
    query block split on the mesh axis, one fused dispatch per device,
    zero collectives (checks are independent).  This is the mesh
    engine's general tier (VERDICT r3 #5: the host oracle is only the
    final fallback now); ``sizes``/``fast_sched`` are per-DEVICE shapes.

    Returns (codes uint8[Q], occ int32[n_devices, L]) — occ rows are
    per-device occupancy vectors (sum them for the engine's EMAs).
    """
    from ketotpu.engine import algebra as alg

    n = mesh.devices.size
    q = qpack.shape[1]
    if q % n:
        raise ValueError(f"batch {q} not divisible by mesh size {n}")

    @functools.partial(
        jax.jit,
        static_argnames=("sizes", "fast_b", "fast_sched", "max_width", "vcap"),
    )
    def run(g, qp, *, sizes, fast_b, fast_sched, max_width, vcap):
        def local(g, qp):
            codes, occ = alg.run_general_packed(
                g, qp, sizes=sizes, fast_b=fast_b, fast_sched=fast_sched,
                max_width=max_width, vcap=vcap,
            )
            return codes, occ[None, :]

        return jax.shard_map(
            local,
            mesh=mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P(), g),
                      P(None, axis)),
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        )(g, qp)

    return run(
        g, jnp.asarray(qpack, jnp.int32),
        sizes=tuple(sizes), fast_b=int(fast_b),
        fast_sched=tuple(fast_sched), max_width=max_width, vcap=vcap,
    )


def _lift(s: Dict) -> Dict:
    """Scalars -> [1] arrays so per-device values concatenate on 'data'."""
    s = dict(s)
    for k in ("cursor", "flags"):
        s[k] = s[k][None]
    return s


def _unlift(s: Dict) -> Dict:
    s = dict(s)
    for k in ("cursor", "flags"):
        s[k] = s[k][0]
    return s


def _specs(q: int):
    """PartitionSpecs for a lifted state pytree."""
    return dict(
        T={
            k: P("data")
            for k in (
                "state result qid kind ns obj rel depth skip vscope parent "
                "prog cop nchild ndone nis nnot nerr delivered neg"
            ).split()
        },
        vset=(P("data"),) * 4,
        cursor=P("data"),
        q_over=P("data"),
        q_subj=P("data"),
        flags=P("data"),
    )


@functools.partial(
    jax.jit, static_argnames=("mesh", "cap", "vcap")
)
def _sharded_init(queries, *, mesh: Mesh, cap: int, vcap: int):
    def local_init(q_ns, q_obj, q_rel, q_subj, q_depth):
        return _lift(
            dev.init_state(q_ns, q_obj, q_rel, q_subj, q_depth, cap=cap, vcap=vcap)
        )

    return jax.shard_map(
        local_init,
        mesh=mesh,
        in_specs=(P("data"),) * 5,
        out_specs=_specs(queries[0].shape[0]),
        check_vma=False,
    )(*queries)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "cap", "arena", "vcap", "max_width", "strict"),
)
def _sharded_step(
    g, s, *, mesh: Mesh, cap: int, arena: int, vcap: int,
    max_width: int, strict: bool,
):
    def local_step(g, s):
        return _lift(
            dev.check_step(
                g, _unlift(s),
                cap=cap, arena=arena, vcap=vcap,
                max_width=max_width, strict=strict,
            )
        )

    specs = _specs(0)
    return jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), g), specs),
        out_specs=specs,
        check_vma=False,
    )(g, s)


def shard_batch_check(
    g: Dict[str, jax.Array],
    queries: Sequence[np.ndarray],
    mesh: Mesh,
    *,
    cap: int = 8192,
    arena: int = 8192,
    vcap: int = 4096,
    max_iters: int = 64,
    max_width: int = 100,
    strict: bool = False,
) -> dev.RunResult:
    """Run a query batch data-parallel over the mesh.

    ``queries`` is the encoded tuple ``(q_ns, q_obj, q_rel, q_subj, q_depth)``;
    the batch length must divide evenly by the mesh size (pad with -1 ids).
    """
    n = mesh.devices.size
    q = queries[0].shape[0]
    if q % n:
        raise ValueError(f"batch {q} not divisible by mesh size {n}")
    queries = tuple(jnp.asarray(a, jnp.int32) for a in queries)
    s = _sharded_init(queries, mesh=mesh, cap=cap, vcap=vcap)
    it = 0
    for it in range(1, max_iters + 1):
        s = _sharded_step(
            g, s, mesh=mesh, cap=cap, arena=arena, vcap=vcap,
            max_width=max_width, strict=strict,
        )
        flags = np.asarray(s["flags"])
        done = (flags & dev.F_ALL_ROOTS_DONE) != 0
        stuck = (flags & (dev.F_PENDING | dev.F_CHANGED)) == 0
        if bool(np.all(done | stuck)):
            break
    # collect per-query verdicts from the sharded root slots
    q_local = q // n

    def local_collect(s):
        T = _unlift(s)["T"]
        root_state = T["state"][:q_local]
        return (
            jnp.where(root_state != dev.S_DONE, dev.R_UNKNOWN, T["result"][:q_local]),
            s["q_over"] | (root_state != dev.S_DONE),
            s["cursor"],
        )

    result, overflow, tasks = jax.jit(
        jax.shard_map(
            local_collect,
            mesh=mesh,
            in_specs=(_specs(0),),
            out_specs=(P("data"), P("data"), P("data")),
            check_vma=False,
        )
    )(s)
    return dev.RunResult(
        result=result,
        overflow=overflow,
        iters=jnp.int32(it),
        tasks=jnp.sum(tasks),
    )
