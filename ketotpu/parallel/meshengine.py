"""MeshCheckEngine: the serving engine over a graph-sharded device mesh.

BASELINE config #5 behind the registry's engine seam: with
``engine.mesh_devices: n`` the registry builds this engine instead of the
single-device one.  The CSR is partitioned by (namespace, object) hash
across an n-device `jax.sharding.Mesh` (parallel/graphshard.py); each BFS
level expands locally, routes cross-shard subject-set / tuple-to-userset
children to their owner shard with `lax.all_to_all`, and merges verdict
bits with `psum` — per-device graph memory drops with mesh size instead
of replicating.

Inherits the single-device engine's whole host surface (encode, classify,
oracle fallback, expand, checkpointing of the base projection) and swaps
only the fast-path dispatch.  Differences forced by sharding:

* the delta overlay is disabled (``max_overlay_pairs = 0``): overlay
  tables are built for the replicated layout, so every write amortizes
  through a full rebuild instead — writes are the rare path at the scale
  a mesh serves (SURVEY §7 step 8's snapshot-oriented design);
* AND/NOT-reachable ("general") queries go straight to the host oracle —
  the task-tree interpreter is single-device;
* the overflow tail falls back to the oracle without a device retry tier
  (capacity on a mesh is per-shard; a retry would need a second stacked
  projection at wider caps for a few queries).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ketotpu.engine.tpu import DeviceCheckEngine, _bucket
from ketotpu.parallel import graphshard
from ketotpu.parallel.mesh import make_mesh


class MeshCheckEngine(DeviceCheckEngine):
    """Graph-sharded batched checks; oracle fallback on the host."""

    def __init__(
        self,
        store,
        namespace_manager=None,
        *,
        mesh_devices: int,
        mesh_axis: str = "shard",
        **kwargs,
    ):
        super().__init__(store, namespace_manager, **kwargs)
        self.mesh = make_mesh(mesh_devices, axis=mesh_axis)
        if self.mesh.devices.size != mesh_devices:
            # make_mesh silently truncates to what exists; serving with
            # fewer devices than shards would DROP the missing shards'
            # tuples as silent denials
            raise ValueError(
                f"engine.mesh_devices={mesh_devices} but only "
                f"{self.mesh.devices.size} JAX devices are available"
            )
        self.mesh_axis = mesh_axis
        self.n_shards = mesh_devices
        self._stacked = None
        # overlay tables target the replicated layout; sharded serving
        # amortizes writes through full rebuilds instead
        self.max_overlay_pairs = 0
        self.max_overlay_dirty = 0

    def _install_device_arrays(self) -> None:
        """Ship the SHARDED stacks; the replicated copy (only batch_expand
        reads it) is built lazily so device 0 doesn't hold the whole graph
        next to its shard."""
        self._base_device = None
        self._device_arrays = None
        _, self._stacked = graphshard.build_sharded_snapshot(
            self.store, self.namespace_manager, self.n_shards, self._vocab
        )

    def _expand_arrays(self):
        if self._device_arrays is None:
            import jax

            from ketotpu.engine import delta as dl

            self._base_device = jax.device_put(self._snap.arrays())
            self._device_arrays = dict(
                self._base_device,
                **jax.device_put(
                    dl.overlay_arrays(
                        self._overlay, self._snap,
                        pair_cap=self.max_overlay_pairs,
                    )
                ),
            )
        return self._device_arrays

    def _dispatch(self, queries, rest_depth: int):
        n = len(queries)
        if n == 0:
            return None
        with self._sync_lock:
            snap = self._snapshot_locked()
            stacked = self._stacked
        enc = self._encode(snap, queries, rest_depth)
        err, general = self._classify(snap, enc[0], enc[2])
        qpad = min(_bucket(n), self.frontier)
        padded = self._pad(enc, n, qpad)
        active = np.pad(~(err | general), (0, qpad - n))
        res = graphshard.sharded_check(
            stacked,
            padded,
            self.mesh,
            axis=self.mesh_axis,
            frontier=self.frontier,
            arena=self.arena,
            max_depth=self.max_depth,
            max_width=self.max_width,
            active=active,
        )
        # general queries are oracle work on this engine (see module doc)
        return (enc, err | general, res)

    def _collect(self, handle, retry: bool = True):
        enc, fallback_mask, res = handle
        n = fallback_mask.shape[0]
        allowed = np.zeros(n, bool)
        fallback = fallback_mask.copy()
        found = np.asarray(res.found)[:n]
        over = np.asarray(res.over)[:n]
        fmask = ~fallback_mask
        allowed[fmask] = found[fmask]
        # found is monotone: overflow voids only not-yet-found queries
        fallback |= fmask & over & ~found
        return allowed, fallback

