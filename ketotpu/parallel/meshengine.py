"""MeshCheckEngine: the serving engine over a graph-sharded device mesh.

BASELINE config #5 behind the registry's engine seam: with
``engine.mesh_devices: n`` the registry builds this engine instead of the
single-device one.  The CSR is partitioned by (namespace, object) hash
across an n-device `jax.sharding.Mesh` (parallel/graphshard.py); each BFS
level expands locally, routes cross-shard subject-set / tuple-to-userset
children to their owner shard with `lax.all_to_all`, and merges verdict
bits with `psum` — per-device graph memory drops with mesh size instead
of replicating.

Inherits the single-device engine's whole host surface (encode, classify,
oracle fallback, expand, checkpointing of the base projection) and swaps
the fast-path dispatch.  Sharded differences:

* **writes ride per-shard delta overlays**: each change routes to its
  owner shard (same (ns, obj) hash as the partitioning) and folds into
  that shard's OverlayState against that shard's snapshot — node ids in
  overlay tables are shard-local, so one replicated overlay cannot work.
  EMPTY overlay tables ship with the base stacks so the shard_map
  program's pytree never changes shape when writes land; a write
  re-ships only the (small, fixed-shape) overlay stacks.  Probe verdicts
  stay overlay-exact; queries that touch a dirty CSR row on ANY shard
  come back ``dirty`` (psum-merged) and fall back to the host oracle.
* **overflow retries on-device** at ``retry_scale``x frontier/arena
  before falling back — same two-tier story as the single-chip engine.
* AND/NOT-reachable ("general") queries run the fused algebra program
  (engine/algebra.py) **against the sharded graph itself**
  (graphshard.sharded_general_check): every per-task read is owner-local
  under the (ns, obj) partitioning, classification merges ride psums,
  and pure-OR fast leaves take the same all_to_all-routed BFS as the
  fast path — per-device graph memory keeps scaling down with mesh
  size, and the tier is overlay-aware (per-shard dirty bits psum-merge).
  The host oracle is only the final fallback (overflow, errors, dirty
  rows).  A budget-bounded replicated copy remains ONLY for
  batch_expand, whose host-side tree reassembly reads global node ids.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ketotpu import compilewatch, deadline, faults, flightrec
from ketotpu.cache.hotspot import HotSpotSketch
from ketotpu.engine import delta as dl
from ketotpu.engine.optable import R_ERR, R_IS
from ketotpu.engine.tpu import DeviceCheckEngine, _bucket, _bucket15
from ketotpu.parallel import graphshard, peerlink
from ketotpu.parallel.mesh import make_mesh

#: collectives over the host's ONE device backend cannot overlap even
#: across ENGINE INSTANCES (two in-process mesh engines — the multi-host
#: parity tests' topology — share the same CPU/TPU backend, and two
#: in-flight sharded programs interleave their all_to_all rendezvous and
#: starve each other), so the run lock is process-global, not per-engine
_MESH_RUN_LOCK = threading.Lock()

#: set while THIS thread is serving a peer's forwarded rows: the mesh
#: engine must answer those locally — re-forwarding a replica-routed row
#: to its hash owner would bounce between hosts forever
_LOCAL_SERVE = threading.local()

#: separator for the string-keyed cross-host root key (vocab ids are
#: per-process; only the strings mean the same thing on every host)
_KEY_SEP = "\x1f"


def _pack_keys(ns_ids: np.ndarray, obj_ids: np.ndarray) -> np.ndarray:
    """(ns, obj) id pairs packed into one int64 key (vectorized compare)."""
    return (
        np.clip(np.asarray(ns_ids, np.int64), 0, None) << 32
    ) | (np.clip(np.asarray(obj_ids, np.int64), 0, None) & 0xFFFFFFFF)


class MeshCheckEngine(DeviceCheckEngine):
    """Graph-sharded batched checks; oracle fallback on the host."""

    # sharded stacks have their own publish discipline: writes route to
    # per-shard overlays and the escape hatch stays the sharded rebuild —
    # no base-engine fold or background generation swap
    supports_fold = False
    supports_background_compaction = False

    def __init__(
        self,
        store,
        namespace_manager=None,
        *,
        mesh_devices: int,
        mesh_axis: str = "shard",
        replica_budget_mb: int = 8192,
        replicate_hot: bool = True,
        hot_min: int = 64,
        replica_max_keys: int = 32,
        rebalance_skew: float = 4.0,
        rebalance_interval_ms: float = 0.0,
        failover: bool = True,
        hostlink=None,
        **kwargs,
    ):
        super().__init__(store, namespace_manager, **kwargs)
        # the mesh overrides _dispatch/_collect wholesale (per-shard
        # routing, all_to_all collectives); the single-program fused wave
        # does not apply here, whatever the shared config says
        self.fused_dispatch = False
        self.mesh = make_mesh(mesh_devices, axis=mesh_axis)
        if self.mesh.devices.size != mesh_devices:
            # make_mesh silently truncates to what exists; serving with
            # fewer devices than shards would DROP the missing shards'
            # tuples as silent denials
            raise ValueError(
                f"engine.mesh_devices={mesh_devices} but only "
                f"{self.mesh.devices.size} JAX devices are available"
            )
        self.mesh_axis = mesh_axis
        self.n_shards = mesh_devices
        self._stacked = None
        self._stacked_base = None
        self._shard_snaps: Optional[List] = None
        self._shard_overlays: Optional[List[dl.OverlayState]] = None
        # ceiling on the lazily-replicated full-graph copy that ONLY
        # batch_expand still uses (its host-side tree reassembly reads
        # global node ids): past this budget expand falls back to the
        # host oracle instead of silently materializing the whole graph
        # on one device.  The general (AND/NOT) tier runs against the
        # sharded stacks and never touches this.
        self.replica_budget_bytes = replica_budget_mb << 20
        # per-shard overlay table capacity; totals still bound by
        # max_overlay_pairs/max_overlay_dirty like the single-chip engine
        self.shard_pair_cap = max(self.max_overlay_pairs // mesh_devices, 256)
        # per-shard serving telemetry (shard_stats / registry gauges):
        # oracle fallbacks attributed to the query's owner shard, and the
        # last general dispatch's per-shard BFS occupancy partials
        self._shard_fallbacks = np.zeros(mesh_devices, np.int64)
        self._shard_gen_occ = np.zeros(mesh_devices)
        # per-shard Leopard closure segments (pair counts by owner set)
        self._leo_shard_pairs = np.zeros(mesh_devices, np.int64)
        self._leo_segments = None
        # -- production serving state (hot replication / rebalance /
        # failover) ----------------------------------------------------
        self.replicate_hot = bool(replicate_hot)
        self.hot_min = int(hot_min)
        self.replica_max_keys = int(replica_max_keys)
        self.rebalance_skew = float(rebalance_skew)
        self.rebalance_interval_ms = float(rebalance_interval_ms)
        self.failover_enabled = bool(failover)
        # count-min sketch over root (ns, obj) keys: the replication
        # controller's hot-key feed (same sketch the cache shield uses)
        self._hot = HotSpotSketch(top_k=max(self.replica_max_keys, 16))
        # (ns_id, obj_id) -> extra shards holding a COPY of the key's
        # rows; published only via the generation-swap in
        # _publish_replica_map, read lock-free on the dispatch path
        self._replica_map: Dict[Tuple[int, int], Tuple[int, ...]] = {}
        # per-shard routed-root counts: the skew signal, the least-loaded
        # replica choice, and the per-shard wave accounting feed
        self._shard_batches = np.zeros(mesh_devices, np.int64)
        self._shard_down = np.zeros(mesh_devices, bool)
        self.replica_routed = 0
        self.replications = 0
        self.rebalances = 0
        self.shard_recoveries = 0
        # collectives over ONE mesh cannot overlap: two in-flight
        # executions of the sharded program interleave their all_to_all
        # rendezvous on the host backend and starve each other, so every
        # device launch (and the shared routing counters) serializes on
        # the process-global run lock (see _MESH_RUN_LOCK)
        self._mesh_run_lock = _MESH_RUN_LOCK
        # -- multi-host topology (parallel/peerlink.py) ------------------
        # the host coordinate partitions SERVING RESPONSIBILITY for root
        # keys, not device memory: every host builds the full sharded
        # graph from the shared store, so any host's verdict for any key
        # is bit-identical — cross-host routing is a throughput/failover
        # decision, never a correctness one
        self.hostlink = hostlink
        self.host_id = hostlink.host_id if hostlink is not None else 0
        self.n_hosts = hostlink.n_hosts if hostlink is not None else 1
        # string-keyed hot sketch for MY owned roots: the cross-host
        # replication controller's feed (the shard-level sketch above
        # keys by per-process vocab ids, useless across hosts)
        self._peer_hot = HotSpotSketch(top_k=max(self.replica_max_keys, 16))
        # key -> remote hosts holding a SERVE-COPY: merged from every
        # owner's heartbeat-published plan plus my own; replaced
        # wholesale (atomic rebind), read lock-free on the dispatch path
        self._peer_replicas: Dict[str, Tuple[int, ...]] = {}
        self._peer_plans: Dict[int, Dict[str, Tuple[int, ...]]] = {}
        self._my_peer_plan: Dict[str, Tuple[int, ...]] = {}
        self._peer_batches = np.zeros(max(self.n_hosts, 1), np.int64)
        self._peer_fallbacks = np.zeros(max(self.n_hosts, 1), np.int64)
        self.peer_deadline_degrades = 0
        self.peer_host_down_events = 0
        self.peer_recover_events = 0
        if hostlink is not None:
            hostlink.attach_engine(self)
        self._rebal_stop = threading.Event()
        self._rebal_thread: Optional[threading.Thread] = None
        if self.rebalance_interval_ms > 0 and mesh_devices > 1:
            t = threading.Thread(
                target=self._rebal_worker, name="keto-mesh-rebalancer",
                daemon=True,
            )
            self._rebal_thread = t
            t.start()

    def _install_leopard(self) -> None:
        """Build the closure index, then partition its element pairs into
        per-shard segments by the OWNER SET's (ns, obj) hash — the same
        partitioning as the CSR, so a shard's segment answers exactly the
        queries whose object node it owns.  The segments replace the
        single replicated device copy: each holds only its shard's slice
        of the sorted pairs (sorting is preserved — the global order is
        by packed (set, element) key, and a subsequence of a sorted array
        is sorted), so per-device closure memory scales down with mesh
        size just like the graph itself."""
        super()._install_leopard()
        # the segments stand in for the replicated HBM copy; probes on the
        # mesh engine take the host searchsorted path (bit-identical)
        self._leo_device = None
        self._leo_segments = None
        self._leo_shard_pairs = np.zeros(self.n_shards, np.int64)
        idx = self._leopard
        if idx is None or len(idx.elt_set) == 0:
            return
        hi = idx.nodes[idx.elt_set.astype(np.int64)] >> 32
        ns = (hi // idx.R).astype(np.int64)
        obj = (idx.nodes[idx.elt_set.astype(np.int64)] & 0xFFFFFFFF)
        shards = graphshard.shard_of_np(ns, obj, self.n_shards)
        self._leo_shard_pairs = np.bincount(
            shards, minlength=self.n_shards
        ).astype(np.int64)
        self._leo_segments = [
            idx.elt_packed[shards == s] for s in range(self.n_shards)
        ]

    def _install_device_arrays(self) -> None:
        """Ship the SHARDED stacks (base + EMPTY overlays); the replicated
        copy (only batch_expand reads it) is built lazily so device 0
        doesn't hold the whole graph next to its shard."""
        self._base_device = None
        self._device_arrays = None
        self._expand_extra = None
        self._shard_snaps, self._stacked_base = (
            graphshard.build_sharded_snapshot(
                self.store, self.namespace_manager, self.n_shards,
                self._vocab, cols=self._cols,
                replicate=self._replica_map,
            )
        )
        # overlay admission checks relation-level pairs against dyn_pairs;
        # a shard's own slice sees only a subset of the graph's pairs, so
        # a write whose pair lives on other shards would spuriously
        # reject -> full reshard.  Taint classification runs on the
        # replicated snapshot anyway, so sharing the GLOBAL pair set is
        # exact and strictly reduces resharding.
        if self._snap is not None:
            for sn in self._shard_snaps:
                sn.dyn_pairs = self._snap.dyn_pairs
        self._shard_overlays = [
            dl.OverlayState() for _ in range(self.n_shards)
        ]
        self._stacked = dict(
            self._stacked_base, **self._overlay_stacks()
        )

    def _swap_shape_signature(self):
        """The mesh serves from the sharded STACKS — sign those across a
        generation swap, not the lazily-built replicated expand copy
        (which a rebuild nulls and would read as always-changed)."""
        return self._array_shapes(self._stacked)

    def _overlay_stacks(self):
        """Per-shard overlay arrays, padded to common shapes and stacked
        (leading axis = shard).  Fixed shapes per rebuild: om_/ovt_ tables
        by ``shard_pair_cap``, ov_dirty by the max shard node count."""
        ovs = [
            dl.overlay_arrays(o, sn, pair_cap=self.shard_pair_cap)
            for o, sn in zip(self._shard_overlays, self._shard_snaps)
        ]
        out = {}
        for k in ovs[0]:
            arrs = [np.asarray(ov[k]) for ov in ovs]
            if arrs[0].ndim == 0:
                out[k] = np.stack(arrs)
                continue
            m = max(a.shape[0] for a in arrs)
            m = _bucket(m, 64) if k == "ov_dirty" else m
            arrs = [
                np.pad(a, [(0, m - a.shape[0])] + [(0, 0)] * (a.ndim - 1))
                for a in arrs
            ]
            out[k] = np.stack(arrs)
        return out

    def _overlay_apply(self, changes) -> bool:
        """Route each change to its owner shard's overlay (the same
        (ns, obj) hash that partitioned the graph) and re-ship only the
        overlay stacks.  False => full rebuild (re-partition).

        The replicated overlay state (self._overlay) is mirrored first:
        batch_expand's host-side delta merge reads it against the
        replicated snapshot (expand_device.OverlayMembers), and its node
        ids are replicated-snapshot ids — the shard overlays' ids are
        shard-local and useless to expand."""
        if self._shard_snaps is None:
            return False
        try:
            dl.apply_changes(self._overlay, self._snap, self._vocab, changes)
        except dl.OverlayRejected:
            return False
        try:
            for op_, t in changes:
                ns = self._vocab.namespaces.lookup(t.namespace)
                obj = self._vocab.objects.lookup(t.object)
                if ns < 0 or obj < 0:
                    return False  # ids not even interned: rebuild
                s = int(graphshard.shard_of_np(
                    np.array([ns]), np.array([obj]), self.n_shards
                )[0])
                # replicated keys fold the change into EVERY copy's
                # overlay too — a replica serving the key's roots must
                # see the same write-visible verdicts as the hash owner
                targets = {s}
                targets.update(
                    self._replica_map.get((int(ns), int(obj)), ())
                )
                for tgt in targets:
                    dl.apply_changes(
                        self._shard_overlays[tgt], self._shard_snaps[tgt],
                        self._vocab, [(op_, t)],
                    )
        except dl.OverlayRejected:
            return False
        pairs = sum(o.size()[0] for o in self._shard_overlays)
        dirty = sum(o.size()[1] for o in self._shard_overlays)
        if pairs > self.max_overlay_pairs or dirty > self.max_overlay_dirty:
            return False
        if any(
            o.size()[0] > self.shard_pair_cap for o in self._shard_overlays
        ):
            return False  # one shard's fixed-shape table would overflow
        try:
            stacks = self._overlay_stacks()
        except ValueError:
            return False
        self._stacked = dict(self._stacked_base, **stacks)
        return True

    def _replica_arrays(self):
        """Bounded lazily-replicated Check arrays (+ overlay tables) for
        batch_expand only, or None when the full graph would exceed
        ``replica_budget_bytes`` — expand falls back to the oracle then."""
        if self._device_arrays is None:
            import jax

            est = sum(
                v.nbytes for v in self._snap.check_arrays().values()
            )
            if est > self.replica_budget_bytes:
                return None
            self._base_device = jax.device_put(self._snap.check_arrays())
            self._device_arrays = dict(
                self._base_device,
                **jax.device_put(
                    dl.overlay_arrays(
                        self._overlay, self._snap,
                        pair_cap=max(self.max_overlay_pairs, 1),
                    )
                ),
            )
        return self._device_arrays

    def _expand_arrays(self):
        if self._replica_arrays() is None:
            return None  # over budget: batch_expand goes to the oracle
        # the expand-only tables extend the bounded replica lazily,
        # exactly like the single-chip engine
        return super()._expand_arrays()

    def _sharded_run(self, stacked, padded, active, boost: int = 1,
                     assign=None):
        import jax

        # collectives over one mesh must not overlap: launch AND finish
        # under the run lock (two in-flight sharded programs interleave
        # their all_to_all rendezvous on the host backend and starve)
        with self._mesh_run_lock:
            res = graphshard.sharded_check(
                stacked,
                padded,
                self.mesh,
                axis=self.mesh_axis,
                frontier=boost * self.frontier,
                arena=boost * self.arena,
                max_depth=self.max_depth,
                max_width=self.max_width,
                active=active,
                assign=assign,
            )
            jax.block_until_ready(res)
        return res

    def _run_general_mesh(self, stacked, enc, gi, boost: int = 1):
        """One fused algebra dispatch over the SHARDED graph stacks for
        the general (AND/NOT) roots (graphshard.sharded_general_check,
        VERDICT r4 #5): no replicated graph copy — per-device graph
        memory keeps scaling down with mesh size; only the per-batch
        skeleton working set is replicated.  Overlay-aware like the
        single-chip program: each shard's slice carries its own overlay
        tables, probes run owner-side, and dirty bits psum-merge.
        Returns (codes, occ_rows, n, fast_b)."""
        n = len(gi)
        qpad = min(_bucket15(max(n, 256), 256), self.max_batch)
        genc = self._pad(tuple(a[gi] for a in enc), n, qpad)
        active = np.arange(qpad) < n
        qpack = np.stack([*genc, active.astype(np.int32)]).astype(np.int32)
        # GLOBAL shapes: the whole batch's skeleton lives on every shard
        sizes, fast_b, fast_sched, vcap = self._gen_schedule(qpad, boost)
        import jax

        with self._mesh_run_lock:  # see _sharded_run: collectives serialize
            codes, occ = graphshard.sharded_general_check(
                stacked, qpack, self.mesh, axis=self.mesh_axis,
                sizes=sizes, fast_b=fast_b, fast_sched=fast_sched,
                max_width=self.max_width, vcap=vcap,
            )
            jax.block_until_ready((codes, occ))
        return codes, occ, n, fast_b

    # -- routing / failover -------------------------------------------------

    def _route_assign(self, ns_ids, obj_ids):
        """Per-root serving-shard assignment.  Defaults to the (ns, obj)
        hash owner; roots of replicated hot keys go to the least-loaded
        live copy instead.  Returns (assign, owner) int32 arrays — owner
        is the hash shard (what child routing and fallback attribution
        use), assign is where the root actually activates."""
        n = self.n_shards
        ns = np.clip(np.asarray(ns_ids, np.int64), 0, None)
        obj = np.clip(np.asarray(obj_ids, np.int64), 0, None)
        owner = graphshard.shard_of_np(ns, obj, n)
        assign = owner.copy()
        rep = self._replica_map
        if rep:
            packed = _pack_keys(ns, obj)
            load = self._shard_batches.astype(np.int64)
            for (kns, kobj), extras in rep.items():
                key = (np.int64(kns) << 32) | (
                    np.int64(kobj) & 0xFFFFFFFF
                )
                m = packed == key
                if not m.any():
                    continue
                kowner = int(graphshard.shard_of_np(
                    np.array([kns]), np.array([kobj]), n
                )[0])
                cands = [
                    s for s in dict.fromkeys((kowner, *extras))
                    if not self._shard_down[s]
                ]
                if not cands:
                    continue  # every copy down: stays owner -> oracle
                best = min(cands, key=lambda s: int(load[s]))
                if best != kowner:
                    self.replica_routed += int(m.sum())
                assign[m] = best
        return assign, owner

    def _poll_shard_faults(self) -> None:
        """Advance per-shard up/down state from the fault plan: a rolled
        shard fault marks the shard down (it degrades to replicas / the
        host oracle — the wave keeps serving); a shard the plan stopped
        targeting recovers on the next dispatch."""
        if not self.failover_enabled:
            return
        for s in range(self.n_shards):
            if self._shard_down[s]:
                if not faults.shard_faulted(s):
                    self._recover_shard(s)
            elif faults.shard_down(s):
                self._shard_down[s] = True
                self._device_failure()

    def _recover_shard(self, s: int) -> None:
        """Bring a faulted shard back: re-ship its segments (the whole
        stacked view refreshes — the per-shard slices are one device_put
        away) and zero its fallback attribution so recovery is observable
        as `keto_mesh_shard_fallbacks{shard=s}` returning to zero."""
        with self._sync_lock:
            if not self._shard_down[s]:
                return
            self._shard_down[s] = False
            if self._stacked_base is not None:
                self._stacked = dict(
                    self._stacked_base, **self._overlay_stacks()
                )
            self._shard_fallbacks[s] = 0
            self.shard_recoveries += 1

    # -- cross-host routing / serving (parallel/peerlink.py) ----------------

    @staticmethod
    def _query_key_cols(queries):
        """(namespace, object) STRING columns for a wave — the cross-host
        coordinate hashes strings, never per-process vocab ids."""
        if hasattr(queries, "encode_for"):
            return queries.ns, queries.obj
        return (
            [q.namespace for q in queries],
            [q.object for q in queries],
        )

    def _route_hosts(self, queries, cand_mask, rest_depth: int):
        """Split a wave by serving host.  Each row's serve-set is its
        owner host plus any heartbeat-published replica hosts; the
        least-loaded LIVE member serves it.  Rows landing on a peer batch
        into one framed round trip per peer (fired here, joined in
        _collect); rows with every copy down — and every cross-host row
        of a wave whose deadline budget is already spent — degrade to the
        oracle instead of blocking the wave."""
        cand = np.flatnonzero(cand_mask)
        if not len(cand):
            return None
        link = self.hostlink
        n = cand_mask.shape[0]
        ns_s, obj_s = self._query_key_cols(queries)
        owner_host = np.fromiter(
            (
                peerlink.host_of(ns_s[i], obj_s[i], self.n_hosts)
                for i in cand
            ),
            np.int32, count=len(cand),
        )
        rep = self._peer_replicas
        if self.replicate_hot:
            mine = cand[owner_host == self.host_id]
            if len(mine):
                self._peer_hot.observe_many(
                    [ns_s[i] + _KEY_SEP + obj_s[i] for i in mine]
                )
        loads = {
            h: (
                float(self._shard_batches.sum()) if h == self.host_id
                else link.peer_load(h)
            )
            for h in range(self.n_hosts)
        }
        downs = {
            h: (False if h == self.host_id else link.peer_down(h))
            for h in range(self.n_hosts)
        }
        sent = np.zeros(n, bool)
        lost = np.zeros(n, bool)
        send: Dict[int, list] = {}
        for pos in range(len(cand)):
            i = int(cand[pos])
            own = int(owner_host[pos])
            extras = rep.get(ns_s[i] + _KEY_SEP + obj_s[i]) if rep else None
            if own == self.host_id and not extras:
                continue  # the common case: I own it, nobody else serves it
            live = [
                h for h in dict.fromkeys((own, *(extras or ())))
                if not downs.get(h, True)
            ]
            if not live:
                # whole serve-set down: this row rides the existing
                # err-mask to the host oracle, attributed to the owner
                lost[i] = True
                self._peer_fallbacks[own] += 1
                continue
            serve = min(live, key=lambda h: loads[h])
            if serve == self.host_id:
                continue
            send.setdefault(serve, []).append(i)
            sent[i] = True
        if not sent.any() and not lost.any():
            return None
        rem = deadline.remaining()
        if rem is not None and rem <= 0 and sent.any():
            # budget already spent: shipping would only return expired —
            # degrade this wave's cross-host rows to the oracle now
            self.peer_deadline_degrades += int(sent.sum())
            for hid, idx in send.items():
                self._peer_fallbacks[hid] += len(idx)
            lost |= sent
            sent = np.zeros(n, bool)
            send = {}
        timeout_s = link.rpc_timeout_s if rem is None else min(
            rem, link.rpc_timeout_s
        )
        pend = {}
        for hid, idx in send.items():
            rows = [queries[i] for i in idx]
            pend[hid] = (
                np.asarray(idx, np.int64),
                link.check_rows_async(hid, rows, rest_depth, timeout_s),
                timeout_s,
            )
            self._peer_batches[hid] += len(idx)
        return {"sent": sent, "lost": lost, "pend": pend}

    def _peer_serve_check(self, rows, rest_depth: int) -> np.ndarray:
        """Answer a peer's forwarded rows from the LOCAL cascade.  The
        local-serve scope pins the whole sub-wave to this host: a
        replica-routed row re-hashed here would forward straight back to
        its owner and bounce forever."""
        prev = getattr(_LOCAL_SERVE, "serving", False)
        _LOCAL_SERVE.serving = True
        try:
            return np.asarray(
                self.batch_check(rows, rest_depth=rest_depth), bool
            )
        finally:
            _LOCAL_SERVE.serving = prev

    def _hb_payload(self) -> dict:
        """What this host publishes on every heartbeat: its load (the
        peers' least-loaded-copy routing signal), shard count, drained
        cursor, and its hot-key replica plan — the consensus-free
        controller's whole protocol rides the heartbeat."""
        plan = self.plan_peer_replicas() if self.replicate_hot else {}
        return {
            "load": float(self._shard_batches.sum()),
            "shards": int(self.n_shards),
            "cursor": int(self._log_cursor),
            "replicas": {k: list(v) for k, v in plan.items()},
        }

    def plan_peer_replicas(self) -> Dict[str, Tuple[int, ...]]:
        """The cross-host replica plan for MY owned hot keys: existing
        placements stick (stability), new hot keys get one copy on the
        least-loaded live remote host.  Copy-never-move like the shard
        controller: every host serves from its own full graph, so a
        serve-copy is a routing fact, not a data move — verdicts stay
        bit-identical wherever a row lands."""
        link = self.hostlink
        if link is None or self.n_hosts < 2:
            return {}
        remote = [h for h in link.live_hosts() if h != self.host_id]
        out: Dict[str, Tuple[int, ...]] = {}
        if remote:
            for key, est in self._peer_hot.top():
                if est < self.hot_min or not isinstance(key, str):
                    continue
                if len(out) >= self.replica_max_keys:
                    break
                kept = tuple(
                    h for h in self._my_peer_plan.get(key, ())
                    if h in remote
                )
                out[key] = kept or (
                    min(remote, key=lambda h: link.peer_load(h)),
                )
        self._my_peer_plan = out
        self._rebuild_peer_replicas()
        return out

    def _merge_peer_replicas(self, hid: int, mapping) -> None:
        """Absorb a peer's heartbeat-published replica plan."""
        self._peer_plans[int(hid)] = {
            str(k): tuple(int(h) for h in v)
            for k, v in (mapping or {}).items()
        }
        self._rebuild_peer_replicas()

    def _rebuild_peer_replicas(self) -> None:
        merged: Dict[str, Tuple[int, ...]] = {}
        for plan in (*self._peer_plans.values(), self._my_peer_plan):
            for k, hosts in plan.items():
                merged[k] = tuple(
                    dict.fromkeys(merged.get(k, ()) + tuple(hosts))
                )
        self._peer_replicas = merged  # atomic rebind: lock-free readers

    def _on_peer_down(self, hid: int) -> None:
        """Heartbeat loss marked a whole peer down: every shard it owns
        is down at once.  Routing reads liveness from the hostlink on
        every wave, so there is nothing to re-ship — the next wave's fast
        roots already reroute to live replicas and the rest degrades to
        the oracle via the err-mask."""
        self.peer_host_down_events += 1

    def _on_peer_up(self, hid: int) -> None:
        """A peer answered again after being down: its owned keys route
        back to it on the next wave (warm rejoin — the peer re-ships its
        own stacks from the shared store before answering)."""
        self.peer_recover_events += 1

    def peer_route_counts(self) -> np.ndarray:
        """Cumulative rows shipped per peer host (the coalescer diffs
        consecutive reads for the wave ledger's per-peer accounting)."""
        return self._peer_batches.copy()

    def mesh_bootstrap(self, hid: int) -> None:
        """Warm-join via segment ship: adopt the peer's projected base
        snapshot (checkpoint codec arrays over the DCN lane) instead of
        re-projecting the store.  Shape-signature gating in the adopt
        path keeps a rejoin at matching shapes free of XLA recompiles."""
        if self.hostlink is None:
            raise RuntimeError("no hostlink attached")
        snap, cursor = self.hostlink.bootstrap_from(int(hid))
        self.adopt_snapshot(snap, cursor=cursor)

    def _dispatch(self, queries, rest_depth: int, fused=None):
        # ``fused`` accepted for base-class call compatibility and
        # ignored: the sharded cascade has no fused-wave variant
        n = len(queries)
        if n == 0:
            return None
        faults.inject("device_dispatch")
        self.dispatches += 1
        t0 = time.perf_counter()
        with self._sync_lock:
            snap = self._snapshot_locked()
            stacked = self._stacked
            # cache-entry freshness stamp: captured under the same lock as
            # the snapshot the verdicts will be computed against
            cursor = self._log_cursor
        enc = self._encode(snap, queries, rest_depth)
        err, general = self._classify(snap, enc[0], enc[2])
        # Leopard first: checks the closure index answers drop out of the
        # sharded BFS entirely (same interception as the single-chip path)
        leo_res = self._leopard_answers(enc, err, general)
        act = ~(err | general)
        if leo_res is not None:
            act &= ~leo_res[1]
        # hot-spot shield after Leopard (shared _cache_consult): cached
        # queries leave both the sharded BFS and the algebra dispatch
        cache_res = self._cache_consult(queries, rest_depth, err, general,
                                        leo_res, cursor)
        if cache_res is not None:
            act &= ~cache_res[0]
            general = general & ~cache_res[0]
        # cross-host routing BEFORE the shard-level machinery: rows whose
        # serving host is a peer leave the local wave entirely (one framed
        # round trip per peer, launched now so the DCN exchange overlaps
        # the local device run; joined last in _collect).  Rows with no
        # live serving host degrade to the oracle via the err-mask.
        peerh = None
        if (self.hostlink is not None and self.n_hosts > 1
                and not getattr(_LOCAL_SERVE, "serving", False)):
            peerh = self._route_hosts(queries, act | general, rest_depth)
            if peerh is not None:
                gone = peerh["sent"] | peerh["lost"]
                act = act & ~gone
                general = general & ~gone
                err = err | gone
        self._poll_shard_faults()
        assign, owner = self._route_assign(enc[0], enc[1])
        if self._shard_down.any():
            # roots whose serving shard is down and that no live replica
            # can absorb degrade to the host oracle; the wave itself keeps
            # serving (general roots activate by hash owner on-device, so
            # a down owner sends them to the oracle too)
            down_fast = act & self._shard_down[assign]
            down_gen = general & self._shard_down[owner]
            act = act & ~down_fast
            general = general & ~down_gen
            err = err | down_fast | down_gen
        if self.replicate_hot and act.any():
            live = np.flatnonzero(act)
            self._hot.observe_many(list(zip(
                np.clip(np.asarray(enc[0])[live], 0, None).tolist(),
                np.clip(np.asarray(enc[1])[live], 0, None).tolist(),
            )))
        # per-shard routed-root accounting: the skew/rebalance signal and
        # the wave ledger's per-shard deltas
        with self._mesh_run_lock:
            np.add.at(self._shard_batches, assign[act], 1)
            if general.any():
                np.add.at(self._shard_batches, owner[general], 1)
        qpad = min(_bucket(n), self.frontier)
        padded = self._pad(enc, n, qpad)
        active = np.pad(act, (0, qpad - n))
        passign = np.pad(assign, (0, qpad - n))
        self._phase("check_encode", time.perf_counter() - t0)
        t0 = time.perf_counter()
        res = self._sharded_run(stacked, padded, active, assign=passign)
        gres = gi = None
        if general.any():
            gi = np.flatnonzero(general)
            gres = self._run_general_mesh(stacked, enc, gi)
        self._phase("check_mesh_dispatch", time.perf_counter() - t0)
        return (enc, err, general, res, gi, gres, stacked, assign, leo_res,
                cache_res, cursor, peerh)

    def _note_fast_tiers(self, mask, handle) -> None:
        # split the fast-path attribution by serving shard so a divergence
        # record names the exact replica that answered
        assign = handle[7]
        for s in np.unique(assign[mask]):
            flightrec.note_tier(
                f"mesh-shard-{int(s)}", int((assign[mask] == s).sum())
            )

    def _collect(self, handle, retry: bool = True):
        (enc, fallback_mask, general, res, gi, gres, stacked, assign,
         leo_res, cache_res, _cursor, peerh) = handle
        n = fallback_mask.shape[0]
        allowed = np.zeros(n, bool)
        fallback = fallback_mask.copy()

        if gres is not None:
            packed = np.asarray(gres[0])[: gres[2]]
            # occ rows: the skeleton level counts and fast_n ([0..D+1])
            # come from the psum-merged levels — replicated GLOBAL values
            # on every shard (take one row, not the n-fold sum) — while
            # the BFS sub-run counts ([D+2:]) are owner-masked per-shard
            # partials whose sum is the true global
            rows = np.asarray(gres[1])
            split = self.gen_levels + 2
            self._shard_gen_occ = rows[:, split:].sum(axis=1).astype(float)
            self._update_gen_occ(
                np.concatenate(
                    [rows[0, :split], rows[:, split:].sum(axis=0)]
                ),
                gres[3],
            )
            codes = (packed & 3).astype(np.int8)
            gover = ((packed >> 2) & 1).astype(bool)
            # dirty: some shard's overlay marked a row the skeleton or a
            # fast leaf touched — oracle answers, no device retry (the
            # retry would read the same stale base)
            gdirty = ((packed >> 3) & 1).astype(bool)
            allowed[gi] = codes == R_IS
            gunres = gover & ~gdirty & (codes != R_ERR)
            if retry and gunres.any() and self.retry_scale > 1:
                ri = gi[np.flatnonzero(gunres)]
                self.retries += len(ri)
                rh = self._run_general_mesh(
                    stacked, enc, ri, boost=self.retry_scale
                )
                rpacked = np.asarray(rh[0])[: rh[2]]
                rcodes = (rpacked & 3).astype(np.int8)
                rover = ((rpacked >> 2) & 1).astype(bool)
                rdirty = ((rpacked >> 3) & 1).astype(bool)
                allowed[ri] = rcodes == R_IS
                gover[gunres] = rover | rdirty | (rcodes == R_ERR)
                codes = codes.copy()
                codes[np.flatnonzero(gunres)] = rcodes
            fallback[gi] |= gover | gdirty | (codes == R_ERR)
        found = np.asarray(res.found)[:n]
        over = np.asarray(res.over)[:n]
        dirty = (
            np.asarray(res.dirty)[:n]
            if res.dirty is not None else np.zeros(n, bool)
        )
        fmask = ~(fallback_mask | general)
        allowed[fmask] = found[fmask]
        # found is monotone and overlay-exact: a dirty/overflow brush only
        # voids not-yet-found queries
        fallback |= fmask & dirty & ~found
        unres = fmask & over & ~found & ~dirty
        if retry and unres.any() and self.retry_scale > 1:
            ri = np.flatnonzero(unres)
            rpad = min(_bucket(len(ri), 256), self.frontier)
            renc = self._pad(tuple(a[ri] for a in enc), len(ri), rpad)
            self.retries += len(ri)
            ract = np.pad(np.ones(len(ri), bool), (0, rpad - len(ri)))
            rassign = (
                np.pad(assign[ri], (0, rpad - len(ri)))
                if assign is not None else None
            )
            rres = self._sharded_run(
                stacked, renc, ract, boost=self.retry_scale, assign=rassign,
            )
            rfound = np.asarray(rres.found)[: len(ri)]
            rover = np.asarray(rres.over)[: len(ri)]
            rdirty = (
                np.asarray(rres.dirty)[: len(ri)]
                if rres.dirty is not None else np.zeros(len(ri), bool)
            )
            allowed[ri] = rfound
            unres[ri] = (rover | rdirty) & ~rfound
        fallback |= unres
        if leo_res is not None:
            # closure-answered queries never fall back: they were masked
            # out of the BFS, so their device bits are inert zeros
            ans = leo_res[1]
            allowed[ans] = leo_res[0][ans]
            fallback &= ~ans
        if cache_res is not None:
            # cached verdicts likewise rode inactive all-zero BFS slots
            allowed[cache_res[0]] = cache_res[1][cache_res[0]]
            fallback &= ~cache_res[0]
        # join the cross-host exchanges LAST and with no lock held: the
        # local device work (including retries) above overlapped the DCN
        # round trips, and a peer serving OUR rows may itself be waiting
        # for this host's run lock
        peer_attr = None
        if peerh is not None:
            peer_attr = peerh["sent"] | peerh["lost"]
            for hid, (idx, pending, tmo) in peerh["pend"].items():
                ok = pending.wait(tmo)
                if ok is not None:
                    allowed[idx] = ok
                    fallback[idx] = False
                    if pending.spans:
                        # the peer recorded under OUR trace id and shipped
                        # its host-stamped timeline back with the verdicts
                        # — adopt it into this request's open span buffer
                        # (no-op when no ctx is open, e.g. wave threads)
                        flightrec.merge_spans(pending.spans)
                    continue
                # the peer never answered inside the budget: those rows
                # ride the oracle.  A clean timeout is deadline
                # semantics; an error is the peer dying mid-wave.
                if pending.error is None:
                    self.peer_deadline_degrades += len(idx)
                self._peer_fallbacks[hid] += len(idx)
                fallback[idx] = True
        # peer-degraded rows are attributed per-PEER, not to the local
        # owner shards: a dead host must not smear fallback counts over
        # this host's (healthy) shard gauges
        fb = np.flatnonzero(
            fallback & ~peer_attr if peer_attr is not None else fallback
        )
        if len(fb):
            # attribute each oracle fallback to the query's owner shard
            # (the same (ns, obj) hash that partitioned the graph); err
            # queries may carry -1 ids — clip, the attribution is
            # advisory telemetry, not a routing decision
            shards = graphshard.shard_of_np(
                np.clip(enc[0][fb], 0, None),
                np.clip(enc[1][fb], 0, None),
                self.n_shards,
            )
            np.add.at(self._shard_fallbacks, shards, 1)
        return allowed, fallback

    def consistency_cursors(self) -> tuple:
        """Per-shard drained-cursor vector for the freshness barrier and
        the shard field of minted snaptokens.  Today the mesh drains the
        shared changelog in lockstep (one ``changes_since`` call routes
        deltas to every shard overlay inside the same ``_sync_lock``
        section), so all entries are equal — but the vector is the
        wire/API contract that lets a future per-shard drain diverge
        without changing any caller."""
        with self._sync_lock:
            return (self._log_cursor,) * self.n_shards

    # -- hot-shard replication + skew rebalancing ---------------------------

    def hot_keys(self) -> List[Tuple[Tuple[int, int], int]]:
        """Hottest (ns_id, obj_id) root keys from the count-min sketch,
        hottest first, thresholded at ``hot_min`` estimated observations
        and capped at ``replica_max_keys``."""
        out = [
            (key, est) for key, est in self._hot.top()
            if est >= self.hot_min and isinstance(key, tuple)
        ]
        return out[: self.replica_max_keys]

    def shard_skew(self) -> float:
        """max/mean routed-root load ratio — the rebalance trigger."""
        b = self._shard_batches.astype(float)
        mean = float(b.mean())
        return float(b.max() / mean) if mean > 0 else 1.0

    def plan_replicas(self) -> Dict[Tuple[int, int], Tuple[int, ...]]:
        """The replica map the controller would publish now: each hot key
        keeps its existing copies (stability — no oscillation between
        equally-loaded shards) and new hot keys get one copy on the
        least-loaded live non-owner shard."""
        n = self.n_shards
        load = self._shard_batches.astype(np.int64)
        new_map: Dict[Tuple[int, int], Tuple[int, ...]] = {}
        for key, _est in self.hot_keys():
            prev = self._replica_map.get(key)
            if prev:
                new_map[key] = prev
                continue
            owner = int(graphshard.shard_of_np(
                np.array([key[0]]), np.array([key[1]]), n
            )[0])
            others = [
                s for s in range(n)
                if s != owner and not self._shard_down[s]
            ]
            if not others:
                continue
            new_map[key] = (min(others, key=lambda s: int(load[s])),)
        return new_map

    def replicate_now(self) -> int:
        """Synchronously publish replicas for the current hot set.
        Returns the number of newly replicated keys (0 = nothing hot, no
        change, or the publish lost a race with a write)."""
        if not self.replicate_hot or self.n_shards < 2:
            return 0
        new_map = self.plan_replicas()
        fresh = [k for k in new_map if k not in self._replica_map]
        if not fresh or not self._publish_replica_map(new_map):
            return 0
        self.replications += len(fresh)
        return len(fresh)

    def rebalance_now(self) -> bool:
        """Skew-triggered repartition: when the routed-root skew crosses
        ``rebalance_skew``, copy the hottest keys OWNED by the loaded
        shard onto the least-loaded live shard and publish the new
        sharding via generation pointer swap (zero verdict divergence:
        replicas are copies, child routing stays by hash)."""
        if self.n_shards < 2 or self.shard_skew() < self.rebalance_skew:
            return False
        b = self._shard_batches.astype(np.int64)
        hot_shard = int(b.argmax())
        cold = [
            int(s) for s in np.argsort(b)
            if int(s) != hot_shard and not self._shard_down[int(s)]
        ]
        if not cold:
            return False
        new_map = dict(self._replica_map)
        moved = 0
        for key, _est in self.hot_keys():
            owner = int(graphshard.shard_of_np(
                np.array([key[0]]), np.array([key[1]]), self.n_shards
            )[0])
            if owner != hot_shard or cold[0] in new_map.get(key, ()):
                continue
            if len(new_map) >= self.replica_max_keys and key not in new_map:
                break
            new_map[key] = tuple(new_map.get(key, ())) + (cold[0],)
            moved += 1
        if not moved or not self._publish_replica_map(new_map):
            return False
        self.rebalances += 1
        return True

    def _publish_replica_map(self, new_map) -> bool:
        """Generation-swapped replica publish, modeled on the off-path
        compactor: pin the column mirror under the sync lock, build the
        re-replicated sharded snapshot OFF the lock (checks keep serving
        the old sharding), then swap pointers under the lock only if no
        write raced the build.  Same-shape swaps (the common case — the
        replica copies pad into the existing max-shard shapes) keep the
        compile observatory warm."""
        with self._sync_lock:
            self._snapshot_locked()  # drain the changelog first
            if self._cols is None or self._shard_snaps is None:
                return False
            frozen = self._cols.freeze()
            token = self._gen_token
            pin_cursor = self._log_cursor
            vocab = self._vocab
        snaps, stacked_base = graphshard.build_sharded_snapshot(
            self.store, self.namespace_manager, self.n_shards, vocab,
            cols=frozen, replicate=new_map,
        )
        with self._sync_lock:
            if token != self._gen_token or pin_cursor != self._log_cursor:
                return False  # a write landed mid-build: next tick retries
            old_sig = self._swap_shape_signature()
            if self._snap is not None:
                # overlay admission reads the GLOBAL pair set (see
                # _install_device_arrays)
                for sn in snaps:
                    sn.dyn_pairs = self._snap.dyn_pairs
            self._shard_snaps = snaps
            self._stacked_base = stacked_base
            # the rebuilt partitions already include every drained delta,
            # so the per-shard overlays restart empty; the replicated
            # overlay/_snap pair (expand + admission) is untouched
            self._shard_overlays = [
                dl.OverlayState() for _ in range(self.n_shards)
            ]
            self._stacked = dict(stacked_base, **self._overlay_stacks())
            self._replica_map = dict(new_map)
            self.generation += 1
            new_sig = self._swap_shape_signature()
            if old_sig is None or new_sig != old_sig:
                self._gen_sched_cache.clear()
                self._clean_dispatches = 0
                compilewatch.get().declare_cold(
                    "replica publish: stacked shapes changed"
                )
            return True

    def _rebal_worker(self) -> None:
        interval = max(self.rebalance_interval_ms, 1.0) / 1000.0
        while not self._rebal_stop.wait(interval):
            try:
                if not self.rebalance_now() and self.replicate_hot:
                    self.replicate_now()
            except Exception:  # noqa: BLE001 - serving view must stay intact
                self.compaction_errors += 1

    def close(self) -> None:
        if self.hostlink is not None:
            self.hostlink.stop()
        self._rebal_stop.set()
        t = self._rebal_thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        super().close()

    def shard_route_counts(self) -> np.ndarray:
        """Cumulative per-shard routed-root counts (the coalescer diffs
        consecutive reads for the wave ledger's per-shard accounting)."""
        return self._shard_batches.copy()

    def mesh_stats(self) -> dict:
        """Engine-level replication / rebalance / failover counters for
        the registry's mesh gauges."""
        out = {
            "replica_keys": len(self._replica_map),
            "replica_routed": int(self.replica_routed),
            "replications": int(self.replications),
            "rebalances": int(self.rebalances),
            "shard_recoveries": int(self.shard_recoveries),
            "shards_down": int(self._shard_down.sum()),
            "skew": round(self.shard_skew(), 3),
        }
        link = self.hostlink
        if link is not None:
            out.update({
                "host_id": int(self.host_id),
                "n_hosts": int(self.n_hosts),
                "hosts_down": sum(
                    1 for h in range(self.n_hosts)
                    if h != self.host_id and link.peer_down(h)
                ),
                "peer_routed": int(self._peer_batches.sum()),
                "peer_fallbacks": int(self._peer_fallbacks.sum()),
                "peer_deadline_degrades": int(self.peer_deadline_degrades),
                "peer_replica_keys": len(self._peer_replicas),
                "peer_recoveries": int(link.peer_recoveries),
                "peer_frontier_rtt_p50_ms": link.frontier_rtt_p50_ms(),
            })
        return out

    def peer_stats(self) -> List[dict]:
        """Per-peer rows (id, liveness, heartbeat age, load, frontier
        round trips, shipped rows, peer-degraded fallbacks) for
        ``/debug/mesh`` and the registry's peer gauges."""
        link = self.hostlink
        if link is None:
            return []
        rows = link.peer_rows()
        for r in rows:
            hid = r["peer"]
            r["routed"] = int(self._peer_batches[hid])
            r["fallbacks"] = int(self._peer_fallbacks[hid])
        return rows

    def shard_stats(self) -> List[dict]:
        """Per-shard serving counters for the registry's mesh gauges and
        `cli.py status`: overlay pressure, graph size, last general
        dispatch's BFS occupancy partial, and cumulative oracle
        fallbacks attributed by owner shard."""
        ovs = self._shard_overlays or []
        snaps = self._shard_snaps or []
        replica_keys = np.zeros(self.n_shards, np.int64)
        for extras in self._replica_map.values():
            for s in extras:
                replica_keys[int(s)] += 1
        out = []
        for i in range(self.n_shards):
            pairs, dirty = ovs[i].size() if i < len(ovs) else (0, 0)
            nodes = (
                int(getattr(snaps[i], "n_nodes", 0)) if i < len(snaps) else 0
            )
            out.append({
                "shard": i,
                "batches": int(self._shard_batches[i]),
                "fallbacks": int(self._shard_fallbacks[i]),
                "replica_keys": int(replica_keys[i]),
                "down": bool(self._shard_down[i]),
                "overlay_pairs": int(pairs),
                "overlay_dirty": int(dirty),
                "nodes": nodes,
                "gen_occupancy": float(self._shard_gen_occ[i]),
                "leopard_pairs": int(self._leo_shard_pairs[i])
                if i < len(self._leo_shard_pairs) else 0,
            })
        return out
