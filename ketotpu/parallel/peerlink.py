"""PeerLink: the cross-host DCN lane of the multi-host mesh.

One host of the mesh is a MeshCheckEngine process (parallel/meshengine.py)
owning a slice of the ROOT-KEY space: ``host_of(namespace, object)`` — a
process-independent hash over the key *strings* (vocab ids are per-process
and useless as a cross-host coordinate) — extends the PR-10 host-computed
``assign`` column with a host coordinate.  Everything that crosses hosts
rides this lane:

* **frontier exchange** — a wave's cross-host rows batch into ONE framed
  round-trip per peer per wave (``check`` op, tuple columns + depth +
  ``deadline_ms`` in the frame meta); the owner answers them against its
  own local cascade, bit-identically;
* **heartbeats** — each owner publishes its load, shard count, drained
  cursor, and hot-key replica plan every ``heartbeat_ms``; the reply
  carries the peer's own payload, so one call refreshes both directions.
  ``miss_budget`` consecutive failures mark the peer DOWN — every shard
  it owns at once — and a later answered beat (or a received one) marks
  it back up;
* **segment shipping** — ``bootstrap`` ships the owner's projected base
  snapshot (the checkpoint codec's flat array dict) so a joining or
  restarted peer adopts warm instead of re-projecting the store.

The wire is the same framed protocol as the same-host worker socket
(server/wire.py) — but TCP across hosts is not a trusted channel, so the
lane is hardened: a shared-secret ``hello`` handshake (constant-time
compare) gates every connection, per-frame size caps tighten the global
wire limits, shared-memory frames are refused outright (``recv_frame``
with no shm cache raises ``WireError``), and any framing violation
closes the connection — the strict one-response-per-request discipline
of ``workers._Conn`` is reused verbatim, just over TCP.

Chaos knobs (ketotpu/faults.py): ``peer_down`` silences a named host's
server (connections close unanswered — the whole-host-failure
simulation), ``peer_drop_rate`` drops client calls before the frame is
sent, ``peer_latency_ms`` stalls every cross-host call.
"""

from __future__ import annotations

import hmac
import socketserver
import threading
import time
import zlib
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from ketotpu import faults, flightrec
from ketotpu.api.types import KetoAPIError
from ketotpu.server import wire

PROTO = 1

#: per-frame caps for the DCN lane (tighter than the same-host wire's
#: global limits): meta is small structured JSON, payloads are bounded by
#: ``max_frame_mb`` — a hostile or desynced peer cannot make a length
#: prefix allocate gigabytes
MAX_PEER_META = 4 * 1024 * 1024

_HOST_SALT = b"\x00keto-mesh-host"


def host_of(namespace: str, obj: str, n_hosts: int) -> int:
    """Owner host for a root key.  Hashes the key STRINGS (crc32 — stable
    across processes, unlike per-process vocab ids or salted ``hash()``),
    so every host computes the same coordinate for the same key."""
    if n_hosts <= 1:
        return 0
    h = zlib.crc32(
        namespace.encode("utf-8") + b"\x1f" + obj.encode("utf-8")
        + _HOST_SALT
    )
    return int(h % n_hosts)


def host_of_queries(queries, n_hosts: int) -> np.ndarray:
    """Vectorized-enough host coordinates for a wave's root queries."""
    return np.fromiter(
        (host_of(q.namespace, q.object, n_hosts) for q in queries),
        dtype=np.int32, count=len(queries),
    )


def _parse_addr(addr) -> Tuple[str, int]:
    if isinstance(addr, (tuple, list)):
        return str(addr[0]), int(addr[1])
    host, _, port = str(addr).rpartition(":")
    if not host:
        raise ValueError(f"peer address {addr!r} is not host:port")
    return host, int(port)


class _Pending:
    """One in-flight cross-host frontier exchange (thread-backed)."""

    __slots__ = ("_evt", "value", "error", "spans")

    def __init__(self):
        self._evt = threading.Event()
        self.value: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        # peer-host span timeline shipped back with the verdicts; the
        # collector merges it into the origin request's open trace ctx
        self.spans: Optional[list] = None

    def wait(self, timeout: Optional[float]) -> Optional[np.ndarray]:
        """Verdict array, or None on failure/timeout (caller degrades)."""
        if not self._evt.wait(timeout):
            return None
        return self.value if self.error is None else None


class _PeerState:
    __slots__ = ("last_seen", "misses", "down", "load", "shards",
                 "cursor", "replica_keys", "roundtrips", "rtts",
                 "bootstraps", "digest")

    def __init__(self):
        self.last_seen = 0.0   # monotonic; 0 = never heard from
        self.misses = 0
        self.down = False
        self.load = 0.0
        self.shards = 0
        self.cursor = -1
        self.replica_keys = 0
        self.roundtrips = 0    # frontier (check) round trips completed
        self.rtts: deque = deque(maxlen=256)  # frontier RTTs, seconds
        self.bootstraps = 0
        # last health digest this peer's heartbeat carried; None until
        # one arrives (older PROTO payloads never send the field, so the
        # fleet view renders those peers as digest-unavailable)
        self.digest: Optional[dict] = None


class _PeerHandler(socketserver.StreamRequestHandler):
    def handle(self):  # noqa: C901 - one linear connection loop
        link: HostLink = self.server.link  # type: ignore[attr-defined]
        caps = dict(max_meta=MAX_PEER_META, max_bin=link.max_frame_bytes)
        try:
            got = wire.recv_frame(self.rfile, **caps)
        except (wire.WireError, OSError):
            return
        if got is None:
            return
        hello, _, _ = got
        # shared-secret handshake gates everything else on the connection;
        # constant-time compare, and a failure answers once then closes
        if (
            hello.get("op") != "hello"
            or int(hello.get("proto", 0)) != PROTO
            or not hmac.compare_digest(
                str(hello.get("secret", "")), link.secret
            )
        ):
            try:
                wire.send_frame(self.connection, {"error": {
                    "msg": "peerlink handshake refused", "status": 403,
                }})
            except OSError:
                pass
            return
        try:
            wire.send_frame(
                self.connection, {"ok": True, "host": link.host_id},
            )
        except OSError:
            return
        link._note_heard(hello.get("host"))
        while True:
            try:
                got = wire.recv_frame(self.rfile, **caps)
            except (wire.WireError, OSError):
                return  # desynced/hostile/gone: drop the connection
            if got is None:
                return
            if faults.peer_silenced(link.host_id):
                # whole-host-failure simulation: this host stops
                # answering DCN frames — close unanswered so the peer
                # sees exactly what a dead process looks like
                return
            meta, arrays, _ = got
            try:
                resp, resp_arrays = link._serve(meta, arrays)
            except KetoAPIError as e:
                resp, resp_arrays = {"error": {
                    "msg": str(e),
                    "status": getattr(e, "status_code", 500),
                }}, None
            except Exception as e:  # noqa: BLE001 - answered, not fatal
                resp, resp_arrays = {"error": {
                    "msg": f"{type(e).__name__}: {e}", "status": 500,
                }}, None
            try:
                wire.send_frame(self.connection, resp, resp_arrays)
            except OSError:
                return


class _PeerServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _PeerClient:
    """One peer's outbound lane: a pooled framed TCP connection with the
    hello handshake on (re)connect.  Transport errors discard the
    connection (strict framing); the next call reconnects."""

    def __init__(self, link: "HostLink", hid: int):
        self._link = link
        self._hid = hid
        self._lock = threading.Lock()
        self._conn = None

    def close(self) -> None:
        with self._lock:
            conn, self._conn = self._conn, None
        if conn is not None:
            conn.close()

    def _connect(self, timeout: Optional[float]):
        from ketotpu.server.workers import _Conn

        link = self._link
        conn = _Conn(
            _parse_addr(link.addrs[self._hid]),
            metrics=link.metrics, shm_threshold=0,
            connect_timeout=timeout,
        )
        try:
            resp, _ = conn.call(
                {"op": "hello", "proto": PROTO, "host": link.host_id,
                 "secret": link.secret},
                timeout=timeout,
            )
        except BaseException:
            conn.close()
            raise
        if not resp.get("ok"):
            conn.close()
            raise ConnectionError("peerlink handshake refused")
        return conn

    def call(self, meta: dict, arrays=None,
             timeout: Optional[float] = None):
        faults.peer_latency()
        if faults.peer_dropped():
            self.close()
            raise ConnectionError("injected peer drop")
        with self._lock:
            had_conn = self._conn is not None
            if self._conn is None:
                self._conn = self._connect(timeout)
            try:
                return self._conn.call(meta, arrays, timeout=timeout)
            except KetoAPIError:
                raise  # typed error: exchange completed, stream aligned
            except Exception:
                self._conn = None
                if not had_conn:
                    raise
            # the cached connection was stale (peer restarted between
            # waves): one fresh connect inside the same budget
            self._conn = self._connect(timeout)
            try:
                return self._conn.call(meta, arrays, timeout=timeout)
            except KetoAPIError:
                raise
            except Exception:
                self._conn = None
                raise


class HostLink:
    """This host's view of the mesh topology: the PeerLink server, one
    outbound client per peer, the heartbeat/liveness loop, and the
    per-peer counters behind ``/debug/mesh`` and the
    ``keto_mesh_peer_*`` gauges."""

    def __init__(
        self,
        host_id: int,
        addrs: List,
        secret: str,
        *,
        heartbeat_ms: float = 500.0,
        miss_budget: int = 3,
        rpc_timeout_ms: float = 2000.0,
        max_frame_mb: int = 64,
        metrics=None,
        breaker_config: Optional[dict] = None,
    ):
        if not secret:
            raise ValueError(
                "peerlink requires a shared secret "
                "(engine.mesh.hosts.secret)"
            )
        self.host_id = int(host_id)
        self.addrs = [_parse_addr(a) for a in addrs]
        self.n_hosts = len(self.addrs)
        if not (0 <= self.host_id < self.n_hosts):
            raise ValueError(
                f"host_id {host_id} outside the {self.n_hosts}-host "
                f"topology"
            )
        self.secret = str(secret)
        self.heartbeat_ms = float(heartbeat_ms)
        self.miss_budget = int(miss_budget)
        self.rpc_timeout_s = float(rpc_timeout_ms) / 1000.0
        self.max_frame_bytes = int(max_frame_mb) << 20
        self.metrics = metrics
        self._engine = None
        self._server: Optional[_PeerServer] = None
        self._server_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._state_lock = threading.Lock()
        self._peers: Dict[int, _PeerState] = {
            h: _PeerState() for h in range(self.n_hosts)
            if h != self.host_id
        }
        self._clients: Dict[int, _PeerClient] = {}
        # per-peer circuit breakers on the frontier-exchange lane: an
        # erroring/timing-out peer fails fast to the oracle degrade path
        # (verdicts stay exact) instead of eating the RPC timeout on
        # every wave; heartbeats bypass the breaker — they are the probe
        # that keeps liveness honest while the lane is open
        self._breaker_config = dict(breaker_config or {})
        self._breakers: Dict[int, "CircuitBreaker"] = {}
        self.host_downs = 0        # peers declared down (cumulative)
        self.peer_recoveries = 0   # peers that came back after down
        # fleet-health seams, wired by Registry._build_hostlink: with a
        # registry, inbound frontier checks record under the caller's
        # traceparent and ship their spans back; with a digest_fn, every
        # heartbeat (both directions) carries this host's health digest.
        # Bare links (tests, older topologies) leave both None and the
        # lane behaves exactly as before.
        self.registry = None
        self.digest_fn = None

    # -- lifecycle ----------------------------------------------------------

    def attach_engine(self, engine) -> None:
        """Bind the serving engine: it answers frontier exchanges
        (``_peer_serve_check``), feeds heartbeat payloads
        (``_hb_payload``), and absorbs topology events
        (``_merge_peer_replicas`` / ``_on_peer_down`` / ``_on_peer_up``)."""
        self._engine = engine

    def bind(self) -> Tuple[str, int]:
        """Start the PeerLink server on this host's address.  Port 0
        binds ephemerally and rewrites the topology entry — callers then
        exchange real addresses via :meth:`set_peer_addr` (tests)."""
        host, port = self.addrs[self.host_id]
        srv = _PeerServer((host, port), _PeerHandler)
        srv.link = self  # type: ignore[attr-defined]
        self._server = srv
        self.addrs[self.host_id] = srv.server_address[:2]
        t = threading.Thread(
            target=srv.serve_forever, kwargs={"poll_interval": 0.05},
            name=f"keto-peerlink-{self.host_id}", daemon=True,
        )
        self._server_thread = t
        t.start()
        return self.addrs[self.host_id]

    def start(self) -> None:
        """Start the heartbeat loop (after :meth:`bind` and topology
        exchange)."""
        if self._hb_thread is not None or self.n_hosts < 2:
            return
        t = threading.Thread(
            target=self._hb_loop,
            name=f"keto-peerlink-hb-{self.host_id}", daemon=True,
        )
        self._hb_thread = t
        t.start()

    def stop(self) -> None:
        self._hb_stop.set()
        t = self._hb_thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        srv = self._server
        if srv is not None:
            srv.shutdown()
            srv.server_close()
            self._server = None
        for c in list(self._clients.values()):
            c.close()

    def set_peer_addr(self, hid: int, addr) -> None:
        self.addrs[int(hid)] = _parse_addr(addr)

    @property
    def addr(self) -> Tuple[str, int]:
        return self.addrs[self.host_id]

    def _client(self, hid: int) -> _PeerClient:
        c = self._clients.get(hid)
        if c is None:
            c = self._clients.setdefault(hid, _PeerClient(self, hid))
        return c

    # -- liveness -----------------------------------------------------------

    def _hb_loop(self) -> None:
        interval = max(self.heartbeat_ms, 10.0) / 1000.0
        while not self._hb_stop.wait(interval):
            try:
                self.heartbeat_now()
            except Exception:  # noqa: BLE001 - liveness must keep polling
                pass

    def heartbeat_now(self) -> None:
        """One synchronous heartbeat round to every peer (the loop's
        body, callable directly so tests drive liveness without
        sleeping)."""
        if faults.peer_silenced(self.host_id):
            return  # a silenced host is fully dead: it stops sending too
        payload = self._local_payload()
        for hid in list(self._peers):
            try:
                resp, _ = self._client(hid).call(
                    {"op": "heartbeat", "host": self.host_id, **payload},
                    timeout=self.rpc_timeout_s,
                )
            except (KetoAPIError, OSError, ConnectionError):
                self._note_miss(hid)
                continue
            self._note_alive(hid, resp)

    def _local_payload(self) -> dict:
        """This host's heartbeat payload: the engine's topology fields
        plus (when the registry wired one) the compact health digest —
        absent entirely on bare links, which is what the legacy-payload
        compatibility guard on the receive side expects."""
        eng = self._engine
        payload = eng._hb_payload() if eng is not None else {}
        if self.digest_fn is not None:
            try:
                payload = dict(payload, digest=self.digest_fn())
            except Exception:  # noqa: BLE001 - health must not kill beats
                pass
        return payload

    def _note_alive(self, hid: int, payload: dict) -> None:
        eng = self._engine
        with self._state_lock:
            st = self._peers.get(hid)
            if st is None:
                return
            was_down = st.down
            st.last_seen = time.monotonic()
            st.misses = 0
            st.down = False
            st.load = float(payload.get("load", st.load) or 0.0)
            st.shards = int(payload.get("shards", st.shards) or 0)
            cur = payload.get("cursor")
            if cur is not None:
                st.cursor = int(cur)
            replicas = payload.get("replicas")
            if replicas is not None:
                st.replica_keys = len(replicas)
            digest = payload.get("digest")
            if isinstance(digest, dict):
                # legacy peers never send the field; keep whatever we
                # last heard (None = never) instead of erasing it
                st.digest = digest
            if was_down:
                self.peer_recoveries += 1
        if eng is not None:
            if replicas is not None:
                eng._merge_peer_replicas(hid, replicas)
            if was_down:
                eng._on_peer_up(hid)

    def _note_miss(self, hid: int) -> None:
        eng = self._engine
        went_down = False
        with self._state_lock:
            st = self._peers.get(hid)
            if st is None:
                return
            st.misses += 1
            if not st.down and st.misses >= self.miss_budget:
                st.down = True
                self.host_downs += 1
                went_down = True
        if went_down and eng is not None:
            eng._on_peer_down(hid)

    def _note_heard(self, hid) -> None:
        """An inbound frame from a peer is liveness evidence too — a
        returning peer's first heartbeat marks it up here before our own
        next outbound round."""
        try:
            hid = int(hid)
        except (TypeError, ValueError):
            return
        if hid in self._peers:
            self._note_alive(hid, {})

    def peer_down(self, hid: int) -> bool:
        st = self._peers.get(int(hid))
        return bool(st is not None and st.down)

    def peer_load(self, hid: int) -> float:
        st = self._peers.get(int(hid))
        return float(st.load) if st is not None else 0.0

    def live_hosts(self) -> List[int]:
        """Every host currently believed up, self included."""
        return [self.host_id] + [
            h for h, st in self._peers.items() if not st.down
        ]

    # -- cross-host ops -----------------------------------------------------

    def breaker(self, hid: int):
        """The (lazily built) circuit breaker guarding peer ``hid``'s
        frontier-exchange lane."""
        from ketotpu.server.overload import CircuitBreaker

        with self._state_lock:
            br = self._breakers.get(hid)
            if br is None:
                br = self._breakers[hid] = CircuitBreaker(
                    f"peer{hid}", metrics=self.metrics,
                    **self._breaker_config,
                )
            return br

    def breakers(self) -> List:
        with self._state_lock:
            return list(self._breakers.values())

    def check_rows_async(
        self, hid: int, rows, rest_depth: int,
        timeout_s: Optional[float],
    ) -> _Pending:
        """Ship one wave's cross-host rows to their serving host as ONE
        framed round trip, concurrently with the local device dispatch.
        The returned pending resolves to the verdict array, or None —
        the caller degrades those rows to the oracle."""
        pending = _Pending()
        breaker = self.breaker(hid)
        if not breaker.allow():
            # lane open: pre-failed pending, no exchange thread — the
            # caller degrades these rows to the oracle immediately
            # (exact verdicts, just slower) instead of waiting out the
            # RPC timeout against a peer that keeps failing
            pending.error = ConnectionError(
                f"peer{hid} circuit breaker open; degrading to oracle"
            )
            pending._evt.set()
            return pending
        meta = {
            "op": "check", "host": self.host_id,
            "depth": int(rest_depth), "n": len(rows),
        }
        if timeout_s is not None:
            meta["deadline_ms"] = max(1, int(timeout_s * 1000))
        # captured HERE, on the dispatching thread, while the request's
        # flightrec ctx is still open — the exchange thread below has no
        # thread-local ctx of its own
        tp = flightrec.current_traceparent()
        if tp:
            meta["traceparent"] = tp
        arrays: Dict[str, np.ndarray] = {}
        wire.pack_tuplecols(arrays, "q", rows)

        def _run():
            t0 = time.monotonic()
            try:
                resp, resp_arrays = self._client(hid).call(
                    meta, arrays, timeout=timeout_s,
                )
                ok = np.asarray(resp_arrays["ok"], np.uint8)
                if ok.shape[0] != len(rows):
                    raise wire.WireError(
                        "peer check verdict count mismatch"
                    )
                pending.spans = resp.get("spans") or None
                pending.value = ok.astype(bool)
            except BaseException as e:  # noqa: BLE001 - reported via wait
                pending.error = e
                breaker.record_failure()
            else:
                breaker.record_success()
                with self._state_lock:
                    st = self._peers.get(hid)
                    if st is not None:
                        st.roundtrips += 1
                        st.rtts.append(time.monotonic() - t0)
            pending._evt.set()

        threading.Thread(
            target=_run, name=f"keto-peerlink-check-{hid}", daemon=True,
        ).start()
        return pending

    def bootstrap_from(self, hid: int, *, timeout_s: float = 60.0):
        """Pull the peer's projected base snapshot (segment ship): the
        checkpoint codec's array dict + the cursor it was captured at.
        Returns ``(snap, cursor)`` ready for ``adopt_snapshot``."""
        from ketotpu.engine import checkpoint as ckpt

        resp, arrays = self._client(hid).call(
            {"op": "bootstrap", "host": self.host_id},
            timeout=timeout_s,
        )
        snap = ckpt.snapshot_from_arrays(arrays)
        with self._state_lock:
            st = self._peers.get(hid)
            if st is not None:
                st.bootstraps += 1
        return snap, int(resp["cursor"])

    # -- server dispatch ----------------------------------------------------

    def _serve(self, meta: dict, arrays) -> Tuple[dict, Optional[dict]]:
        from ketotpu import deadline

        op = meta.get("op")
        if op == "ping":
            return {"ok": True, "host": self.host_id}, None
        if op == "heartbeat":
            self._note_alive_from_wire(meta)
            # the reply carries this host's own payload (digest included)
            # so one heartbeat call refreshes health in both directions
            return {
                "ok": True, "host": self.host_id, **self._local_payload(),
            }, None
        if op == "check":
            eng = self._engine
            if eng is None:
                raise KetoAPIError("no engine attached to this peer")
            rows = wire.unpack_tuplecols(arrays, "q")
            ms = meta.get("deadline_ms")
            tp = meta.get("traceparent")
            if tp and self.registry is not None:
                # open a span buffer under the CALLER's trace id (the
                # PR-11 owner↔worker pattern on the DCN lane): stage
                # notes from the local cascade land here, and the whole
                # timeline ships back with the verdicts — host-stamped
                # so the stitched trace attributes every span
                with flightrec.rpc_recording(
                    self.registry, "peer_check", traceparent=tp,
                    detail=(
                        f"peer host {meta.get('host')} -> "
                        f"host {self.host_id} frontier check"
                    ),
                ):
                    with deadline.scope(
                        None if ms is None else ms / 1000.0
                    ):
                        ok = eng._peer_serve_check(
                            rows, int(meta.get("depth", 0))
                        )
                    spans = [
                        dict(s, host=self.host_id)
                        for s in flightrec.export_spans()
                    ]
                return (
                    {"ok": True, "n": len(ok), "spans": spans},
                    {"ok": np.asarray(ok, np.uint8)},
                )
            with deadline.scope(None if ms is None else ms / 1000.0):
                ok = eng._peer_serve_check(
                    rows, int(meta.get("depth", 0))
                )
            return (
                {"ok": True, "n": len(ok)},
                {"ok": np.asarray(ok, np.uint8)},
            )
        if op == "bootstrap":
            from ketotpu.engine import checkpoint as ckpt

            eng = self._engine
            if eng is None:
                raise KetoAPIError("no engine attached to this peer")
            snap, cursor, fingerprint, _rows, _tail, _head, _version = (
                eng.replication_snapshot()
            )
            return (
                {"ok": True, "cursor": int(cursor),
                 "fingerprint": int(fingerprint)},
                ckpt.snapshot_to_arrays(snap),
            )
        raise KetoAPIError(f"unknown peerlink op {op!r}")

    def _note_alive_from_wire(self, meta: dict) -> None:
        try:
            hid = int(meta.get("host", -1))
        except (TypeError, ValueError):
            return
        if hid in self._peers:
            self._note_alive(hid, meta)

    # -- observability ------------------------------------------------------

    def frontier_rtt_p50_ms(self) -> float:
        samples: List[float] = []
        with self._state_lock:
            for st in self._peers.values():
                samples.extend(st.rtts)
        if not samples:
            return 0.0
        samples.sort()
        return round(1000.0 * samples[len(samples) // 2], 3)

    def peer_rows(self) -> List[dict]:
        """Per-peer rows for ``/debug/mesh`` and the wave ledger: id,
        heartbeat age, liveness, shards owned, replica keys, frontier
        round trips."""
        now = time.monotonic()
        out = []
        with self._state_lock:
            for hid in sorted(self._peers):
                st = self._peers[hid]
                rtts = sorted(st.rtts)
                out.append({
                    "peer": hid,
                    "addr": "%s:%d" % self.addrs[hid],
                    "down": bool(st.down),
                    "heartbeat_age_s": (
                        round(now - st.last_seen, 3)
                        if st.last_seen else -1.0
                    ),
                    "misses": int(st.misses),
                    "load": float(st.load),
                    "shards_owned": int(st.shards),
                    "cursor": int(st.cursor),
                    "replica_keys": int(st.replica_keys),
                    "frontier_roundtrips": int(st.roundtrips),
                    "frontier_rtt_p50_ms": (
                        round(1000.0 * rtts[len(rtts) // 2], 3)
                        if rtts else 0.0
                    ),
                    "bootstraps": int(st.bootstraps),
                    "breaker": (
                        self._breakers[hid].state
                        if hid in self._breakers else "closed"
                    ),
                    # None = this peer has never sent one (legacy
                    # payload); /debug/fleet renders that "unavailable"
                    "digest": st.digest,
                })
        return out

    def stats(self) -> dict:
        rows = self.peer_rows()
        return {
            "host_id": self.host_id,
            "n_hosts": self.n_hosts,
            "addr": "%s:%d" % self.addr,
            "hosts_down": sum(1 for r in rows if r["down"]),
            "host_downs_total": int(self.host_downs),
            "peer_recoveries": int(self.peer_recoveries),
            "frontier_rtt_p50_ms": self.frontier_rtt_p50_ms(),
            "peers": rows,
        }
