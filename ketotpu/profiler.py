"""On-demand device profiling: ``POST /debug/profile?seconds=N``.

Once the flight recorder has named the slow request, the wave ledger has
named its wave, and the compile log has ruled recompiles out, the last
step of the runbook is a real device trace.  This module wraps
``jax.profiler`` trace capture behind a config gate so an operator can
pull an N-second trace from a LIVE serving process without restarting it
with profiling flags.

Safety properties the REST handler relies on:

* **Config-gated** — disabled by default (``observability.profiler
  .enabled``); a probe against a production box that nobody armed
  returns 403, it does not start writing trace files.
* **One capture at a time** — ``jax.profiler`` keeps global state; a
  second concurrent start would corrupt the first capture.  The lock is
  non-blocking: a busy profiler answers 409 immediately.
* **Bounded** — ``seconds`` is clamped to ``max_seconds``; a typo'd
  ``seconds=3600`` cannot pin the capture thread for an hour.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Optional


class ProfilerDisabled(RuntimeError):
    """Profiling is not armed in config (`observability.profiler.enabled`)."""


class ProfilerBusy(RuntimeError):
    """A capture is already in progress (jax.profiler state is global)."""


class DeviceProfiler:
    """Config-gated, single-flight jax.profiler trace capture."""

    def __init__(self, enabled: bool = False, out_dir: str = "",
                 max_seconds: float = 60.0):
        self.enabled = bool(enabled)
        self.out_dir = out_dir or ""
        self.max_seconds = float(max_seconds)
        self._lock = threading.Lock()
        self.captures = 0
        self.last_artifact: Optional[str] = None

    def capture(self, seconds: float) -> dict:
        """Block for ``seconds`` (clamped) of trace capture; returns the
        artifact metadata ``{path, seconds, started_ts}``."""
        if not self.enabled:
            raise ProfilerDisabled(
                "device profiling is disabled; set "
                "observability.profiler.enabled=true to arm it"
            )
        seconds = max(0.1, min(float(seconds), self.max_seconds))
        if not self._lock.acquire(blocking=False):
            raise ProfilerBusy("a profile capture is already in progress")
        try:
            import jax

            base = self.out_dir or os.path.join(
                tempfile.gettempdir(), "keto-tpu-profiles"
            )
            os.makedirs(base, exist_ok=True)
            started = time.time()
            path = os.path.join(base, f"profile-{int(started)}")
            jax.profiler.start_trace(path)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
            self.captures += 1
            self.last_artifact = path
            return {
                "path": path,
                "seconds": seconds,
                "started_ts": round(started, 3),
            }
        finally:
            self._lock.release()
