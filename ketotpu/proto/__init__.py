"""Generated protobuf bindings for the vendored Keto wire contract.

The ``proto/`` tree at the repo root vendors the reference's `.proto` files
unchanged (SURVEY §7 step 1; `proto/ory/keto/relation_tuples/v1alpha2/
check_service.proto:18-21` etc.); `protoc --python_out` regenerates this
package (see scripts/gen_proto.sh).  The generated modules import each other
through the absolute ``ory.keto...`` package path protoc emits, so this
package root goes on ``sys.path``.
"""

import os
import sys

_HERE = os.path.dirname(__file__)
if _HERE not in sys.path:
    sys.path.insert(0, _HERE)

from health.v1 import health_pb2  # noqa: E402,F401
from ory.keto.opl.v1alpha1 import syntax_service_pb2  # noqa: E402,F401
from ory.keto.relation_tuples.v1alpha2 import (  # noqa: E402,F401
    batch_service_pb2,
    check_service_pb2,
    expand_service_pb2,
    namespaces_service_pb2,
    read_service_pb2,
    relation_tuples_pb2,
    stream_service_pb2,
    version_pb2,
    watch_service_pb2,
    write_service_pb2,
)
