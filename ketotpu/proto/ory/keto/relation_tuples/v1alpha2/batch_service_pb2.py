# -*- coding: utf-8 -*-
# source: ory/keto/relation_tuples/v1alpha2/batch_service.proto
"""Protobuf bindings for the BatchCheck/BatchExpand wire messages.

These RPCs are EXTENSIONS over the vendored reference contract — Keto at
this version has no batch RPCs — so there is no upstream generated module
to vendor.  `protoc` is unavailable in this environment; like
watch_service_pb2, the module assembles the FileDescriptorProto
programmatically and feeds it through the exact AddSerializedFile +
builder path protoc output uses.  The human-readable source lives at
proto/ory/keto/relation_tuples/v1alpha2/batch_service.proto.

Only messages are declared here: the RPCs themselves ride on the EXISTING
CheckService/ExpandService (as BatchCheck/BatchExpand methods), and those
service descriptors are already registered by their own modules — the
method registration authority is ketotpu.proto.services.SERVICES, which
gRPC consults instead of the descriptor pool.
"""
from google.protobuf import descriptor_pb2 as _dpb
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
from google.protobuf.internal import builder as _builder

_sym_db = _symbol_database.Default()

# dependencies must be registered in the pool before this file is added
from ory.keto.relation_tuples.v1alpha2 import relation_tuples_pb2 as ory_dot_keto_dot_relation__tuples_dot_v1alpha2_dot_relation__tuples__pb2  # noqa: E501,F401
from ory.keto.relation_tuples.v1alpha2 import expand_service_pb2 as ory_dot_keto_dot_relation__tuples_dot_v1alpha2_dot_expand__service__pb2  # noqa: E501,F401

_PKG = "ory.keto.relation_tuples.v1alpha2"
_F = _dpb.FieldDescriptorProto


def _file_descriptor() -> bytes:
    fd = _dpb.FileDescriptorProto()
    fd.name = "ory/keto/relation_tuples/v1alpha2/batch_service.proto"
    fd.package = _PKG
    fd.syntax = "proto3"
    fd.dependency.append(
        "ory/keto/relation_tuples/v1alpha2/relation_tuples.proto"
    )
    fd.dependency.append(
        "ory/keto/relation_tuples/v1alpha2/expand_service.proto"
    )

    def field(msg, name, number, ftype, type_name="", repeated=False):
        f = msg.field.add()
        f.name = name
        f.number = number
        f.label = _F.LABEL_REPEATED if repeated else _F.LABEL_OPTIONAL
        f.type = ftype
        if type_name:
            f.type_name = type_name
        f.json_name = name
        return f

    req = fd.message_type.add()
    req.name = "BatchCheckRequest"
    field(req, "tuples", 1, _F.TYPE_MESSAGE, f".{_PKG}.RelationTuple",
          repeated=True)
    # ONE consistency mode for the whole batch: every verdict is computed
    # against the same snapshot
    field(req, "snaptoken", 2, _F.TYPE_STRING)
    field(req, "latest", 3, _F.TYPE_BOOL)
    field(req, "max_depth", 4, _F.TYPE_INT32)

    item = fd.message_type.add()
    item.name = "BatchCheckResponseItem"
    field(item, "allowed", 1, _F.TYPE_BOOL)
    # per-item error isolation: status!=0 carries the item's HTTP-shaped
    # status code (400 bad tuple, 504 deadline, ...) without failing the
    # batch; allowed is meaningless for such items
    field(item, "error", 2, _F.TYPE_STRING)
    field(item, "status", 3, _F.TYPE_INT32)

    resp = fd.message_type.add()
    resp.name = "BatchCheckResponse"
    field(resp, "results", 1, _F.TYPE_MESSAGE,
          f".{_PKG}.BatchCheckResponseItem", repeated=True)
    field(resp, "snaptoken", 2, _F.TYPE_STRING)

    ereq = fd.message_type.add()
    ereq.name = "BatchExpandRequest"
    field(ereq, "subjects", 1, _F.TYPE_MESSAGE, f".{_PKG}.SubjectSet",
          repeated=True)
    field(ereq, "snaptoken", 2, _F.TYPE_STRING)
    field(ereq, "latest", 3, _F.TYPE_BOOL)
    field(ereq, "max_depth", 4, _F.TYPE_INT32)

    eitem = fd.message_type.add()
    eitem.name = "BatchExpandResponseItem"
    field(eitem, "tree", 1, _F.TYPE_MESSAGE, f".{_PKG}.SubjectTree")
    field(eitem, "error", 2, _F.TYPE_STRING)
    field(eitem, "status", 3, _F.TYPE_INT32)

    eresp = fd.message_type.add()
    eresp.name = "BatchExpandResponse"
    field(eresp, "results", 1, _F.TYPE_MESSAGE,
          f".{_PKG}.BatchExpandResponseItem", repeated=True)
    field(eresp, "snaptoken", 2, _F.TYPE_STRING)
    return fd.SerializeToString()


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile(_file_descriptor())

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(
    DESCRIPTOR, "ory.keto.relation_tuples.v1alpha2.batch_service_pb2",
    globals(),
)
