# -*- coding: utf-8 -*-
# source: ory/keto/relation_tuples/v1alpha2/stream_service.proto
"""Protobuf bindings for the StreamCheck session wire messages.

The streaming check session is an EXTENSION over the vendored reference
contract (Keto at this version has no streaming RPCs), so there is no
upstream generated module to vendor.  Like batch_service_pb2, the module
assembles the FileDescriptorProto programmatically and feeds it through
the exact AddSerializedFile + builder path protoc output uses.  The
human-readable source lives at
proto/ory/keto/relation_tuples/v1alpha2/stream_service.proto.

Only messages are declared here: the RPC itself rides on the EXISTING
CheckService (as a StreamCheck bidi method) — the method registration
authority is ketotpu.proto.services.SERVICES, which gRPC consults
instead of the descriptor pool.
"""
from google.protobuf import descriptor_pb2 as _dpb
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
from google.protobuf.internal import builder as _builder

_sym_db = _symbol_database.Default()

# dependencies must be registered in the pool before this file is added
from ory.keto.relation_tuples.v1alpha2 import relation_tuples_pb2 as ory_dot_keto_dot_relation__tuples_dot_v1alpha2_dot_relation__tuples__pb2  # noqa: E501,F401
from ory.keto.relation_tuples.v1alpha2 import batch_service_pb2 as ory_dot_keto_dot_relation__tuples_dot_v1alpha2_dot_batch__service__pb2  # noqa: E501,F401

_PKG = "ory.keto.relation_tuples.v1alpha2"
_F = _dpb.FieldDescriptorProto


def _file_descriptor() -> bytes:
    fd = _dpb.FileDescriptorProto()
    fd.name = "ory/keto/relation_tuples/v1alpha2/stream_service.proto"
    fd.package = _PKG
    fd.syntax = "proto3"
    fd.dependency.append(
        "ory/keto/relation_tuples/v1alpha2/relation_tuples.proto"
    )
    fd.dependency.append(
        "ory/keto/relation_tuples/v1alpha2/batch_service.proto"
    )

    def field(msg, name, number, ftype, type_name="", repeated=False):
        f = msg.field.add()
        f.name = name
        f.number = number
        f.label = _F.LABEL_REPEATED if repeated else _F.LABEL_OPTIONAL
        f.type = ftype
        if type_name:
            f.type_name = type_name
        f.json_name = name
        return f

    req = fd.message_type.add()
    req.name = "StreamCheckRequest"
    # handshake (first message only): session-wide consistency mode +
    # requested admission weight
    field(req, "open", 1, _F.TYPE_BOOL)
    field(req, "units", 2, _F.TYPE_UINT32)
    field(req, "snaptoken", 3, _F.TYPE_STRING)
    field(req, "latest", 4, _F.TYPE_BOOL)
    field(req, "max_depth", 5, _F.TYPE_INT32)
    # block: per-session sequence number + the columnar tuple payload
    field(req, "seq", 6, _F.TYPE_UINT64)
    field(req, "tuples", 7, _F.TYPE_MESSAGE, f".{_PKG}.RelationTuple",
          repeated=True)
    field(req, "close", 8, _F.TYPE_BOOL)

    resp = fd.message_type.add()
    resp.name = "StreamCheckResponse"
    # handshake reply: session id + granted block credits; error/status
    # carry a REFUSAL (brownout 429, session cap 507) with the
    # retry_after_s backoff hint
    field(resp, "session", 1, _F.TYPE_STRING)
    field(resp, "credits", 2, _F.TYPE_UINT32)
    field(resp, "max_block_rows", 3, _F.TYPE_UINT32)
    # verdict block: seq echoes the request block; results are
    # row-aligned with its tuples (per-item error isolation)
    field(resp, "seq", 4, _F.TYPE_UINT64)
    field(resp, "results", 5, _F.TYPE_MESSAGE,
          f".{_PKG}.BatchCheckResponseItem", repeated=True)
    field(resp, "snaptoken", 6, _F.TYPE_STRING)
    field(resp, "error", 7, _F.TYPE_STRING)
    field(resp, "status", 8, _F.TYPE_INT32)
    field(resp, "retry_after_s", 9, _F.TYPE_UINT32)
    return fd.SerializeToString()


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile(_file_descriptor())

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(
    DESCRIPTOR, "ory.keto.relation_tuples.v1alpha2.stream_service_pb2",
    globals(),
)
