# -*- coding: utf-8 -*-
# source: ory/keto/relation_tuples/v1alpha2/watch_service.proto
"""Protobuf bindings for WatchService (the Zanzibar Watch API extension).

This service is NOT part of the vendored reference contract — Keto at this
version has no Watch RPC — so there is no upstream generated module to
vendor.  `protoc` is unavailable in this environment; instead of a
pre-serialized descriptor blob the module assembles the
FileDescriptorProto programmatically and feeds it through the exact
AddSerializedFile + builder path protoc output uses, so the registered
messages are indistinguishable from generated ones.  The human-readable
source lives at proto/ory/keto/relation_tuples/v1alpha2/watch_service.proto.
"""
from google.protobuf import descriptor_pb2 as _dpb
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
from google.protobuf.internal import builder as _builder

_sym_db = _symbol_database.Default()

# the dependency must be registered in the pool before this file is added
from ory.keto.relation_tuples.v1alpha2 import relation_tuples_pb2 as ory_dot_keto_dot_relation__tuples_dot_v1alpha2_dot_relation__tuples__pb2  # noqa: E501,F401

_PKG = "ory.keto.relation_tuples.v1alpha2"
_F = _dpb.FieldDescriptorProto


def _file_descriptor() -> bytes:
    fd = _dpb.FileDescriptorProto()
    fd.name = "ory/keto/relation_tuples/v1alpha2/watch_service.proto"
    fd.package = _PKG
    fd.syntax = "proto3"
    fd.dependency.append(
        "ory/keto/relation_tuples/v1alpha2/relation_tuples.proto"
    )

    def field(msg, name, number, ftype, type_name=""):
        f = msg.field.add()
        f.name = name
        f.number = number
        f.label = _F.LABEL_OPTIONAL
        f.type = ftype
        if type_name:
            f.type_name = type_name
        f.json_name = name
        return f

    req = fd.message_type.add()
    req.name = "WatchRelationTuplesRequest"
    # resume cursor: replay the changelog suffix after this token first
    field(req, "snaptoken", 1, _F.TYPE_STRING)
    # optional server-side namespace filter
    field(req, "namespace", 2, _F.TYPE_STRING)

    resp = fd.message_type.add()
    resp.name = "WatchRelationTuplesResponse"
    # event: "delta" | "heartbeat" | "resync_required"
    field(resp, "event", 1, _F.TYPE_STRING)
    # action: "insert" | "delete" (delta events only)
    field(resp, "action", 2, _F.TYPE_STRING)
    field(resp, "relation_tuple", 3, _F.TYPE_MESSAGE,
          f".{_PKG}.RelationTuple")
    # resume cursor valid after this event
    field(resp, "snaptoken", 4, _F.TYPE_STRING)

    svc = fd.service.add()
    svc.name = "WatchService"
    m = svc.method.add()
    m.name = "Watch"
    m.input_type = f".{_PKG}.WatchRelationTuplesRequest"
    m.output_type = f".{_PKG}.WatchRelationTuplesResponse"
    m.server_streaming = True
    return fd.SerializeToString()


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile(_file_descriptor())

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(
    DESCRIPTOR, "ory.keto.relation_tuples.v1alpha2.watch_service_pb2",
    globals(),
)
