"""Hand-written gRPC service glue for the vendored Keto wire contract.

`grpc_tools` (the protoc Python gRPC plugin) is not available in this
environment, so the servicer/stub scaffolding that `*_pb2_grpc.py` files
would normally carry is written out here instead.  The wire behavior is
identical: full method names, request/response serializers, and unary-unary
handlers exactly as the reference's generated Go bindings expose them
(`proto/ory/keto/relation_tuples/v1alpha2/*_grpc.pb.go`).

Service inventory (SURVEY §2 proto row):
  CheckService.Check                       check_service.proto:18-21
  ExpandService.Expand                     expand_service.proto:18-21
  ReadService.ListRelationTuples           read_service.proto:18-21
  WriteService.{Transact,Delete}RelationTuples   write_service.proto:17-22
  NamespacesService.ListNamespaces         namespaces_service.proto:15-18
  VersionService.GetVersion                version.proto:15-18
  SyntaxService.Check                      opl/v1alpha1/syntax_service.proto:13-16
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple, Type

import grpc

from ketotpu.proto import (
    batch_service_pb2,
    check_service_pb2,
    expand_service_pb2,
    health_pb2,
    namespaces_service_pb2,
    read_service_pb2,
    stream_service_pb2,
    syntax_service_pb2,
    version_pb2,
    watch_service_pb2,
    write_service_pb2,
)

_RTS = "ory.keto.relation_tuples.v1alpha2"
_OPL = "ory.keto.opl.v1alpha1"

# service name -> {method: (request type, response type)}
SERVICES: Dict[str, Dict[str, Tuple[Type, Type]]] = {
    f"{_RTS}.CheckService": {
        "Check": (check_service_pb2.CheckRequest, check_service_pb2.CheckResponse),
        # EXTENSION: first-class batched checks — one RPC, many verdicts,
        # one shared consistency mode + snaptoken for the whole batch
        # (proto/ory/keto/relation_tuples/v1alpha2/batch_service.proto).
        # Served on the columnar path by default (engine/columns.py): the
        # parsed tuples become one ColumnBlock and the verdict array
        # scatters back into per-item results.
        "BatchCheck": (
            batch_service_pb2.BatchCheckRequest,
            batch_service_pb2.BatchCheckResponse,
        ),
        # EXTENSION: streaming check session — one bidi stream per
        # session, admitted ONCE at the handshake; the client pumps
        # columnar blocks with per-block sequence numbers and verdict
        # blocks come back out-of-order as engine waves complete
        # (proto/ory/keto/relation_tuples/v1alpha2/stream_service.proto,
        # server/session.py)
        "StreamCheck": (
            stream_service_pb2.StreamCheckRequest,
            stream_service_pb2.StreamCheckResponse,
            "bidi_stream",
        ),
    },
    f"{_RTS}.ExpandService": {
        "Expand": (expand_service_pb2.ExpandRequest, expand_service_pb2.ExpandResponse),
        # EXTENSION: batched expansion trees, same batch semantics
        "BatchExpand": (
            batch_service_pb2.BatchExpandRequest,
            batch_service_pb2.BatchExpandResponse,
        ),
    },
    f"{_RTS}.ReadService": {
        "ListRelationTuples": (
            read_service_pb2.ListRelationTuplesRequest,
            read_service_pb2.ListRelationTuplesResponse,
        ),
        # Leopard reverse-query APIs: ListObjects enumerates objects a
        # subject reaches through the closure; ListSubjects enumerates a
        # node's element set.  Both reuse the ListRelationTuples wire
        # shapes — the relation_query carries the fixed coordinates and
        # each result row comes back as a full relation tuple.
        "ListObjects": (
            read_service_pb2.ListRelationTuplesRequest,
            read_service_pb2.ListRelationTuplesResponse,
        ),
        "ListSubjects": (
            read_service_pb2.ListRelationTuplesRequest,
            read_service_pb2.ListRelationTuplesResponse,
        ),
    },
    f"{_RTS}.WatchService": {
        # EXTENSION: the Zanzibar Watch API (no reference analog at this
        # version) — server-streaming change feed with snaptoken resume
        # (proto/ory/keto/relation_tuples/v1alpha2/watch_service.proto)
        "Watch": (
            watch_service_pb2.WatchRelationTuplesRequest,
            watch_service_pb2.WatchRelationTuplesResponse,
            "server_stream",
        ),
    },
    f"{_RTS}.WriteService": {
        "TransactRelationTuples": (
            write_service_pb2.TransactRelationTuplesRequest,
            write_service_pb2.TransactRelationTuplesResponse,
        ),
        "DeleteRelationTuples": (
            write_service_pb2.DeleteRelationTuplesRequest,
            write_service_pb2.DeleteRelationTuplesResponse,
        ),
    },
    f"{_RTS}.NamespacesService": {
        "ListNamespaces": (
            namespaces_service_pb2.ListNamespacesRequest,
            namespaces_service_pb2.ListNamespacesResponse,
        ),
    },
    f"{_RTS}.VersionService": {
        "GetVersion": (version_pb2.GetVersionRequest, version_pb2.GetVersionResponse),
    },
    f"{_OPL}.SyntaxService": {
        "Check": (syntax_service_pb2.CheckRequest, syntax_service_pb2.CheckResponse),
    },
    "grpc.health.v1.Health": {
        "Check": (
            health_pb2.HealthCheckRequest,
            health_pb2.HealthCheckResponse,
        ),
        # server-streaming: yields the current status, then every change
        # (grpc/health/v1/health.proto Watch)
        "Watch": (
            health_pb2.HealthCheckRequest,
            health_pb2.HealthCheckResponse,
            "server_stream",
        ),
    },
}


def add_servicer_to_server(service_name: str, servicer, server) -> None:
    """Register ``servicer`` (an object with one method per RPC) for
    ``service_name`` on a `grpc.Server` / `grpc.aio.Server`."""
    methods = SERVICES[service_name]
    handlers = {}
    for method, spec in methods.items():
        req_t, resp_t = spec[0], spec[1]
        if "bidi_stream" in spec[2:]:
            make = grpc.stream_stream_rpc_method_handler
        elif "server_stream" in spec[2:]:
            make = grpc.unary_stream_rpc_method_handler
        else:
            make = grpc.unary_unary_rpc_method_handler
        handlers[method] = make(
            getattr(servicer, method),
            request_deserializer=req_t.FromString,
            response_serializer=resp_t.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service_name, handlers),)
    )


class _Stub:
    """Client stub: one callable per RPC method (unary, server-stream,
    or bidi-stream)."""

    def __init__(self, channel: grpc.Channel, service_name: str):
        for method, spec in SERVICES[service_name].items():
            req_t, resp_t = spec[0], spec[1]
            if "bidi_stream" in spec[2:]:
                make = channel.stream_stream
            elif "server_stream" in spec[2:]:
                make = channel.unary_stream
            else:
                make = channel.unary_unary
            setattr(
                self,
                method,
                make(
                    f"/{service_name}/{method}",
                    request_serializer=req_t.SerializeToString,
                    response_deserializer=resp_t.FromString,
                ),
            )


def _stub_class(service: str) -> Callable[[grpc.Channel], _Stub]:
    def make(channel: grpc.Channel) -> _Stub:
        return _Stub(channel, service)

    return make


CheckServiceStub = _stub_class(f"{_RTS}.CheckService")
ExpandServiceStub = _stub_class(f"{_RTS}.ExpandService")
ReadServiceStub = _stub_class(f"{_RTS}.ReadService")
WatchServiceStub = _stub_class(f"{_RTS}.WatchService")
WriteServiceStub = _stub_class(f"{_RTS}.WriteService")
NamespacesServiceStub = _stub_class(f"{_RTS}.NamespacesService")
VersionServiceStub = _stub_class(f"{_RTS}.VersionService")
SyntaxServiceStub = _stub_class(f"{_OPL}.SyntaxService")

CHECK_SERVICE = f"{_RTS}.CheckService"
EXPAND_SERVICE = f"{_RTS}.ExpandService"
READ_SERVICE = f"{_RTS}.ReadService"
WATCH_SERVICE = f"{_RTS}.WatchService"
WRITE_SERVICE = f"{_RTS}.WriteService"
NAMESPACES_SERVICE = f"{_RTS}.NamespacesService"
VERSION_SERVICE = f"{_RTS}.VersionService"
SYNTAX_SERVICE = f"{_OPL}.SyntaxService"
