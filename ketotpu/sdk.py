"""Python client SDK over the REST API.

The reference ships a generated Swagger SDK (`internal/httpclient/`,
regenerated from `spec/api.json`) that its e2e suite drives as the fourth
transport (`internal/e2e/full_suit_test.go:65-94`).  This is the same
artifact for this framework: a typed client over the public REST surface,
returning the package's own API types (`ketotpu.api.types`) and raising
its typed errors on failure.

Stdlib-only (urllib), synchronous, one class per API port pairing:

    sdk = KetoClient("http://127.0.0.1:4466", "http://127.0.0.1:4467")
    sdk.check("File", "doc", "view", SubjectID("alice"))    -> bool
    sdk.expand(SubjectSet("File", "doc", "view"))           -> Tree | None
    sdk.list_relation_tuples(RelationQuery(namespace="n"))  -> (rows, token)
    sdk.create_relation_tuple(t) / sdk.delete_relation_tuple(t)
    sdk.patch([("insert", t), ("delete", u)])
    sdk.delete_relation_tuples(RelationQuery(...))
    sdk.check_opl_syntax(source)                            -> [errors]
"""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import List, Optional, Sequence, Tuple

from ketotpu.server.overload import RetryBudget

from ketotpu.api.types import (
    BadRequestError,
    KetoAPIError,
    NotFoundError,
    RelationQuery,
    RelationTuple,
    StaleSnapshotError,
    Subject,
    SubjectID,
    SubjectSet,
    Tree,
    subject_from_string,
)


class SDKError(KetoAPIError):
    """Non-2xx response that maps to no specific API error."""

    def __init__(self, status: int, body: str):
        super().__init__(f"unexpected status {status}: {body[:200]}")
        self.status = status
        self.body = body


class KetoClient:
    def __init__(
        self,
        read_url: str,
        write_url: Optional[str] = None,
        *,
        opl_url: Optional[str] = None,
        timeout: float = 30.0,
        max_retries: int = 2,
        retry_budget_ratio: float = 0.1,
    ):
        self.read_url = read_url.rstrip("/")
        self.write_url = (write_url or read_url).rstrip("/")
        self.opl_url = (opl_url or read_url).rstrip("/")
        self.timeout = timeout
        #: snaptoken minted by the most recent write on this client
        #: (X-Keto-Snaptoken response header); feed it back into
        #: ``check(..., snaptoken=...)`` for read-your-writes
        self.last_snaptoken: Optional[str] = None
        # cooperative retry protocol: a 429/503 is retried, honoring the
        # server's Retry-After hint (jittered, capped by the remaining
        # client timeout) — but only within a token-bucket retry budget
        # (retries capped to a fraction of successes), so a fleet of
        # SDKs cannot amplify an overload into a retry storm.
        # max_retries=0 disables retries entirely.
        self.max_retries = max(0, int(max_retries))
        self.retry_budget = RetryBudget(ratio=retry_budget_ratio)
        self.retries = 0  # observability: retries actually performed

    # -- transport ----------------------------------------------------------

    def _request_once(
        self, method: str, url: str, body: Optional[dict | list] = None
    ) -> Tuple[int, str, dict]:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url,
            data=data,
            method=method,
            headers={"Content-Type": "application/json"} if data else {},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                token = resp.headers.get("X-Keto-Snaptoken")
                if token:
                    self.last_snaptoken = token
                return resp.status, resp.read().decode(), dict(resp.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode(), dict(e.headers or {})

    @staticmethod
    def _retry_delay(headers: dict, attempt: int) -> float:
        """Backoff before a retry: the server's Retry-After hint when it
        sent one (already jittered server-side), exponential backoff
        otherwise — re-jittered here so a shed cohort spreads out."""
        hint = 0.0
        for k, v in headers.items():
            if str(k).lower() == "retry-after":
                try:
                    hint = float(v)
                except (TypeError, ValueError):
                    hint = 0.0
                break
        if hint <= 0.0:
            hint = 0.25 * (2 ** attempt)
        return hint * (0.5 + random.random() * 0.5)

    def _request(
        self, method: str, url: str, body: Optional[dict | list] = None
    ) -> Tuple[int, str]:
        from ketotpu import faults

        status, text, headers = self._request_once(method, url, body)
        for attempt in range(self.max_retries):
            if status not in (429, 503):
                break
            storm = faults.should("retry_storm")
            if not storm and not self.retry_budget.allow_retry():
                break  # budget dry: surface the 429/503 as-is
            delay = 0.0 if storm else min(
                self._retry_delay(headers, attempt), max(0.0, self.timeout)
            )
            if delay > 0.0:
                time.sleep(delay)
            self.retries += 1
            status, text, headers = self._request_once(method, url, body)
        if status < 500 and status != 429:
            self.retry_budget.record_success()
        return status, text

    @staticmethod
    def _raise_for(status: int, body: str):
        if status == 400:
            raise BadRequestError(_error_message(body))
        if status == 404:
            raise NotFoundError(_error_message(body))
        if status == 412:
            raise StaleSnapshotError(_error_message(body))
        raise SDKError(status, body)

    # -- check --------------------------------------------------------------

    def check(
        self,
        namespace: str,
        object: str,
        relation: str,
        subject: Subject,
        *,
        max_depth: int = 0,
        snaptoken: Optional[str] = None,
        latest: bool = False,
    ) -> bool:
        """Permission check via the non-mirroring openapi variant
        (`getCheckNoStatus`, check/handler.go:156): unknown namespace is
        ``False``, not an error.

        ``snaptoken`` requests an at-least-as-fresh read (the server
        raises :class:`StaleSnapshotError` if it cannot catch up in the
        request budget); ``latest=True`` forces a fully fresh read."""
        r = RelationTuple(namespace, object, relation, subject)
        params = dict(r.to_url_query())
        if max_depth:
            params["max-depth"] = str(max_depth)
        if snaptoken:
            params["snaptoken"] = snaptoken
        if latest:
            params["latest"] = "true"
        q = urllib.parse.urlencode(params)
        status, body = self._request(
            "GET", f"{self.read_url}/relation-tuples/check/openapi?{q}"
        )
        if status != 200:
            self._raise_for(status, body)
        return bool(json.loads(body)["allowed"])

    def check_tuple(
        self,
        t: RelationTuple,
        *,
        max_depth: int = 0,
        snaptoken: Optional[str] = None,
        latest: bool = False,
    ) -> bool:
        return self.check(
            t.namespace, t.object, t.relation, t.subject,
            max_depth=max_depth, snaptoken=snaptoken, latest=latest,
        )

    @staticmethod
    def _consistency_fields(
        consistency: Optional[str], snaptoken: Optional[str], latest: bool,
    ) -> dict:
        """One consistency mode for a whole batch: ``consistency`` is
        either the string ``"latest"`` or a snaptoken (explicit
        ``snaptoken=``/``latest=`` kwargs still work)."""
        out: dict = {}
        if consistency == "latest" or latest:
            out["latest"] = True
        elif consistency:
            out["snaptoken"] = consistency
        if snaptoken and "snaptoken" not in out:
            out["snaptoken"] = snaptoken
        return out

    def batch_check_results(
        self,
        tuples: Sequence[RelationTuple],
        *,
        max_depth: int = 0,
        consistency: Optional[str] = None,
        snaptoken: Optional[str] = None,
        latest: bool = False,
    ) -> List[dict]:
        """Per-item verdicts for many checks in ONE request (batch front
        door, POST /relation-tuples/batch/check).  Each result is either
        ``{"allowed": bool}`` or ``{"error": str, "status": int}`` — a bad
        item never poisons its neighbours.  The whole batch shares one
        consistency mode and one deadline budget.  Items may be
        ``RelationTuple`` objects, already-encoded JSON dicts, or
        canonical ``"Ns:obj#rel@subject"`` strings (the same forms the
        CLI's ``check --batch`` jsonl accepts)."""
        payload: dict = {
            "tuples": [
                t if isinstance(t, dict)
                else RelationTuple.from_string(t).to_json()
                if isinstance(t, str)
                else t.to_json()
                for t in tuples
            ]
        }
        if max_depth:
            payload["max_depth"] = max_depth
        payload.update(
            self._consistency_fields(consistency, snaptoken, latest)
        )
        status, body = self._request(
            "POST", f"{self.read_url}/relation-tuples/batch/check", payload
        )
        if status != 200:
            self._raise_for(status, body)
        data = json.loads(body)
        if data.get("snaptoken"):
            self.last_snaptoken = data["snaptoken"]
        return list(data["results"])

    def batch_check(
        self,
        tuples: Sequence[RelationTuple],
        *,
        max_depth: int = 0,
        consistency: Optional[str] = None,
        snaptoken: Optional[str] = None,
        latest: bool = False,
    ) -> List[bool]:
        """Many checks in one request over the batch front door; the TPU
        engine answers the whole list in fused device dispatches.  Returns
        one verdict per tuple; a per-item error raises its typed error
        (use :meth:`batch_check_results` for per-item isolation)."""
        results = self.batch_check_results(
            tuples, max_depth=max_depth, consistency=consistency,
            snaptoken=snaptoken, latest=latest,
        )
        out: List[bool] = []
        for r in results:
            if "error" in r:
                self._raise_for(
                    int(r.get("status", 500)), json.dumps(r)
                )
            out.append(bool(r["allowed"]))
        return out

    def batch_check_columns(
        self,
        namespaces: Sequence[str],
        objects: Sequence[str],
        relations: Sequence[str],
        subjects: Sequence,
        *,
        max_depth: int = 0,
        consistency: Optional[str] = None,
        snaptoken: Optional[str] = None,
        latest: bool = False,
    ) -> List[bool]:
        """Column-form convenience over the batch front door: four
        parallel sequences build the wire payload in one pass, so a
        caller already holding columnar data (a dataframe, a log scan)
        never constructs RelationTuples.  ``subjects`` entries may be
        subject-id strings, ``SubjectID``/``SubjectSet`` objects, or
        ``{"namespace","object","relation"}`` dicts (subject sets).
        The server answers on its columnar path; one verdict per row, a
        per-item error raises its typed error."""
        n = len(namespaces)
        if not (len(objects) == n and len(relations) == n
                and len(subjects) == n):
            raise ValueError("column lengths differ")
        items = []
        for i in range(n):
            s = subjects[i]
            d = {
                "namespace": namespaces[i],
                "object": objects[i],
                "relation": relations[i],
            }
            if isinstance(s, SubjectSet):
                d["subject_set"] = {
                    "namespace": s.namespace,
                    "object": s.object,
                    "relation": s.relation,
                }
            elif isinstance(s, SubjectID):
                d["subject_id"] = s.id
            elif isinstance(s, dict):
                d["subject_set"] = s
            else:
                d["subject_id"] = str(s)
            items.append(d)
        results = self.batch_check_results(
            items, max_depth=max_depth, consistency=consistency,
            snaptoken=snaptoken, latest=latest,
        )
        out: List[bool] = []
        for r in results:
            if "error" in r:
                self._raise_for(int(r.get("status", 500)), json.dumps(r))
            out.append(bool(r["allowed"]))
        return out

    # -- expand -------------------------------------------------------------

    def expand(
        self,
        subject_set: SubjectSet,
        *,
        max_depth: int = 0,
        snaptoken: Optional[str] = None,
        latest: bool = False,
    ) -> Optional[Tree]:
        params = {
            "namespace": subject_set.namespace,
            "object": subject_set.object,
            "relation": subject_set.relation,
        }
        if max_depth:
            params["max-depth"] = str(max_depth)
        if snaptoken:
            params["snaptoken"] = snaptoken
        if latest:
            params["latest"] = "true"
        q = urllib.parse.urlencode(params)
        status, body = self._request(
            "GET", f"{self.read_url}/relation-tuples/expand?{q}"
        )
        if status == 404:
            return None  # empty expansion (expand/handler.go:98-101)
        if status != 200:
            self._raise_for(status, body)
        return Tree.from_json(json.loads(body))

    def batch_expand_results(
        self,
        subject_sets: Sequence[SubjectSet],
        *,
        max_depth: int = 0,
        consistency: Optional[str] = None,
        snaptoken: Optional[str] = None,
        latest: bool = False,
    ) -> List[dict]:
        """Per-item trees for many expansions in ONE request (batch front
        door, POST /relation-tuples/batch/expand).  Each result is either
        ``{"tree": {...}}`` or ``{"error": str, "status": int}`` (an empty
        expansion is a per-item 404, matching the single endpoint)."""
        payload: dict = {"subjects": [
            {
                "namespace": s.namespace,
                "object": s.object,
                "relation": s.relation,
            }
            for s in subject_sets
        ]}
        if max_depth:
            payload["max_depth"] = max_depth
        payload.update(
            self._consistency_fields(consistency, snaptoken, latest)
        )
        status, body = self._request(
            "POST", f"{self.read_url}/relation-tuples/batch/expand", payload
        )
        if status != 200:
            self._raise_for(status, body)
        data = json.loads(body)
        if data.get("snaptoken"):
            self.last_snaptoken = data["snaptoken"]
        return list(data["results"])

    def batch_expand(
        self,
        subject_sets: Sequence[SubjectSet],
        *,
        max_depth: int = 0,
        consistency: Optional[str] = None,
        snaptoken: Optional[str] = None,
        latest: bool = False,
    ) -> List[Optional[Tree]]:
        """Many expansions in one request.  Returns one ``Tree`` (or
        ``None`` for an empty expansion) per subject set; a non-404
        per-item error raises its typed error."""
        out: List[Optional[Tree]] = []
        for r in self.batch_expand_results(
            subject_sets, max_depth=max_depth, consistency=consistency,
            snaptoken=snaptoken, latest=latest,
        ):
            if "error" in r:
                if int(r.get("status", 500)) == 404:
                    out.append(None)
                    continue
                self._raise_for(int(r.get("status", 500)), json.dumps(r))
            else:
                out.append(Tree.from_json(r["tree"]))
        return out

    # -- relation tuples ----------------------------------------------------

    def list_relation_tuples(
        self,
        query: Optional[RelationQuery] = None,
        *,
        page_token: str = "",
        page_size: int = 0,
    ) -> Tuple[List[RelationTuple], str]:
        params = dict(query.to_url_query()) if query is not None else {}
        if page_token:
            params["page_token"] = page_token
        if page_size:
            params["page_size"] = str(page_size)
        q = urllib.parse.urlencode(params)
        status, body = self._request(
            "GET", f"{self.read_url}/relation-tuples?{q}"
        )
        if status != 200:
            self._raise_for(status, body)
        data = json.loads(body)
        return (
            [RelationTuple.from_json(d) for d in data["relation_tuples"]],
            data.get("next_page_token", ""),
        )

    # -- Leopard listing APIs (reverse queries) -----------------------------

    def list_objects(
        self,
        namespace: str,
        relation: str,
        subject: "Subject | str",
        *,
        page_token: str = "",
        page_size: int = 0,
    ) -> Tuple[List[str], str]:
        """Objects the subject reaches in ``namespace#relation`` through
        set containment (GET /relation-tuples/list-objects, answered from
        the engine's closure index).  Returns (objects, next_page_token).

        ``subject`` may be a ``Subject`` or its string form ("alice",
        "Group:eng#members")."""
        if isinstance(subject, str):
            subject = subject_from_string(subject)
        params = dict(
            RelationQuery(
                namespace=namespace, relation=relation
            ).with_subject(subject).to_url_query()
        )
        if page_token:
            params["page_token"] = page_token
        if page_size:
            params["page_size"] = str(page_size)
        q = urllib.parse.urlencode(params)
        status, body = self._request(
            "GET", f"{self.read_url}/relation-tuples/list-objects?{q}"
        )
        if status != 200:
            self._raise_for(status, body)
        data = json.loads(body)
        objs = data.get("objects")
        if objs is None:
            objs = [
                RelationTuple.from_json(d).object
                for d in data["relation_tuples"]
            ]
        return list(objs), data.get("next_page_token", "")

    def list_subjects(
        self,
        namespace: str,
        object: str,
        relation: str,
        *,
        page_token: str = "",
        page_size: int = 0,
    ) -> Tuple[List[Subject], str]:
        """Subjects reaching ``namespace:object#relation`` (GET
        /relation-tuples/list-subjects).  Returns (subjects, token)."""
        params = {
            "namespace": namespace, "object": object, "relation": relation,
        }
        if page_token:
            params["page_token"] = page_token
        if page_size:
            params["page_size"] = str(page_size)
        q = urllib.parse.urlencode(params)
        status, body = self._request(
            "GET", f"{self.read_url}/relation-tuples/list-subjects?{q}"
        )
        if status != 200:
            self._raise_for(status, body)
        data = json.loads(body)
        return (
            [
                RelationTuple.from_json(d).subject
                for d in data["relation_tuples"]
            ],
            data.get("next_page_token", ""),
        )

    def create_relation_tuple(self, t: RelationTuple) -> RelationTuple:
        status, body = self._request(
            "PUT", f"{self.write_url}/admin/relation-tuples", t.to_json()
        )
        if status not in (200, 201):
            self._raise_for(status, body)
        return RelationTuple.from_json(json.loads(body))

    def delete_relation_tuple(self, t: RelationTuple) -> None:
        self._delete(t.to_url_query())

    def delete_relation_tuples(self, query: RelationQuery) -> None:
        """Delete everything the query matches (DELETE /admin/relation-tuples
        with query params, transact_server.go:72)."""
        self._delete(query.to_url_query())

    def _delete(self, params: dict) -> None:
        q = urllib.parse.urlencode(params)
        status, body = self._request(
            "DELETE", f"{self.write_url}/admin/relation-tuples?{q}"
        )
        if status != 204:
            self._raise_for(status, body)

    def patch(
        self, deltas: Sequence[Tuple[str, RelationTuple]]
    ) -> None:
        """PATCH /admin/relation-tuples with [{action, relation_tuple}]
        deltas; action is "insert" or "delete" (handler.go PATCH route)."""
        body = [
            {"action": action, "relation_tuple": t.to_json()}
            for action, t in deltas
        ]
        status, out = self._request(
            "PATCH", f"{self.write_url}/admin/relation-tuples", body
        )
        if status != 204:
            self._raise_for(status, out)

    # -- streaming sessions --------------------------------------------------

    def check_session(
        self,
        addr: Tuple[str, int],
        *,
        units: int = 0,
        consistency: Optional[str] = None,
        max_depth: int = 0,
        metadata: Optional[dict] = None,
    ) -> "CheckSession":
        """Open a streaming check session on the server's raw TCP session
        lane (server/session.py; address = ``Server.addresses["session"]``
        or the pinned ``session.port``).  Use as a context manager::

            with client.check_session((host, port)) as sess:
                for verdicts in sess.stream(blocks):   # in-order
                    ...
                # or out-of-order: seq = sess.submit(tuples);
                # sess.results() yields (seq, verdicts, errors)

        The session is admitted ONCE at the handshake (``units`` of
        interactive weight; 0 = server default) and shares one
        consistency mode (``consistency`` is ``"latest"`` or a
        snaptoken).  Handshake refusals (brownout/cap) are retried
        within this client's retry budget, honoring the server's
        Retry-After hint; a connection lost mid-stream reconnects the
        same way and REPLAYS every unacknowledged block — verdicts are
        acks, so no submitted block is ever silently lost."""
        return CheckSession(
            self, addr, units=units, consistency=consistency,
            max_depth=max_depth, metadata=metadata,
        )

    # -- watch --------------------------------------------------------------

    def watch(
        self,
        *,
        snaptoken: Optional[str] = None,
        namespace: Optional[str] = None,
        heartbeats: bool = False,
    ):
        """Stream relation-tuple changes (GET /relation-tuples/watch,
        server-sent events).  Yields dicts shaped like::

            {"event": "delta", "action": "insert",
             "relation_tuple": {...}, "snaptoken": "..."}

        ``snaptoken`` resumes from a previous position, replaying every
        change after it.  The stream ends after a terminal
        ``resync_required`` event (the cursor fell off the bounded
        changelog — re-list and subscribe fresh).  Heartbeat events are
        suppressed unless ``heartbeats=True``.  Iterate and ``close()``
        the returned generator (or break out of the loop) to detach."""
        params = {}
        if snaptoken:
            params["snaptoken"] = snaptoken
        if namespace:
            params["namespace"] = namespace
        url = f"{self.read_url}/relation-tuples/watch"
        if params:
            url += "?" + urllib.parse.urlencode(params)
        req = urllib.request.Request(url, method="GET")
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout)
        except urllib.error.HTTPError as e:
            body = e.read().decode()
            e.close()
            self._raise_for(e.code, body)

        def events():
            event, data = None, None
            try:
                for raw in resp:
                    line = raw.decode("utf-8").rstrip("\r\n")
                    if line.startswith(":"):
                        continue  # SSE comment / stream-open ping
                    if line.startswith("event:"):
                        event = line[6:].strip()
                    elif line.startswith("data:"):
                        data = line[5:].strip()
                    elif line == "" and event is not None:
                        out = json.loads(data) if data else {}
                        out["event"] = event
                        terminal = event == "resync_required"
                        skip = event == "heartbeat" and not heartbeats
                        event, data = None, None
                        if not skip:
                            yield out
                        if terminal:
                            return
            finally:
                resp.close()

        return events()

    # -- opl ----------------------------------------------------------------

    def check_opl_syntax(self, source: str) -> List[dict]:
        """Parse errors for an OPL document ([] = valid), POST
        /opl/syntax/check (schema/handler.go:31-45)."""
        req = urllib.request.Request(
            f"{self.opl_url}/opl/syntax/check",
            data=source.encode(),
            method="POST",
            headers={"Content-Type": "text/plain"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                status, body = resp.status, resp.read().decode()
        except urllib.error.HTTPError as e:
            status, body = e.code, e.read().decode()
        if status != 200:
            self._raise_for(status, body)
        return json.loads(body).get("errors", [])

    # -- meta ---------------------------------------------------------------

    def health(self) -> bool:
        status, _ = self._request("GET", f"{self.read_url}/health/ready")
        return status == 200

    def version(self) -> str:
        status, body = self._request("GET", f"{self.read_url}/version")
        if status != 200:
            self._raise_for(status, body)
        return json.loads(body)["version"]


class CheckSession:
    """Client half of the streaming session lane (see
    :meth:`KetoClient.check_session`).

    Synchronous, single-threaded: ``submit`` sends a block (blocking only
    when the server's credit window is full — it then reads one verdict
    to free a slot), ``results`` drains verdicts out of order,
    ``stream`` is the in-order convenience.  Every submitted block stays
    in ``_unacked`` until its verdict frame arrives; a dropped
    connection reconnects (retry-budget aware, Retry-After honored) and
    replays the unacked blocks on the fresh session."""

    def __init__(self, client: KetoClient, addr: Tuple[str, int], *,
                 units: int = 0, consistency: Optional[str] = None,
                 max_depth: int = 0, metadata: Optional[dict] = None):
        self._client = client
        self._addr = (str(addr[0]), int(addr[1]))
        self._units = int(units)
        self._latest = consistency == "latest"
        self._snaptoken = "" if self._latest else str(consistency or "")
        self._max_depth = int(max_depth)
        self._metadata = dict(metadata or {})
        self._sock: Optional[object] = None
        self._rfile = None
        self._seq = 0
        self._unacked: dict = {}     # seq -> (meta, arrays) to replay
        self._results: dict = {}     # seq -> (verdicts, errors) done
        self.session_id = ""
        self.credits = 1
        self.max_block_rows = 1 << 30
        self.reconnects = 0          # observability
        self._connect(replay=False)

    # -- context manager ----------------------------------------------

    def __enter__(self) -> "CheckSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- transport ----------------------------------------------------

    def _connect(self, *, replay: bool) -> None:
        import socket as _socket

        from ketotpu.server import wire

        attempt = 0
        while True:
            try:
                sock = _socket.create_connection(
                    self._addr, timeout=self._client.timeout)
                sock.setsockopt(
                    _socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
                hello: dict = {
                    "op": "hello", "v": 1, "units": self._units,
                    "snaptoken": self._snaptoken, "latest": self._latest,
                    "max_depth": self._max_depth,
                }
                if self._metadata:
                    hello["metadata"] = self._metadata
                wire.send_frame(sock, hello)
                rfile = sock.makefile("rb")
                got = wire.recv_frame(rfile)
                if got is None:
                    raise SDKError(503, "session lane closed at handshake")
                meta, _, _ = got
                if meta.get("ok"):
                    self._sock, self._rfile = sock, rfile
                    self.session_id = str(meta.get("session", ""))
                    self.credits = int(meta.get("credits", 1)) or 1
                    self.max_block_rows = int(
                        meta.get("max_block_rows", 0)) or (1 << 30)
                    break
                sock.close()
                status = int(meta.get("status", 503))
                headers = {"retry-after": meta.get("retry_after", 0)}
                err = str(meta.get("error", "session refused"))
            except OSError as e:
                status, headers, err = 503, {}, str(e)
            # refusal/conn-failure: cooperative retry, same protocol as
            # the HTTP front door (budget + jittered Retry-After)
            if (attempt >= self._client.max_retries
                    or status not in (429, 503, 507)
                    or not self._client.retry_budget.allow_retry()):
                raise SDKError(status, err)
            time.sleep(min(
                self._client._retry_delay(headers, attempt),
                max(0.0, self._client.timeout),
            ))
            self._client.retries += 1
            attempt += 1
        if replay:
            self.reconnects += 1
            for seq in sorted(self._unacked):
                meta, arrays = self._unacked[seq]
                self._send(meta, arrays, may_reconnect=False)

    def _send(self, meta: dict, arrays, *, may_reconnect: bool = True):
        from ketotpu.server import wire

        try:
            wire.send_frame(self._sock, meta, arrays)
        except OSError:
            if not may_reconnect:
                raise
            self._reconnect()

    def _reconnect(self) -> None:
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = self._rfile = None
        self._connect(replay=True)

    def _recv_one(self) -> bool:
        """Read ONE frame; file verdicts/errors; False when the current
        connection died (after reconnect+replay)."""
        from ketotpu.server import wire

        try:
            got = wire.recv_frame(self._rfile)
        except (OSError, wire.WireError):
            got = None
        if got is None:
            if not self._unacked:
                raise SDKError(503, "session lane closed")
            self._reconnect()
            return False
        meta, arrays, _ = got
        op = meta.get("op")
        if op == "verdicts":
            seq = int(meta["seq"])
            errors = {
                int(row): (str(msg), int(code))
                for row, msg, code in meta.get("errs") or ()
            }
            ok = arrays.get("ok")
            verdicts = [bool(v) for v in ok.tolist()] if ok is not None \
                else []
            if meta.get("snaptoken"):
                self._client.last_snaptoken = meta["snaptoken"]
            self._unacked.pop(seq, None)
            self._results[seq] = (verdicts, errors)
            return True
        if op == "error":
            seq = int(meta.get("seq", -1))
            self._unacked.pop(seq, None)
            self._results[seq] = (
                None,
                {-1: (str(meta.get("error", "block failed")),
                      int(meta.get("status", 500)))},
            )
            return True
        return True                  # pong/bye/unknown: ignore

    # -- encoding ------------------------------------------------------

    @staticmethod
    def _encode(tuples: Sequence) -> Tuple[int, dict]:
        import numpy as np

        from ketotpu.server import wire

        parsed = [
            RelationTuple.from_string(t) if isinstance(t, str) else t
            for t in tuples
        ]
        n = len(parsed)
        skind = np.zeros(n, dtype=np.uint8)
        ns, obj, rel = [], [], []
        sa, sb, sc = [], [], []
        for i, t in enumerate(parsed):
            ns.append(t.namespace)
            obj.append(t.object)
            rel.append(t.relation)
            s = t.subject
            if isinstance(s, SubjectSet):
                skind[i] = 1
                sa.append(s.namespace)
                sb.append(s.object)
                sc.append(s.relation or "")
            else:
                sa.append(s.id)
                sb.append("")
                sc.append("")
        arrays = {"skind": skind}
        for name, col in (("ns", ns), ("obj", obj), ("rel", rel),
                          ("sa", sa), ("sb", sb), ("sc", sc)):
            wire.pack_strcol(arrays, name, col)
        return n, arrays

    # -- public API ----------------------------------------------------

    def submit(self, tuples: Sequence, *, max_depth: int = 0,
               deadline_ms: int = 0) -> int:
        """Send one block (``RelationTuple`` objects or canonical
        strings); returns its seq.  Blocks only while the credit window
        is full — then drains one verdict first."""
        if not tuples:
            raise BadRequestError("empty block")
        if len(tuples) > self.max_block_rows:
            raise BadRequestError(
                f"block of {len(tuples)} rows exceeds server cap "
                f"{self.max_block_rows}")
        while len(self._unacked) >= self.credits:
            self._recv_one()
        n, arrays = self._encode(tuples)
        seq = self._seq
        self._seq += 1
        meta = {"op": "block", "seq": seq, "n": n}
        if max_depth:
            meta["max_depth"] = int(max_depth)
        if deadline_ms:
            meta["deadline_ms"] = int(deadline_ms)
        self._unacked[seq] = (meta, arrays)
        self._send(meta, arrays)
        return seq

    def results(self):
        """Yield ``(seq, verdicts, errors)`` OUT OF ORDER as verdict
        frames arrive, until every submitted block is answered.
        ``verdicts`` is None for a block-level failure (its error rides
        in ``errors[-1]``)."""
        while self._results or self._unacked:
            while not self._results:
                self._recv_one()
            seq = next(iter(self._results))
            verdicts, errors = self._results.pop(seq)
            yield seq, verdicts, errors

    def wait(self, seq: int):
        """Block until ``seq``'s verdicts arrive; returns
        ``(verdicts, errors)``."""
        while seq not in self._results:
            if seq not in self._unacked:
                raise BadRequestError(f"unknown seq {seq}")
            self._recv_one()
        return self._results.pop(seq)

    def stream(self, blocks, *, max_depth: int = 0):
        """Iterator in, verdicts out: submit each block from the
        iterable, yield each block's verdict list IN submission order
        (pipelined up to the credit window).  A block-level failure
        raises :class:`SDKError`."""
        pending: List[int] = []

        def pop_front():
            verdicts, errors = self.wait(pending.pop(0))
            if verdicts is None:
                msg, code = errors.get(-1, ("block failed", 500))
                raise SDKError(code, msg)
            return verdicts

        for block in blocks:
            pending.append(self.submit(block, max_depth=max_depth))
            # keep at most a window's worth pending so verdicts flow
            # out while blocks flow in
            while len(pending) > max(1, self.credits - 1):
                yield pop_front()
        while pending:
            yield pop_front()

    def close(self) -> None:
        """Graceful end: drain, say goodbye, drop the socket."""
        from ketotpu.server import wire

        if self._sock is None:
            return
        try:
            for _ in self.results():
                pass
            wire.send_frame(self._sock, {"op": "end"})
            while True:
                got = wire.recv_frame(self._rfile)
                if got is None or got[0].get("op") == "bye":
                    break
        except (OSError, wire.WireError, SDKError):
            pass
        finally:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = self._rfile = None


def _error_message(body: str) -> str:
    try:
        data = json.loads(body)
        if isinstance(data, dict):
            err = data.get("error", data)
            if isinstance(err, dict):
                return str(err.get("message", body))
            return str(err)
    except (json.JSONDecodeError, TypeError):
        pass
    return body
