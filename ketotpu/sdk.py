"""Python client SDK over the REST API.

The reference ships a generated Swagger SDK (`internal/httpclient/`,
regenerated from `spec/api.json`) that its e2e suite drives as the fourth
transport (`internal/e2e/full_suit_test.go:65-94`).  This is the same
artifact for this framework: a typed client over the public REST surface,
returning the package's own API types (`ketotpu.api.types`) and raising
its typed errors on failure.

Stdlib-only (urllib), synchronous, one class per API port pairing:

    sdk = KetoClient("http://127.0.0.1:4466", "http://127.0.0.1:4467")
    sdk.check("File", "doc", "view", SubjectID("alice"))    -> bool
    sdk.expand(SubjectSet("File", "doc", "view"))           -> Tree | None
    sdk.list_relation_tuples(RelationQuery(namespace="n"))  -> (rows, token)
    sdk.create_relation_tuple(t) / sdk.delete_relation_tuple(t)
    sdk.patch([("insert", t), ("delete", u)])
    sdk.delete_relation_tuples(RelationQuery(...))
    sdk.check_opl_syntax(source)                            -> [errors]
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import List, Optional, Sequence, Tuple

from ketotpu.api.types import (
    BadRequestError,
    KetoAPIError,
    NotFoundError,
    RelationQuery,
    RelationTuple,
    Subject,
    SubjectSet,
    Tree,
    subject_from_string,
)


class SDKError(KetoAPIError):
    """Non-2xx response that maps to no specific API error."""

    def __init__(self, status: int, body: str):
        super().__init__(f"unexpected status {status}: {body[:200]}")
        self.status = status
        self.body = body


class KetoClient:
    def __init__(
        self,
        read_url: str,
        write_url: Optional[str] = None,
        *,
        opl_url: Optional[str] = None,
        timeout: float = 30.0,
    ):
        self.read_url = read_url.rstrip("/")
        self.write_url = (write_url or read_url).rstrip("/")
        self.opl_url = (opl_url or read_url).rstrip("/")
        self.timeout = timeout

    # -- transport ----------------------------------------------------------

    def _request(
        self, method: str, url: str, body: Optional[dict | list] = None
    ) -> Tuple[int, str]:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url,
            data=data,
            method=method,
            headers={"Content-Type": "application/json"} if data else {},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status, resp.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    @staticmethod
    def _raise_for(status: int, body: str):
        if status == 400:
            raise BadRequestError(_error_message(body))
        if status == 404:
            raise NotFoundError(_error_message(body))
        raise SDKError(status, body)

    # -- check --------------------------------------------------------------

    def check(
        self,
        namespace: str,
        object: str,
        relation: str,
        subject: Subject,
        *,
        max_depth: int = 0,
    ) -> bool:
        """Permission check via the non-mirroring openapi variant
        (`getCheckNoStatus`, check/handler.go:156): unknown namespace is
        ``False``, not an error."""
        r = RelationTuple(namespace, object, relation, subject)
        q = urllib.parse.urlencode(
            dict(r.to_url_query(), **({"max-depth": str(max_depth)} if max_depth else {}))
        )
        status, body = self._request(
            "GET", f"{self.read_url}/relation-tuples/check/openapi?{q}"
        )
        if status != 200:
            self._raise_for(status, body)
        return bool(json.loads(body)["allowed"])

    def check_tuple(self, t: RelationTuple, *, max_depth: int = 0) -> bool:
        return self.check(
            t.namespace, t.object, t.relation, t.subject, max_depth=max_depth
        )

    def batch_check(
        self, tuples: Sequence[RelationTuple], *, max_depth: int = 0
    ) -> List[bool]:
        """Many checks in one request (extension endpoint
        POST /relation-tuples/check/batch; the TPU engine answers the whole
        list in fused device dispatches)."""
        url = f"{self.read_url}/relation-tuples/check/batch"
        if max_depth:
            url += f"?max-depth={max_depth}"
        status, body = self._request(
            "POST", url, {"tuples": [t.to_json() for t in tuples]}
        )
        if status != 200:
            self._raise_for(status, body)
        return [bool(r["allowed"]) for r in json.loads(body)["results"]]

    # -- expand -------------------------------------------------------------

    def expand(
        self, subject_set: SubjectSet, *, max_depth: int = 0
    ) -> Optional[Tree]:
        params = {
            "namespace": subject_set.namespace,
            "object": subject_set.object,
            "relation": subject_set.relation,
        }
        if max_depth:
            params["max-depth"] = str(max_depth)
        q = urllib.parse.urlencode(params)
        status, body = self._request(
            "GET", f"{self.read_url}/relation-tuples/expand?{q}"
        )
        if status == 404:
            return None  # empty expansion (expand/handler.go:98-101)
        if status != 200:
            self._raise_for(status, body)
        return Tree.from_json(json.loads(body))

    # -- relation tuples ----------------------------------------------------

    def list_relation_tuples(
        self,
        query: Optional[RelationQuery] = None,
        *,
        page_token: str = "",
        page_size: int = 0,
    ) -> Tuple[List[RelationTuple], str]:
        params = dict(query.to_url_query()) if query is not None else {}
        if page_token:
            params["page_token"] = page_token
        if page_size:
            params["page_size"] = str(page_size)
        q = urllib.parse.urlencode(params)
        status, body = self._request(
            "GET", f"{self.read_url}/relation-tuples?{q}"
        )
        if status != 200:
            self._raise_for(status, body)
        data = json.loads(body)
        return (
            [RelationTuple.from_json(d) for d in data["relation_tuples"]],
            data.get("next_page_token", ""),
        )

    # -- Leopard listing APIs (reverse queries) -----------------------------

    def list_objects(
        self,
        namespace: str,
        relation: str,
        subject: "Subject | str",
        *,
        page_token: str = "",
        page_size: int = 0,
    ) -> Tuple[List[str], str]:
        """Objects the subject reaches in ``namespace#relation`` through
        set containment (GET /relation-tuples/list-objects, answered from
        the engine's closure index).  Returns (objects, next_page_token).

        ``subject`` may be a ``Subject`` or its string form ("alice",
        "Group:eng#members")."""
        if isinstance(subject, str):
            subject = subject_from_string(subject)
        params = dict(
            RelationQuery(
                namespace=namespace, relation=relation
            ).with_subject(subject).to_url_query()
        )
        if page_token:
            params["page_token"] = page_token
        if page_size:
            params["page_size"] = str(page_size)
        q = urllib.parse.urlencode(params)
        status, body = self._request(
            "GET", f"{self.read_url}/relation-tuples/list-objects?{q}"
        )
        if status != 200:
            self._raise_for(status, body)
        data = json.loads(body)
        objs = data.get("objects")
        if objs is None:
            objs = [
                RelationTuple.from_json(d).object
                for d in data["relation_tuples"]
            ]
        return list(objs), data.get("next_page_token", "")

    def list_subjects(
        self,
        namespace: str,
        object: str,
        relation: str,
        *,
        page_token: str = "",
        page_size: int = 0,
    ) -> Tuple[List[Subject], str]:
        """Subjects reaching ``namespace:object#relation`` (GET
        /relation-tuples/list-subjects).  Returns (subjects, token)."""
        params = {
            "namespace": namespace, "object": object, "relation": relation,
        }
        if page_token:
            params["page_token"] = page_token
        if page_size:
            params["page_size"] = str(page_size)
        q = urllib.parse.urlencode(params)
        status, body = self._request(
            "GET", f"{self.read_url}/relation-tuples/list-subjects?{q}"
        )
        if status != 200:
            self._raise_for(status, body)
        data = json.loads(body)
        return (
            [
                RelationTuple.from_json(d).subject
                for d in data["relation_tuples"]
            ],
            data.get("next_page_token", ""),
        )

    def create_relation_tuple(self, t: RelationTuple) -> RelationTuple:
        status, body = self._request(
            "PUT", f"{self.write_url}/admin/relation-tuples", t.to_json()
        )
        if status not in (200, 201):
            self._raise_for(status, body)
        return RelationTuple.from_json(json.loads(body))

    def delete_relation_tuple(self, t: RelationTuple) -> None:
        self._delete(t.to_url_query())

    def delete_relation_tuples(self, query: RelationQuery) -> None:
        """Delete everything the query matches (DELETE /admin/relation-tuples
        with query params, transact_server.go:72)."""
        self._delete(query.to_url_query())

    def _delete(self, params: dict) -> None:
        q = urllib.parse.urlencode(params)
        status, body = self._request(
            "DELETE", f"{self.write_url}/admin/relation-tuples?{q}"
        )
        if status != 204:
            self._raise_for(status, body)

    def patch(
        self, deltas: Sequence[Tuple[str, RelationTuple]]
    ) -> None:
        """PATCH /admin/relation-tuples with [{action, relation_tuple}]
        deltas; action is "insert" or "delete" (handler.go PATCH route)."""
        body = [
            {"action": action, "relation_tuple": t.to_json()}
            for action, t in deltas
        ]
        status, out = self._request(
            "PATCH", f"{self.write_url}/admin/relation-tuples", body
        )
        if status != 204:
            self._raise_for(status, out)

    # -- opl ----------------------------------------------------------------

    def check_opl_syntax(self, source: str) -> List[dict]:
        """Parse errors for an OPL document ([] = valid), POST
        /opl/syntax/check (schema/handler.go:31-45)."""
        req = urllib.request.Request(
            f"{self.opl_url}/opl/syntax/check",
            data=source.encode(),
            method="POST",
            headers={"Content-Type": "text/plain"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                status, body = resp.status, resp.read().decode()
        except urllib.error.HTTPError as e:
            status, body = e.code, e.read().decode()
        if status != 200:
            self._raise_for(status, body)
        return json.loads(body).get("errors", [])

    # -- meta ---------------------------------------------------------------

    def health(self) -> bool:
        status, _ = self._request("GET", f"{self.read_url}/health/ready")
        return status == 200

    def version(self) -> str:
        status, body = self._request("GET", f"{self.read_url}/version")
        if status != 200:
            self._raise_for(status, body)
        return json.loads(body)["version"]


def _error_message(body: str) -> str:
    try:
        data = json.loads(body)
        if isinstance(data, dict):
            err = data.get("error", data)
            if isinstance(err, dict):
                return str(err.get("message", body))
            return str(err)
    except (json.JSONDecodeError, TypeError):
        pass
    return body
