"""Serving layer: handlers, REST router, multiplexed 4-port daemon."""

from ketotpu.server.daemon import Server, serve_all

__all__ = ["Server", "serve_all"]
