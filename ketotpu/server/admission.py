"""Bounded in-flight admission control with load shedding.

One controller per registry, shared by the REST handler threads and the
gRPC interceptor of every port: the budget bounds total concurrent
request handling in this process, which is what protects the coalescer
backlog and the owner socket pool from unbounded queueing.  When the
budget is exhausted new work is shed immediately with 429 /
``RESOURCE_EXHAUSTED`` and a ``Retry-After`` hint — a fast no is the
whole point; queueing here would just move the hang.
"""

from __future__ import annotations

import threading


class AdmissionController:
    """Semaphore-shaped in-flight budget that sheds instead of blocking."""

    def __init__(self, limit: int = 0):
        self.limit = int(limit)
        self.inflight = 0
        self.shed = 0  # observability: requests refused at admission
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.limit > 0

    def try_acquire(self, weight: int = 1) -> bool:
        """Admit ``weight`` units of work, or refuse without blocking.

        Batch RPCs are admitted by ITEM count, not request count — one
        4096-item batch costs 4096 units, so a flood of batches sheds at
        the same engine pressure a flood of singles would.  A single
        batch larger than the whole budget is clamped to the budget:
        it can still run, but only alone (otherwise any batch above
        ``limit`` would be unservable by construction).
        """
        if self.limit <= 0:
            return True
        weight = min(max(1, int(weight)), self.limit)
        with self._lock:
            if self.inflight + weight > self.limit:
                self.shed += weight
                return False
            self.inflight += weight
            return True

    def release(self, weight: int = 1) -> None:
        if self.limit <= 0:
            return
        weight = min(max(1, int(weight)), self.limit)
        with self._lock:
            self.inflight = max(0, self.inflight - weight)
