"""Bounded in-flight admission control with priority-class load shedding.

One controller per registry, shared by the REST handler threads and the
gRPC interceptor of every port: the budget bounds total concurrent
request handling in this process, which is what protects the coalescer
backlog and the owner socket pool from unbounded queueing.  When the
budget is exhausted new work is shed immediately with 429 /
``RESOURCE_EXHAUSTED`` and a ``Retry-After`` hint — a fast no is the
whole point; queueing here would just move the hang.

Two refinements over a plain semaphore:

* **Dynamic limit** — ``limit`` is rewritten at runtime by the AIMD
  controller in :mod:`ketotpu.server.overload`.  ``try_acquire``
  therefore returns the *granted token* (the clamped weight) and
  ``release`` takes exactly that token back: re-clamping the weight
  against the *current* limit on release would leak budget whenever the
  limit shrank mid-flight.
* **Priority classes** — each request is admitted under a class
  (interactive check > expand/list > batch items > watch/bootstrap)
  whose budget is a fraction of the shared limit.  Lower classes hit
  their ceiling first, so under pressure batch and list traffic sheds
  while interactive checks keep landing; the brownout ladder tightens
  the fractions stage by stage until only exempt probes remain.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Optional

# priority classes, best-served first
CLASS_INTERACTIVE = "interactive"  # single check / openapi check
CLASS_BULK = "bulk"                # expand, list, admin reads/writes
CLASS_BATCH = "batch"              # batch front doors + per-item weight
CLASS_BACKGROUND = "background"    # watch, bootstrap, changefeed

CLASSES = (CLASS_INTERACTIVE, CLASS_BULK, CLASS_BATCH, CLASS_BACKGROUND)

# occupancy ceilings as fractions of the shared limit, per brownout
# stage.  Stage 0 leaves headroom above batch/background so interactive
# checks always find room; stage 1 sheds batch/background outright and
# halves bulk; stage 2 is interactive-only; stage 3 sheds everything
# (admission-exempt debug/health surfaces never reach this table).
STAGE_FRACTIONS: Dict[int, Dict[str, float]] = {
    0: {CLASS_INTERACTIVE: 1.00, CLASS_BULK: 0.95,
        CLASS_BATCH: 0.90, CLASS_BACKGROUND: 0.85},
    1: {CLASS_INTERACTIVE: 1.00, CLASS_BULK: 0.50,
        CLASS_BATCH: 0.00, CLASS_BACKGROUND: 0.00},
    2: {CLASS_INTERACTIVE: 1.00, CLASS_BULK: 0.00,
        CLASS_BATCH: 0.00, CLASS_BACKGROUND: 0.00},
    3: {CLASS_INTERACTIVE: 0.00, CLASS_BULK: 0.00,
        CLASS_BATCH: 0.00, CLASS_BACKGROUND: 0.00},
}

STAGE_NAMES = ("normal", "brownout-1", "brownout-2", "full-shed")


class AdmissionController:
    """Semaphore-shaped in-flight budget that sheds instead of blocking."""

    def __init__(self, limit: int = 0):
        self.limit = int(limit)
        self.inflight = 0
        self.shed = 0  # observability: units refused at admission
        # capacity sheds: refused because the request would not fit under
        # the raw limit even ignoring class caps — ORGANIC pressure.  The
        # remainder (total - capacity) are policy sheds: the stage/class
        # fraction refused them, i.e. the brownout ladder doing its job.
        # The OverloadController walks the ladder on capacity sheds only,
        # otherwise a full-shed stage wedges itself: every probe it sheds
        # would read as fresh pressure and de-escalation could never start.
        self.shed_capacity = 0
        self.stage = 0  # brownout ladder stage, written by OverloadController
        self.shed_by_class: Dict[str, int] = {k: 0 for k in CLASSES}
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.limit > 0

    def class_cap(self, klass: Optional[str]) -> int:
        """Occupancy ceiling for ``klass`` under the current stage.

        ``ceil`` keeps tiny test budgets honest: a fraction of 0.9 on a
        limit of 2 still admits 2 units, it only bites once the limit is
        large enough for the headroom to be a whole unit.
        """
        fractions = STAGE_FRACTIONS.get(self.stage, STAGE_FRACTIONS[3])
        frac = fractions.get(klass or CLASS_INTERACTIVE,
                             fractions[CLASS_BULK])
        if frac <= 0.0:
            return 0
        return min(self.limit, int(math.ceil(self.limit * frac)))

    def try_acquire(self, weight: int = 1,
                    klass: str = CLASS_INTERACTIVE) -> int:
        """Admit ``weight`` units of work, or refuse without blocking.

        Returns the granted token (the clamped weight, truthy) when
        admitted and ``0`` when shed — pass the token verbatim to
        :meth:`release`.  Batch RPCs are admitted by ITEM count, not
        request count — one 4096-item batch costs 4096 units, so a flood
        of batches sheds at the same engine pressure a flood of singles
        would.  A single batch larger than its class ceiling is clamped
        to that ceiling: it can still run, but only alone (otherwise any
        batch above the cap would be unservable by construction).
        """
        weight = max(1, int(weight))
        if self.limit <= 0:
            return weight
        with self._lock:
            weight = min(weight, self.limit)
            cap = self.class_cap(klass)
            # clamp against the CLASS cap, not just the limit: a batch
            # wider than the class ceiling must stay servable when the
            # lane is idle (granted == cap admits it only alone).  A cap
            # of 0 is a policy shed — nothing to clamp to.
            granted = min(weight, cap) if cap > 0 else weight
            if self.inflight + granted > cap:
                self.shed += weight
                if self.inflight + weight > self.limit:
                    self.shed_capacity += weight
                if klass in self.shed_by_class:
                    self.shed_by_class[klass] += 1
                else:
                    self.shed_by_class[klass] = 1
                return 0
            self.inflight += granted
            return granted

    def release(self, token: int = 1) -> None:
        """Return exactly the units granted by :meth:`try_acquire`.

        The token is NOT re-clamped against the current limit: the limit
        is dynamic, and clamping a release after a mid-flight shrink
        would free fewer units than were taken, leaking budget forever.
        """
        if self.limit <= 0:
            return
        with self._lock:
            self.inflight = max(0, self.inflight - max(0, int(token)))

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "limit": self.limit,
                "inflight": self.inflight,
                "shed": self.shed,
                "shed_capacity": self.shed_capacity,
                "stage": self.stage,
                "stage_name": STAGE_NAMES[min(self.stage,
                                              len(STAGE_NAMES) - 1)],
                "shed_by_class": dict(self.shed_by_class),
                "class_caps": {k: self.class_cap(k) for k in CLASSES},
            }
