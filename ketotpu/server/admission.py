"""Bounded in-flight admission control with load shedding.

One controller per registry, shared by the REST handler threads and the
gRPC interceptor of every port: the budget bounds total concurrent
request handling in this process, which is what protects the coalescer
backlog and the owner socket pool from unbounded queueing.  When the
budget is exhausted new work is shed immediately with 429 /
``RESOURCE_EXHAUSTED`` and a ``Retry-After`` hint — a fast no is the
whole point; queueing here would just move the hang.
"""

from __future__ import annotations

import threading


class AdmissionController:
    """Semaphore-shaped in-flight budget that sheds instead of blocking."""

    def __init__(self, limit: int = 0):
        self.limit = int(limit)
        self.inflight = 0
        self.shed = 0  # observability: requests refused at admission
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.limit > 0

    def try_acquire(self) -> bool:
        """Admit one request, or refuse without blocking."""
        if self.limit <= 0:
            return True
        with self._lock:
            if self.inflight >= self.limit:
                self.shed += 1
                return False
            self.inflight += 1
            return True

    def release(self) -> None:
        if self.limit <= 0:
            return
        with self._lock:
            if self.inflight > 0:
                self.inflight -= 1
