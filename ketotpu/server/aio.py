"""Asyncio HTTP/1.1 front end for the REST routers.

Replaces the ``ThreadingHTTPServer`` thread-per-connection model: one
event loop owns every connection (accept, header parsing, keep-alive
idle timeouts, response writes), so concurrency 512+ costs file
descriptors, not threads.  Request HANDLING — route dispatch, admission,
deadline scopes, the flight-recorder stage vector — still runs on a
small worker-thread pool (``limit.http_workers``), because the handler
cores block on the engine; the pool bounds handler concurrency while the
loop keeps accepting and buffering.

Contract parity with the old server (server/rest.py keeps the
``make_http_server`` entry point; the Router/handler surface is
untouched):

* HTTP/1.1 keep-alive by default, ``Connection: close`` and HTTP/1.0
  honored; pipelined requests are answered in order off the same buffer;
* the accept backlog is bounded (``limit.accept_backlog``) — overload
  queues in the kernel and sheds at admission, never as an unbounded
  thread herd;
* per-request flow is the exact _serve flow the threaded handler ran:
  flightrec recording for known ops, admission try/acquire + shed
  metrics, X-Request-Timeout deadline scope, CORS, access log;
* SSE streams (StreamingResponse) detach onto a dedicated pump thread so
  a parked watch subscriber never pins a pool worker; chunks are written
  back through the loop;
* TLS is first-class (``ssl_ctx=``): the handshake runs per-connection
  inside the loop, so a stalled client can never block accepts — the
  deferred-handshake workaround the threaded metrics port needed.
"""

from __future__ import annotations

import asyncio
import json
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from contextlib import nullcontext
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ketotpu import deadline, flightrec
from ketotpu.api.types import KetoAPIError
from ketotpu.server import overload

_ALLOWED_METHODS = {"GET", "POST", "PUT", "DELETE", "PATCH"}
_MAX_HEADER_BYTES = 65536
_MAX_HEADERS = 100

#: sentinel returns from the worker-side handler to the connection loop
_KEEP, _CLOSE, _DETACHED = "keep", "close", "detached"


class _BadRequest(Exception):
    pass


class AsyncHTTPServer:
    """Event-loop HTTP server with the ThreadingHTTPServer lifecycle
    surface the daemon drives: ``server_address``, ``serve_forever()``,
    ``shutdown()``, ``server_close()``."""

    def __init__(self, router, host: str, port: int, *,
                 reuse_port: bool = False, ssl_ctx=None):
        from ketotpu.server import rest as _rest

        self._rest = _rest
        self.router = router
        self.registry = router.r
        self.logger = self.registry.logger()
        cfg = self.registry.config
        self.access_log = bool(cfg.get("log.request_log", True))
        # per-connection idle/read timeout: bounds a stalled client to one
        # file descriptor for at most this long (the threaded server's
        # per-connection read timeout analog)
        self.idle_timeout = 30.0
        backlog = int(cfg.get("limit.accept_backlog", 512))
        workers = max(1, int(cfg.get("limit.http_workers", 8)))
        # pre-created listening socket: the daemon reads .server_address
        # right after construction, before serve_forever runs
        self._sock = socket.create_server(
            (host, port), backlog=backlog, reuse_port=reuse_port,
        )
        self.server_address = self._sock.getsockname()
        self._backlog = backlog
        self._ssl_ctx = ssl_ctx
        self._pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="http-worker",
        )
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stop_ev: Optional[asyncio.Event] = None
        self._conn_tasks: set = set()
        self._started = threading.Event()
        self._done = threading.Event()

    # -- lifecycle (ThreadingHTTPServer-shaped) ------------------------------

    def serve_forever(self, poll_interval: float = 0.5) -> None:
        asyncio.run(self._main())

    def shutdown(self) -> None:
        loop = self._loop
        if loop is not None and not loop.is_closed():
            ev = self._stop_ev

            def _stop():
                if ev is not None:
                    ev.set()

            try:
                loop.call_soon_threadsafe(_stop)
            except RuntimeError:  # loop already closed under us
                pass
        if self._started.is_set():
            self._done.wait(timeout=10.0)

    def server_close(self) -> None:
        self._pool.shutdown(wait=False)
        try:
            self._sock.close()
        except OSError:
            pass

    # -- event loop ----------------------------------------------------------

    async def _main(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._stop_ev = asyncio.Event()
        server = await asyncio.start_server(
            self._client, sock=self._sock, ssl=self._ssl_ctx,
            backlog=self._backlog,
            ssl_handshake_timeout=self.idle_timeout if self._ssl_ctx else None,
        )
        self._started.set()
        try:
            await self._stop_ev.wait()
        finally:
            server.close()
            try:
                await server.wait_closed()
            except Exception:  # noqa: BLE001 - shutdown must not raise
                pass
            for t in list(self._conn_tasks):
                t.cancel()
            if self._conn_tasks:
                await asyncio.gather(
                    *self._conn_tasks, return_exceptions=True
                )
            self._done.set()

    async def _client(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        peer = writer.get_extra_info("peername") or ("?", 0)
        detached = False
        try:
            while True:
                try:
                    line = await asyncio.wait_for(
                        reader.readline(), self.idle_timeout
                    )
                except (asyncio.TimeoutError, TimeoutError):
                    break
                if not line:
                    break  # EOF between requests: clean keep-alive close
                if line in (b"\r\n", b"\n"):
                    continue  # stray CRLF between pipelined requests
                try:
                    method, target, version, headers, body = (
                        await self._read_request(line, reader, writer)
                    )
                except _BadRequest as e:
                    await self._write(
                        writer, _simple_response(400, str(e), close=True)
                    )
                    break
                keep = _wants_keepalive(version, headers)
                outcome = await self._loop.run_in_executor(
                    self._pool, self._handle,
                    method, target, headers, body, peer, writer,
                )
                if outcome == _DETACHED:
                    detached = True
                    return  # the pump thread owns the writer now
                if outcome == _CLOSE or not keep:
                    break
        except asyncio.CancelledError:
            pass
        except (ConnectionError, OSError):
            pass
        finally:
            self._conn_tasks.discard(task)
            if not detached:
                try:
                    writer.close()
                except Exception:  # noqa: BLE001
                    pass

    async def _read_request(self, line: bytes, reader, writer):
        try:
            parts = line.decode("latin-1").rstrip("\r\n").split()
            method, target, version = parts[0], parts[1], parts[2]
        except (IndexError, UnicodeDecodeError):
            raise _BadRequest("malformed request line") from None
        if version not in ("HTTP/1.1", "HTTP/1.0"):
            raise _BadRequest(f"unsupported protocol {version}")
        headers = {}
        total = 0
        while True:
            h = await asyncio.wait_for(reader.readline(), self.idle_timeout)
            if not h:
                raise _BadRequest("unexpected EOF in headers")
            if h in (b"\r\n", b"\n"):
                break
            total += len(h)
            if total > _MAX_HEADER_BYTES or len(headers) >= _MAX_HEADERS:
                raise _BadRequest("headers too large")
            try:
                name, _, value = h.decode("latin-1").partition(":")
            except UnicodeDecodeError:
                raise _BadRequest("malformed header") from None
            headers[name.strip().lower()] = value.strip()
        if headers.get("expect", "").lower() == "100-continue":
            writer.write(b"HTTP/1.1 100 Continue\r\n\r\n")
            await writer.drain()
        body = b""
        try:
            length = int(headers.get("content-length") or 0)
        except ValueError:
            raise _BadRequest("bad Content-Length") from None
        if length > 0:
            try:
                body = await asyncio.wait_for(
                    reader.readexactly(length), self.idle_timeout
                )
            except asyncio.IncompleteReadError:
                raise _BadRequest("truncated body") from None
        return method, target, version, headers, body

    # -- response writes (called from worker threads) ------------------------

    async def _write(self, writer: asyncio.StreamWriter, data: bytes) -> None:
        writer.write(data)
        await writer.drain()

    def _send(self, writer: asyncio.StreamWriter, data: bytes,
              timeout: float = 30.0) -> None:
        fut = asyncio.run_coroutine_threadsafe(
            self._write(writer, data), self._loop
        )
        fut.result(timeout=timeout)

    def _close_writer(self, writer: asyncio.StreamWriter) -> None:
        def _do():
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

        try:
            self._loop.call_soon_threadsafe(_do)
        except RuntimeError:
            pass

    # -- request handling (worker pool) --------------------------------------

    def _handle(self, method: str, target: str, hdrs: dict, body: bytes,
                peer, writer) -> str:
        try:
            return self._serve(method, target, hdrs, body, peer, writer)
        except Exception:  # noqa: BLE001 - connection-level failure
            self.logger.exception("http connection handler failed")
            try:
                self._send(
                    writer,
                    _simple_response(500, "internal error", close=True),
                )
            except Exception:  # noqa: BLE001
                pass
            return _CLOSE

    def _serve(self, method: str, target: str, hdrs: dict, body: bytes,
               peer, writer) -> str:
        rest = self._rest
        router, registry = self.router, self.registry
        if method == "OPTIONS":
            # CORS preflight (rs/cors handles OPTIONS before routing)
            origin = hdrs.get("origin")
            want = hdrs.get("access-control-request-method")
            hs = rest.cors_headers(
                router.cors, origin, request_method=want, preflight=True,
            ) if router.cors else None
            head = _head(204 if hs else 405, list((hs or {}).items())
                         + [("Content-Length", "0")])
            self._send(writer, head)
            return _KEEP
        if method not in _ALLOWED_METHODS:
            self._send(
                writer,
                _simple_response(501, f"unsupported method {method!r}",
                                 close=True),
            )
            return _CLOSE
        t0 = time.perf_counter()
        parsed = urlparse(target)
        query = rest._flatten_query(parse_qs(parsed.query))
        t_parse = time.perf_counter()
        op = rest._RPC_OPS.get(parsed.path)
        rec = flightrec.rpc_recording(
            registry, op, traceparent=hdrs.get("traceparent"),
            detail=f"{method} {parsed.path}", t0=t0,
        ) if op else nullcontext()
        with rec:
            flightrec.note_stage("parse", t_parse - t0)
            ctl = (
                registry.admission()
                if parsed.path not in rest._ADMISSION_EXEMPT else None
            )
            token = 0
            klass = overload.classify_rest_path(parsed.path)
            if ctl is not None and not (
                token := ctl.try_acquire(klass=klass)
            ):
                registry.metrics().counter(
                    "keto_requests_shed_total", 1.0,
                    help="requests refused by admission control",
                    transport="rest", klass=klass,
                )
                registry.metrics().observe(
                    flightrec.STAGE_METRIC, 0.0,
                    help="per-RPC stage wall time decomposition",
                    op=op or "http", stage="shed",
                )
                status, payload, extra = (
                    429,
                    rest._error_body(
                        429,
                        f"in-flight limit reached ({ctl.limit}); "
                        "retry later",
                    ),
                    {"Retry-After": registry.retry_after_hint()},
                )
            else:
                try:
                    try:
                        # per-request budget: the X-Request-Timeout header
                        # bounds every blocking hop downstream
                        budget = deadline.parse_timeout(
                            hdrs.get("x-request-timeout")
                        )
                    except KetoAPIError as e:
                        code = e.status_code or 500
                        status, payload, extra = (
                            code, rest._error_body(code, str(e)), {}
                        )
                    else:
                        with deadline.scope(budget):
                            status, payload, extra = router.dispatch(
                                method, parsed.path,
                                rest.Request(query, body, hdrs),
                            )
                finally:
                    if ctl is not None:
                        ctl.release(token)
            flightrec.note_stage("compute", time.perf_counter() - t_parse)
            flightrec.note(status=status)
            if (op == "check" and isinstance(payload, dict)
                    and "allowed" in payload):
                flightrec.note(verdict=payload["allowed"])
            t_enc = time.perf_counter()
            if isinstance(payload, rest.StreamingResponse):
                return self._serve_stream(
                    method, parsed.path, status, payload, extra, hdrs,
                    peer, writer, t0,
                )
            if payload is None:
                data = b""
                ctype = "application/json"
            elif isinstance(payload, tuple):
                ctype, text = payload
                # bytes pass through untouched: the columnar batch route
                # renders its whole response frame pre-encoded
                data = (text if isinstance(text, (bytes, bytearray))
                        else text.encode("utf-8"))
            else:
                ctype = "application/json"
                data = json.dumps(payload).encode("utf-8")
            headers = [
                ("Content-Type", ctype),
                ("Content-Length", str(len(data))),
            ]
            headers.extend(extra.items())
            if router.cors:
                headers.extend((rest.cors_headers(
                    router.cors, hdrs.get("origin")
                ) or {}).items())
            self._send(writer, _head(status, headers) + data)
            flightrec.note_stage("encode", time.perf_counter() - t_enc)
        dt = time.perf_counter() - t0
        registry.metrics().observe(
            "keto_http_request_duration_seconds", dt,
            help="REST request latency",
            endpoint=router.endpoint, method=method, status=str(status),
        )
        if parsed.path not in ("/health/alive", "/health/ready"):
            if self.access_log:
                self.logger.info(
                    "http_request", extra={"fields": {
                        "method": method,
                        "path": parsed.path,
                        "status": status,
                        "duration_ms": round(dt * 1e3, 3),
                        "peer": "%s:%s" % tuple(peer[:2]),
                        "endpoint": router.endpoint,
                    }},
                )
            else:
                self.logger.debug(
                    "%s %s -> %d (%.1fms)",
                    method, parsed.path, status, dt * 1e3,
                )
        return _KEEP

    def _serve_stream(self, method, path, status, payload, extra, hdrs,
                      peer, writer, t0) -> str:
        """SSE escape hatch: write the head, then detach the stream onto
        its own pump thread so a parked subscriber costs a thread only
        while it is STREAMING — never a pool worker.  The pump owns the
        writer from here; chunk writes ride back through the loop."""
        rest, router, registry = self._rest, self.router, self.registry
        headers = [
            ("Content-Type", payload.content_type),
            ("Cache-Control", "no-store"),
            ("Connection", "close"),
        ]
        headers.extend(extra.items())
        if router.cors:
            headers.extend((rest.cors_headers(
                router.cors, hdrs.get("origin")
            ) or {}).items())
        self._send(writer, _head(status, headers))
        flightrec.note_stage("encode", 0.0)

        def pump():
            try:
                for chunk in payload.iterator:
                    self._send(writer, chunk)
            except Exception:  # noqa: BLE001 - client gone: end the stream
                pass
            finally:
                close = getattr(payload.iterator, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:  # noqa: BLE001
                        pass
                self._close_writer(writer)
                dt = time.perf_counter() - t0
                registry.metrics().observe(
                    "keto_http_request_duration_seconds", dt,
                    help="REST request latency",
                    endpoint=router.endpoint, method=method,
                    status=str(status),
                )
                if self.access_log:
                    self.logger.info(
                        "http_stream", extra={"fields": {
                            "method": method,
                            "path": path,
                            "status": status,
                            "duration_ms": round(dt * 1e3, 3),
                            "peer": "%s:%s" % tuple(peer[:2]),
                            "endpoint": router.endpoint,
                        }},
                    )

        threading.Thread(
            target=pump, daemon=True, name="http-sse-pump",
        ).start()
        return _DETACHED


# -- response encoding helpers ------------------------------------------------


def _head(status: int, headers) -> bytes:
    from ketotpu.server.rest import _STATUS_TEXT

    reason = _STATUS_TEXT.get(status, "OK" if status < 400 else "Error")
    lines = [f"HTTP/1.1 {status} {reason}\r\n"]
    for k, v in headers:
        lines.append(f"{k}: {v}\r\n")
    lines.append("\r\n")
    return "".join(lines).encode("latin-1")


def _simple_response(status: int, message: str, *, close: bool = False) -> bytes:
    body = json.dumps({
        "error": {"code": status, "message": message}
    }).encode("utf-8")
    headers = [
        ("Content-Type", "application/json"),
        ("Content-Length", str(len(body))),
    ]
    if close:
        headers.append(("Connection", "close"))
    return _head(status, headers) + body


def _wants_keepalive(version: str, headers: dict) -> bool:
    conn = headers.get("connection", "").lower()
    if version == "HTTP/1.0":
        return conn == "keep-alive"
    return conn != "close"
