"""The serving daemon: 4 multiplexed ports, gRPC + REST on each.

Parity with `internal/driver/daemon.go:105-151,230-315`: the reference
listens on read (:4466), write (:4467), metrics (:4468) and opl (:4469),
cmux-splitting each port into an HTTP/2 gRPC server and an HTTP/1 REST
router.  Python's grpc server owns its listening socket, so the cmux here
is a byte-level multiplexer: the public port accepts the connection, peeks
the first bytes, and splices the stream to an internal gRPC or REST backend
bound on localhost — protocol detection by the HTTP/2 client preface
(``PRI * HTTP/2.0``), exactly what cmux matches on.

gRPC service placement mirrors `daemon.go:488-543`:
  read:   CheckService, ExpandService, ReadService, NamespacesService,
          VersionService, grpc.health.v1.Health
  write:  WriteService, VersionService, Health
  opl:    SyntaxService, VersionService, Health
  metrics: REST only (prometheus + health + version), like the reference's
          plain-HTTP metrics port (daemon.go:189-228).

Graceful shutdown closes acceptors first, then stops backends with a grace
period (daemon.go:109-135).
"""

from __future__ import annotations

import socket
import threading
import time
from concurrent import futures
from typing import Dict, List, Optional, Tuple

import grpc

from ketotpu.proto import health_pb2
from ketotpu.proto.services import (
    CHECK_SERVICE,
    EXPAND_SERVICE,
    NAMESPACES_SERVICE,
    READ_SERVICE,
    SYNTAX_SERVICE,
    VERSION_SERVICE,
    WRITE_SERVICE,
    add_servicer_to_server,
)
from ketotpu.server import rest
from ketotpu.server.handlers import (
    CheckHandler,
    ExpandHandler,
    NamespaceHandler,
    RelationTupleHandler,
    SyntaxHandler,
    VersionHandler,
)

HEALTH_SERVICE = "grpc.health.v1.Health"

# the HTTP/2 client connection preface cmux matches on
_H2_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"


class HealthServicer:
    """grpc.health.v1.Health/Check over the registry's readiness checks."""

    def __init__(self, registry):
        self.r = registry

    def Check(self, request, context):
        failing = [v for v in self.r.health().values() if v != "ok"]
        status = (
            health_pb2.HealthCheckResponse.NOT_SERVING
            if failing
            else health_pb2.HealthCheckResponse.SERVING
        )
        return health_pb2.HealthCheckResponse(status=status)


def _pump(src: socket.socket, dst: socket.socket) -> None:
    try:
        while True:
            data = src.recv(65536)
            if not data:
                break
            dst.sendall(data)
    except OSError:
        pass
    finally:
        for s, how in ((dst, socket.SHUT_WR), (src, socket.SHUT_RD)):
            try:
                s.shutdown(how)
            except OSError:
                pass


class _Mux(threading.Thread):
    """One public port: sniff the preface, splice to gRPC or REST backend."""

    def __init__(self, host: str, port: int, grpc_addr: Tuple[str, int],
                 rest_addr: Tuple[str, int], logger):
        super().__init__(daemon=True)
        self.listener = socket.create_server(
            (host, port), reuse_port=False, backlog=128
        )
        self.addr = self.listener.getsockname()[:2]
        self.grpc_addr = grpc_addr
        self.rest_addr = rest_addr
        self.logger = logger
        self._closing = threading.Event()

    def run(self) -> None:
        while not self._closing.is_set():
            try:
                conn, _ = self.listener.accept()
            except OSError:
                break
            threading.Thread(
                target=self._splice, args=(conn,), daemon=True
            ).start()

    def _splice(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(10.0)
            # cmux buffers until it can match; a fragmented preface may
            # deliver fewer than 4 bytes first, so peek until decidable.
            # MSG_PEEK returns immediately once any bytes exist, hence the
            # tiny sleep between re-peeks of a still-matching partial head.
            deadline = time.monotonic() + 10.0
            while True:
                head = conn.recv(len(_H2_PREFACE), socket.MSG_PEEK)
                if (
                    not head
                    or len(head) >= 4
                    or head != _H2_PREFACE[: len(head)]
                    or time.monotonic() > deadline
                ):
                    break
                time.sleep(0.005)
            conn.settimeout(None)
            target = (
                self.grpc_addr if head.startswith(b"PRI ") else self.rest_addr
            )
            backend = socket.create_connection(target)
        except OSError as e:
            self.logger.debug("mux splice failed: %s", e)
            conn.close()
            return
        t = threading.Thread(target=_pump, args=(conn, backend), daemon=True)
        t.start()
        _pump(backend, conn)

    def close(self) -> None:
        self._closing.set()
        try:
            self.listener.close()
        except OSError:
            pass


class Server:
    """ServeAll analog: boot every port, block until stop()."""

    def __init__(self, registry):
        self.registry = registry
        self.logger = registry.logger()
        self._grpc_servers: List[grpc.Server] = []
        self._http_servers: List = []
        self._muxes: List[_Mux] = []
        self._threads: List[threading.Thread] = []
        self.addresses: Dict[str, Tuple[str, int]] = {}
        self._stopped = threading.Event()

    # -- construction -------------------------------------------------------

    def _grpc_backend(self, services: Dict[str, object]) -> Tuple[str, int]:
        server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16),
            options=[("grpc.so_reuseport", 0)],
            # embedder-supplied interceptors (ketoctx
            # WithGRPCUnaryInterceptors, daemon.go:450-486 chain)
            interceptors=tuple(self.registry.options.grpc_interceptors),
        )
        for name, servicer in services.items():
            add_servicer_to_server(name, servicer, server)
        port = server.add_insecure_port("127.0.0.1:0")
        server.start()
        self._grpc_servers.append(server)
        return ("127.0.0.1", port)

    def _rest_backend(self, router: rest.Router) -> Tuple[str, int]:
        httpd = rest.make_http_server(router, "127.0.0.1", 0)
        self._http_servers.append(httpd)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        return httpd.server_address[:2]

    def start(self) -> "Server":
        r = self.registry
        version = VersionHandler(r)
        health = HealthServicer(r)
        check = CheckHandler(r)
        expand = ExpandHandler(r)
        tuples = RelationTupleHandler(r)
        namespaces = NamespaceHandler(r)
        syntax = SyntaxHandler(r)

        ports = {
            "read": (
                {
                    CHECK_SERVICE: check,
                    EXPAND_SERVICE: expand,
                    READ_SERVICE: tuples,
                    NAMESPACES_SERVICE: namespaces,
                    VERSION_SERVICE: version,
                    HEALTH_SERVICE: health,
                },
                rest.read_router(r),
            ),
            "write": (
                {
                    WRITE_SERVICE: tuples,
                    VERSION_SERVICE: version,
                    HEALTH_SERVICE: health,
                },
                rest.write_router(r),
            ),
            "opl": (
                {
                    SYNTAX_SERVICE: syntax,
                    VERSION_SERVICE: version,
                    HEALTH_SERVICE: health,
                },
                rest.opl_router(r),
            ),
        }
        for name, (services, router) in ports.items():
            host, port = r.config.listen_on(name)
            grpc_addr = self._grpc_backend(services)
            rest_addr = self._rest_backend(router)
            mux = _Mux(host, port, grpc_addr, rest_addr, self.logger)
            mux.start()
            self._muxes.append(mux)
            self.addresses[name] = mux.addr
            self.logger.info(
                "serving %s on %s:%d (gRPC+REST multiplexed)",
                name, *mux.addr,
            )

        # metrics: plain HTTP, no gRPC, no mux (daemon.go:189-228)
        host, port = r.config.listen_on("metrics")
        httpd = rest.make_http_server(rest.metrics_router(r), host, port)
        self._http_servers.append(httpd)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        self.addresses["metrics"] = httpd.server_address[:2]
        self.logger.info("serving metrics on %s:%d", *self.addresses["metrics"])
        return self

    # -- lifecycle ----------------------------------------------------------

    def wait(self, timeout: Optional[float] = None) -> None:
        self._stopped.wait(timeout)

    def stop(self, grace: float = 5.0) -> None:
        for mux in self._muxes:
            mux.close()
        for s in self._grpc_servers:
            s.stop(grace)
        for httpd in self._http_servers:
            httpd.shutdown()
            httpd.server_close()
        self._stopped.set()


def serve_all(registry) -> Server:
    """Build + start the full 4-port daemon (Registry.ServeAll analog)."""
    return Server(registry).start()
