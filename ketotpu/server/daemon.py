"""The serving daemon: 4 multiplexed ports, gRPC + REST on each.

Parity with `internal/driver/daemon.go:105-151,230-315`: the reference
listens on read (:4466), write (:4467), metrics (:4468) and opl (:4469),
cmux-splitting each port into an HTTP/2 gRPC server and an HTTP/1 REST
router.  Python's grpc server owns its listening socket, so the cmux here
is a byte-level multiplexer: the public port accepts the connection, peeks
the first bytes, and splices the stream to an internal gRPC or REST backend
bound on localhost — protocol detection by the HTTP/2 client preface
(``PRI * HTTP/2.0``), exactly what cmux matches on.

gRPC service placement mirrors `daemon.go:488-543`:
  read:   CheckService, ExpandService, ReadService, NamespacesService,
          VersionService, grpc.health.v1.Health
  write:  WriteService, VersionService, Health
  opl:    SyntaxService, VersionService, Health
  metrics: REST only (prometheus + health + version), like the reference's
          plain-HTTP metrics port (daemon.go:189-228).

Graceful shutdown closes acceptors first, then stops backends with a grace
period (daemon.go:109-135).
"""

from __future__ import annotations

import os
import socket
import ssl
import threading
import time
from concurrent import futures
from typing import Dict, List, Optional, Tuple

import grpc

from ketotpu.proto import health_pb2
from ketotpu.proto.services import (
    CHECK_SERVICE,
    EXPAND_SERVICE,
    NAMESPACES_SERVICE,
    READ_SERVICE,
    SYNTAX_SERVICE,
    VERSION_SERVICE,
    WATCH_SERVICE,
    WRITE_SERVICE,
    add_servicer_to_server,
)
from ketotpu.server import rest
from ketotpu.server.handlers import (
    CheckHandler,
    ExpandHandler,
    NamespaceHandler,
    RelationTupleHandler,
    SyntaxHandler,
    VersionHandler,
    WatchHandler,
)

HEALTH_SERVICE = "grpc.health.v1.Health"

# the HTTP/2 client connection preface cmux matches on
_H2_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"


class HealthServicer:
    """grpc.health.v1.Health Check + Watch over the registry's checks.

    Readiness values follow a three-state convention: ``"ok"``, a
    ``"degraded: ..."`` string (still SERVING — the device engine fell
    back to CPU, or a worker is respawning), or anything else meaning
    down (NOT_SERVING).  ``status --block`` reads the degraded detail off
    the REST readiness body; the gRPC surface keeps the reference's
    binary protocol."""

    #: Watch repolls the registry at this cadence; a status CHANGE is
    #: streamed immediately at the next tick
    watch_interval = 0.3

    def __init__(self, registry):
        self.r = registry

    def _status(self):
        values = self.r.health().values()
        hard = [
            v for v in values
            if v != "ok" and not str(v).startswith("degraded")
        ]
        return (
            health_pb2.HealthCheckResponse.NOT_SERVING
            if hard
            else health_pb2.HealthCheckResponse.SERVING
        )

    def Check(self, request, context):
        return health_pb2.HealthCheckResponse(status=self._status())

    def Watch(self, request, context):
        """Server-streaming health: current status now, then every change."""
        last = None
        while context.is_active():
            status = self._status()
            if status != last:
                last = status
                yield health_pb2.HealthCheckResponse(status=status)
            time.sleep(self.watch_interval)


def _pump(src: socket.socket, dst: socket.socket) -> None:
    try:
        while True:
            data = src.recv(65536)
            if not data:
                break
            dst.sendall(data)
    except OSError:
        pass
    finally:
        for s, how in ((dst, socket.SHUT_WR), (src, socket.SHUT_RD)):
            try:
                s.shutdown(how)
            except OSError:
                pass


class _Mux(threading.Thread):
    """One public port: sniff the preface, splice to gRPC or REST backend.

    With ``ssl_ctx`` set, the public listener terminates TLS (the
    reference's per-port `serve.<iface>.tls`, embedx/config.schema.json:
    260-296): the handshake runs before protocol sniffing and the
    localhost backends stay plaintext.  The context advertises ALPN
    h2 + http/1.1 so gRPC clients negotiate HTTP/2."""

    def __init__(self, host: str, port: int, grpc_addr: Tuple[str, int],
                 rest_addr: Tuple[str, int], logger,
                 ssl_ctx: Optional[ssl.SSLContext] = None,
                 reuse_port: bool = False,
                 sniff_timeout: float = 10.0):
        super().__init__(daemon=True)
        # a client that connects and never speaks is disconnected after
        # this long — it must not hold a splice thread (limit.sniff_timeout_ms)
        self.sniff_timeout = sniff_timeout
        # reuse_port: SO_REUSEPORT worker mode (server/workers.py) — the
        # kernel load-balances accepted connections across processes
        # bound to the same public port
        self.listener = socket.create_server(
            (host, port), reuse_port=reuse_port, backlog=128
        )
        self.addr = self.listener.getsockname()[:2]
        self.grpc_addr = grpc_addr
        self.rest_addr = rest_addr
        self.logger = logger
        self.ssl_ctx = ssl_ctx
        self._closing = threading.Event()

    def run(self) -> None:
        while not self._closing.is_set():
            try:
                conn, _ = self.listener.accept()
            except OSError:
                break
            threading.Thread(
                target=self._splice, args=(conn,),
                name="keto-mux-splice", daemon=True,
            ).start()

    def _splice(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(self.sniff_timeout)
            if self.ssl_ctx is not None:
                conn = self.ssl_ctx.wrap_socket(conn, server_side=True)
            # cmux buffers until it can match.  READ (not MSG_PEEK — TLS
            # sockets cannot peek) until the protocol is decidable; the
            # sniffed bytes are forwarded to the chosen backend below.
            head = b""
            while (
                len(head) < 4 and head == _H2_PREFACE[: len(head)]
            ):
                chunk = conn.recv(len(_H2_PREFACE) - len(head))
                if not chunk:
                    break
                head += chunk
            conn.settimeout(None)
            target = (
                self.grpc_addr if head.startswith(b"PRI ") else self.rest_addr
            )
            backend = socket.create_connection(target)
            if head:
                backend.sendall(head)
        except (OSError, ssl.SSLError) as e:
            self.logger.debug("mux splice failed: %s", e)
            conn.close()
            return
        t = threading.Thread(target=_pump, args=(conn, backend),
                             name="keto-mux-pump", daemon=True)
        t.start()
        _pump(backend, conn)
        # the backend finished talking; reap the client->backend pump.
        # A client that never closes its half would park that pump in
        # recv() forever — and close() from this thread does NOT
        # interrupt a blocked recv(), so fully shut both sockets down
        # first (recv returns EOF), then close.
        t.join(self.sniff_timeout)
        if t.is_alive():
            for s in (conn, backend):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
            t.join(self.sniff_timeout)
        for s in (conn, backend):
            try:
                s.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closing.set()
        try:
            self.listener.close()
        except OSError:
            pass


class Server:
    """ServeAll analog: boot every port, block until stop()."""

    def __init__(self, registry, *, reuse_port: bool = False):
        self.registry = registry
        self.reuse_port = reuse_port
        self.logger = registry.logger()
        self._grpc_servers: List[grpc.Server] = []
        self._http_servers: List = []
        self._muxes: List[_Mux] = []
        self._threads: List[threading.Thread] = []
        self.addresses: Dict[str, Tuple[str, int]] = {}
        self._engine_host = None
        self._session_lane = None
        self._stopped = threading.Event()
        # anonymized usage telemetry (daemon.go:64-98 seam): inert unless
        # sqa.server_url is configured AND the operator did not opt out.
        # Exactly ONE reporter per deployment like the reference: an
        # SO_REUSEPORT worker (reuse_port=True) must not add an N-fold
        # duplicate stream under the same deployment id
        self.sqa = None
        if not reuse_port:
            from ketotpu.sqa import maybe_start

            self.sqa = maybe_start(
                registry.config,
                network_id=str(registry.network_id),
                metrics=registry.metrics(),
                logger=self.logger,
            )

    # -- construction -------------------------------------------------------

    def _grpc_backend(self, services: Dict[str, object]) -> Tuple[str, int]:
        from ketotpu.server.interceptors import (
            AccessLogInterceptor,
            AdmissionInterceptor,
        )

        server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16),
            options=[("grpc.so_reuseport", 0)],
            # access-log/metrics interceptor first so its duration covers
            # the embedder-supplied chain (ketoctx
            # WithGRPCUnaryInterceptors, daemon.go:450-486); admission runs
            # inside it so shed RPCs still show in the access log, and it
            # binds the RPC deadline budget around everything downstream
            interceptors=(
                AccessLogInterceptor(self.registry),
                AdmissionInterceptor(self.registry),
                *self.registry.options.grpc_interceptors,
            ),
        )
        for name, servicer in services.items():
            add_servicer_to_server(name, servicer, server)
        port = server.add_insecure_port("127.0.0.1:0")
        server.start()
        self._grpc_servers.append(server)
        return ("127.0.0.1", port)

    def _ssl_context(self, endpoint: str) -> Optional[ssl.SSLContext]:
        """TLS context from serve.<endpoint>.tls, or None (plaintext)."""
        get = getattr(self.registry.config, "tls_config", None)
        tls = get(endpoint) if get else None
        if not tls:
            return None
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(tls["cert"], tls["key"])
        try:
            ctx.set_alpn_protocols(["h2", "http/1.1"])
        except NotImplementedError:  # pragma: no cover - platform quirk
            pass
        return ctx

    def _rest_backend(self, router: rest.Router) -> Tuple[str, int]:
        httpd = rest.make_http_server(router, "127.0.0.1", 0)
        self._http_servers.append(httpd)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        return httpd.server_address[:2]

    def start(self) -> "Server":
        r = self.registry
        version = VersionHandler(r)
        health = HealthServicer(r)
        check = CheckHandler(r)
        expand = ExpandHandler(r)
        tuples = RelationTupleHandler(r)
        namespaces = NamespaceHandler(r)
        syntax = SyntaxHandler(r)
        watch = WatchHandler(r)

        ports = {
            "read": (
                {
                    CHECK_SERVICE: check,
                    EXPAND_SERVICE: expand,
                    READ_SERVICE: tuples,
                    WATCH_SERVICE: watch,
                    NAMESPACES_SERVICE: namespaces,
                    VERSION_SERVICE: version,
                    HEALTH_SERVICE: health,
                },
                rest.read_router(r),
            ),
            "write": (
                {
                    WRITE_SERVICE: tuples,
                    VERSION_SERVICE: version,
                    HEALTH_SERVICE: health,
                },
                rest.write_router(r),
            ),
            "opl": (
                {
                    SYNTAX_SERVICE: syntax,
                    VERSION_SERVICE: version,
                    HEALTH_SERVICE: health,
                },
                rest.opl_router(r),
            ),
        }
        for name, (services, router) in ports.items():
            host, port = r.config.listen_on(name)
            grpc_addr = self._grpc_backend(services)
            rest_addr = self._rest_backend(router)
            ctx = self._ssl_context(name)
            sniff_s = float(
                r.config.get("limit.sniff_timeout_ms", 10000)
            ) / 1000.0
            mux = _Mux(host, port, grpc_addr, rest_addr, self.logger,
                       ssl_ctx=ctx, reuse_port=self.reuse_port,
                       sniff_timeout=sniff_s)
            mux.start()
            self._muxes.append(mux)
            self.addresses[name] = mux.addr
            self.logger.info(
                "serving %s on %s:%d (gRPC+REST multiplexed%s)",
                name, *mux.addr, ", TLS" if ctx else "",
            )

        # metrics: plain HTTP, no gRPC, no mux (daemon.go:189-228)
        host, port = r.config.listen_on("metrics")
        ctx = self._ssl_context("metrics")
        # TLS rides the event loop (server/aio.py): per-connection
        # handshakes run inside the loop with their own timeout, so a
        # stalled client can never block accepts — no deferred-handshake
        # socket wrapping needed
        httpd = rest.make_http_server(
            rest.metrics_router(r), host, port,
            reuse_port=self.reuse_port, ssl_ctx=ctx,
        )
        self._http_servers.append(httpd)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        self.addresses["metrics"] = httpd.server_address[:2]
        self.logger.info("serving metrics on %s:%d", *self.addresses["metrics"])

        # streaming session lane (server/session.py): raw TCP, wire.py
        # frames, one admission acquire per session.  Ephemeral by
        # default (session.port 0) — discover via addresses["session"].
        # SO_REUSEPORT rides self.reuse_port so front-door workers can
        # share one pinned lane port.
        broker = r.session_broker()
        if broker is not None and broker.enabled:
            from ketotpu.server.session import SessionLane

            lane_host = str(r.config.get("session.host") or "") \
                or r.config.listen_on("read")[0]
            lane_port = int(r.config.get("session.port", 0) or 0)
            self._session_lane = SessionLane(
                broker, lane_host, lane_port,
                reuse_port=self.reuse_port,
                front_door=str(os.environ.get("KETO_FRONT_DOOR", "")),
            )
            self._session_lane.start()
            self.addresses["session"] = self._session_lane.address
            self.logger.info(
                "serving session lane on %s:%d",
                *self.addresses["session"],
            )

        # replication channel: a single-process daemon that owns the device
        # engine publishes the engine-host socket when durability.socket is
        # configured, so a warm standby can bootstrap + tail it (the same
        # wire --workers mode uses; in that mode the owner process, not
        # this daemon, hosts the socket)
        repl_sock = str(r.config.get("durability.socket") or "")
        if repl_sock and not self.reuse_port \
                and r._device_engine() is not None:
            from ketotpu.server.workers import EngineHostServer

            self._engine_host = EngineHostServer(
                r, repl_sock, health_fn=r.health,
            ).start()
            self.logger.info(
                "serving engine host (replication wire) on %s", repl_sock
            )
        # close the signal->actuation loop: the overload plane starts
        # AIMD-adjusting the admission limit off SLO burn + wave wait.
        # Started here — not in Registry.init() — so only serving
        # processes pay for the 2Hz control thread; stop() retires it
        # via close_engines()
        ov = r.overload()
        if ov is not None:
            ov.start()
        return self

    # -- lifecycle ----------------------------------------------------------

    def wait(self, timeout: Optional[float] = None) -> None:
        self._stopped.wait(timeout)

    def stop(self, grace: float = 5.0) -> None:
        if self.sqa is not None:
            self.sqa.close()
        if self._session_lane is not None:
            try:
                self._session_lane.stop()
            except Exception:  # noqa: BLE001 - shutdown must not raise
                pass
            self._session_lane = None
        if self._engine_host is not None:
            try:
                self._engine_host.stop()
            except Exception:  # noqa: BLE001 - shutdown must not raise
                pass
            self._engine_host = None
        for mux in self._muxes:
            mux.close()
        # retire the coalescer BEFORE the gRPC backends drain: its wave
        # worker thread and any queued slots must not outlive the daemon
        # (a closed coalescer answers stragglers directly on the inner
        # engine, so in-grace RPCs still complete)
        self.registry.close_engines()
        for s in self._grpc_servers:
            s.stop(grace)
        for httpd in self._http_servers:
            httpd.shutdown()
            httpd.server_close()
        # flush + stop the OTLP exporter AFTER the backends drain so the
        # final requests' spans ship; only if a tracer was ever built —
        # constructing one here just to close it would be pure waste
        tracer = self.registry._tracer
        close = getattr(tracer, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001 - shutdown must not raise
                self.logger.debug("tracer close failed", exc_info=True)
        self._stopped.set()


def serve_all(registry, *, reuse_port: bool = False) -> Server:
    """Build + start the full 4-port daemon (Registry.ServeAll analog)."""
    return Server(registry, reuse_port=reuse_port).start()
