"""API handlers: the transport-agnostic cores + gRPC servicer methods.

One handler class per reference handler package, each holding a Registry
(the `handlerDependencies` interface soup, e.g. `internal/check/handler.go:
28-37`).  Methods named after gRPC RPCs are the servicer implementations
registered via `ketotpu.proto.services.add_servicer_to_server`; the
``*_core`` methods are shared by REST routes (server/rest.py).

Behavioral parity notes (each encoded below, with the reference site):

* unknown namespace on REST check ⇒ ``allowed=false`` with HTTP 200/403, not
  404 (`check/handler.go:169-171`); on gRPC it propagates as NOT_FOUND;
* ``/relation-tuples/check`` mirrors the verdict in the HTTP status (403 on
  deny); ``/relation-tuples/check/openapi`` always answers 200
  (`check/handler.go:54-59,141-154`);
* Expand of a subject-id is a leaf tree without touching the engine
  (`expand/handler.go:115-126`); an empty expansion is 404 on REST
  (`expand/handler.go:98-101`);
* snaptokens are real here (the snapshot epoch of the device engine),
  where the reference returns "not yet implemented"
  (`check/handler.go:329`, `transact_server.go:63-66`).
"""

from __future__ import annotations

import queue
import threading
import time
from contextlib import contextmanager

import grpc
import numpy as np

from ketotpu import consistency, deadline, flightrec
from ketotpu.cache import context as cache_context
from ketotpu.cache import expand_key as cache_expand_key
from ketotpu.engine import columns
from ketotpu.api.proto_codec import (
    query_from_proto,
    tree_to_proto,
    tuple_from_proto,
    tuple_to_proto,
)
from ketotpu.api.types import (
    BadRequestError,
    DeadlineExceededError,
    KetoAPIError,
    NotFoundError,
    RelationQuery,
    RelationTuple,
    SubjectSet,
    TooManyRequestsError,
)
from ketotpu.observability import (
    PERMISSIONS_CHECKED,
    PERMISSIONS_EXPANDED,
    RELATIONTUPLES_CHANGED,
    RELATIONTUPLES_DELETED,
)
from ketotpu.opl.parser import parse as opl_parse
from ketotpu.proto import (
    batch_service_pb2,
    check_service_pb2,
    expand_service_pb2,
    namespaces_service_pb2,
    read_service_pb2,
    stream_service_pb2,
    syntax_service_pb2,
    version_pb2,
    watch_service_pb2,
    write_service_pb2,
)

_GRPC_CODES = {
    400: grpc.StatusCode.INVALID_ARGUMENT,
    403: grpc.StatusCode.PERMISSION_DENIED,
    404: grpc.StatusCode.NOT_FOUND,
    409: grpc.StatusCode.ALREADY_EXISTS,
    412: grpc.StatusCode.FAILED_PRECONDITION,  # stale snaptoken (Zanzibar)
    429: grpc.StatusCode.RESOURCE_EXHAUSTED,
    500: grpc.StatusCode.INTERNAL,
    503: grpc.StatusCode.UNAVAILABLE,
    504: grpc.StatusCode.DEADLINE_EXCEEDED,
}


def _md(context) -> dict:
    """gRPC invocation metadata as a lower-cased dict (for the
    Contextualizer seam, ketoctx/contextualizer.go)."""
    try:
        return {k.lower(): v for k, v in (context.invocation_metadata() or ())}
    except Exception:  # noqa: BLE001 - metadata is best-effort
        return {}


def _abort(context, e: Exception):
    """Map a typed API error onto the gRPC status surface (the herodot
    error-unwrap interceptor, daemon.go:468-478)."""
    if isinstance(e, KetoAPIError):
        code = _GRPC_CODES.get(e.status_code or 500, grpc.StatusCode.UNKNOWN)
        context.abort(code, str(e))
    context.abort(grpc.StatusCode.INTERNAL, str(e))


@contextmanager
def batch_admission(r, n: int):
    """Per-item admission accounting for batch RPCs.

    The front door (REST handler / gRPC interceptor) already admitted the
    REQUEST (weight 1); a batch of n items acquires the remaining n-1
    units here so a flood of batches sheds at the same engine pressure a
    flood of singles would.  Refusal raises the typed 429 that both
    transports already map (Retry-After on REST, RESOURCE_EXHAUSTED on
    gRPC)."""
    from ketotpu.server.admission import CLASS_BATCH

    ctl = r.admission()
    extra = max(0, int(n) - 1)
    if extra == 0 or ctl is None or not ctl.enabled:
        yield
        return
    # the front door already holds this REQUEST's unit, so clamp the
    # batch's extra weight to the batch CLASS ceiling minus that held
    # unit: an oversized batch can still run, but only alone (clamping
    # to the raw limit would put the total above the class cap and make
    # any batch > cap unservable by construction).  Under brownout the
    # ladder clamps batch weight much harder — a brownout-1 batch may
    # only take a small slice of the budget.
    cap = max(1, ctl.class_cap(CLASS_BATCH) - 1)
    if ctl.stage >= 1:
        cap = min(cap, max(1, ctl.limit // 8))
    extra = min(extra, cap)
    token = ctl.try_acquire(extra, klass=CLASS_BATCH)
    if not token:
        r.metrics().counter(
            "keto_requests_shed_total", 1.0,
            help="requests refused by admission control",
            transport="batch", klass=CLASS_BATCH,
        )
        raise TooManyRequestsError(
            f"in-flight limit reached ({ctl.limit}); "
            f"batch of {n} refused; retry later"
        )
    try:
        yield
    finally:
        ctl.release(token)


def record_batch(r, op: str, n: int) -> None:
    """Batch observability vocabulary (README metric table)."""
    r.metrics().counter(
        "keto_batch_requests_total", 1.0,
        help="batch RPCs served", op=op,
    )
    r.metrics().observe(
        "keto_batch_size", float(n),
        help="items per batch RPC", op=op,
    )


class CheckHandler:
    """`internal/check/handler.go` — REST core + CheckService servicer."""

    def __init__(self, registry):
        self.r = registry

    def check_core(
        self, tuple_: RelationTuple, max_depth: int, r=None
    ) -> bool:
        """Engine dispatch incl. the unknown-namespace probe the Mapper does
        (uuid_mapping.go:199 via GetNamespaceByName); raises NotFoundError
        for unknown namespaces — REST swallows it, gRPC propagates."""
        r = r if r is not None else self.r
        shadow = r.shadow()
        shadow_cur = shadow.reserve() if shadow is not None else None
        with r.tracer().span("check.Engine.CheckIsMember"):
            # ReadOnlyMapper: namespace checks + validation without interning
            r.read_only_mapper().from_tuple(tuple_)
            allowed = r.check_engine().check_is_member(tuple_, max_depth)
        if shadow_cur is not None:
            shadow.submit(tuple_, max_depth, allowed, cursor=shadow_cur)
        r.tracer().event(PERMISSIONS_CHECKED)
        r.metrics().counter(
            "keto_checks_total", 1, help="authorization checks served",
            allowed=str(allowed).lower(),
        )
        return allowed

    def check_rest(
        self, tuple_: RelationTuple, max_depth: int, headers=None,
        *, snaptoken=None, latest=False,
    ) -> bool:
        r = self.r.resolve(headers)
        token = consistency.ensure_fresh(r, snaptoken, latest, op="check")
        # bind the request's consistency mode + the X-Keto-Cache escape
        # hatch for the hot-spot shield probes further down the stack
        with cache_context.request_scope(r, headers, token=token,
                                         latest=latest):
            try:
                return self.check_core(tuple_, max_depth, r)
            except NotFoundError:
                return False  # check/handler.go:169-171

    def batch_check_core(self, tuples, max_depth: int, r=None):
        """Batched checks through the engine's batch surface (the TPU
        engine answers the whole list in fused device dispatches).  An
        EXTENSION over the reference contract — Keto has no BatchCheck RPC
        at this version (SURVEY §2 proto row); REST route:
        POST /relation-tuples/check/batch.  Per-item semantics match the
        single openapi check: unknown namespace => allowed=false."""
        r = r if r is not None else self.r
        with r.tracer().span("check.Engine.BatchCheck"):
            ok_idx, out = [], [False] * len(tuples)
            for i, t in enumerate(tuples):
                try:
                    r.read_only_mapper().from_tuple(t)
                except NotFoundError:
                    continue  # unknown namespace: deny (handler.go:169-171)
                ok_idx.append(i)
            engine = r.check_engine()
            if ok_idx:
                batch = [tuples[i] for i in ok_idx]
                bc = getattr(engine, "batch_check", None)
                verdicts = (
                    bc(batch, max_depth) if bc is not None
                    else [engine.check_is_member(t, max_depth) for t in batch]
                )
                for i, v in zip(ok_idx, verdicts):
                    out[i] = bool(v)
        for v in out:
            r.metrics().counter(
                "keto_checks_total", 1,
                help="authorization checks served",
                allowed=str(v).lower(),
            )
        r.tracer().event(PERMISSIONS_CHECKED)
        return out

    def batch_check_items(self, items, max_depth: int, r=None):
        """Wire-facing batch core with PER-ITEM verdicts and errors.

        ``items`` entries are either RelationTuples or exceptions (a
        caller that failed to parse item i passes the error in its slot —
        one bad tuple must not fail the batch).  Returns one dict per
        item: ``{"allowed": bool}`` or ``{"error": str, "status": int}``.

        Error isolation contract:
        * per-item parse/validation errors -> that item only;
        * unknown namespace -> ``allowed=false`` (single-check parity);
        * a deadline expiry mid-batch -> the UNANSWERED items come back
          as per-item 504 DEADLINE_EXCEEDED entries and the batch still
          returns (partial results, not a dropped batch);
        * any other engine-level failure annotates the items that were
          riding that dispatch, never the pre-resolved ones.
        """
        r = r if r is not None else self.r
        out: list = [None] * len(items)
        ok_idx = []
        for i, t in enumerate(items):
            if isinstance(t, Exception):
                code = getattr(t, "status_code", None) or 400
                out[i] = {"error": str(t), "status": int(code)}
                continue
            try:
                r.read_only_mapper().from_tuple(t)
            except NotFoundError:
                out[i] = {"allowed": False}  # check/handler.go:169-171
                continue
            except KetoAPIError as e:
                out[i] = {"error": str(e), "status": e.status_code or 400}
                continue
            ok_idx.append(i)
        if ok_idx:
            engine = r.check_engine()
            batch = [items[i] for i in ok_idx]
            with r.tracer().span("check.Engine.BatchCheck"):
                try:
                    rem = deadline.remaining()
                    if rem is not None and rem <= 0:
                        raise DeadlineExceededError(
                            "deadline exceeded before batch dispatch"
                        )
                    bc = getattr(engine, "batch_check", None)
                    verdicts = (
                        bc(batch, max_depth) if bc is not None
                        else [
                            engine.check_is_member(t, max_depth)
                            for t in batch
                        ]
                    )
                    for i, v in zip(ok_idx, verdicts):
                        out[i] = {"allowed": bool(v)}
                except DeadlineExceededError as e:
                    # ONE deadline budget for the whole batch: the expiry
                    # is batch-wide by design, every unanswered item gets
                    # its per-item 504 (partial results, the batch returns)
                    for i in ok_idx:
                        if out[i] is None:
                            out[i] = {"error": str(e), "status": 504}
                except KetoAPIError:
                    # a typed error aborted the fused dispatch: answer
                    # each unanswered item individually so only the
                    # erroring items fail (still inside the one budget)
                    for i in ok_idx:
                        if out[i] is not None:
                            continue
                        rem = deadline.remaining()
                        if rem is not None and rem <= 0:
                            out[i] = {
                                "error": "deadline exceeded mid-batch",
                                "status": 504,
                            }
                            continue
                        try:
                            out[i] = {"allowed": bool(
                                engine.check_is_member(items[i], max_depth)
                            )}
                        except KetoAPIError as e2:
                            out[i] = {
                                "error": str(e2),
                                "status": e2.status_code or 500,
                            }
        for v in out:
            if v is not None and "allowed" in v:
                r.metrics().counter(
                    "keto_checks_total", 1,
                    help="authorization checks served",
                    allowed=str(v["allowed"]).lower(),
                )
        r.tracer().event(PERMISSIONS_CHECKED)
        return out

    def batch_check_columnar(self, raw_tuples, max_depth: int, r=None):
        """Columnar batch-check core (the served-checks hot path).

        ``raw_tuples`` is the decoded-JSON ``tuples`` list straight off
        the wire; it is parsed ONCE into string columns
        (engine/columns.py), bulk-encoded to dense int32 ids with one
        vectorized vocab probe per column, and answered through the
        engine's block surface — no per-item Python object chain.

        Returns ``(allowed, errors)``: a bool ndarray with one verdict
        per input item, and ``{item_index: (message, http_status)}`` for
        the items that failed instead (their ``allowed`` slot is
        meaningless).  Error-isolation semantics match
        :meth:`batch_check_items` exactly — per-item parse errors, the
        unknown-namespace deny, per-item 504 fan-out on deadline expiry,
        and per-item scalar re-checks when a typed error aborts the
        fused dispatch (counted in ``keto_columnar_fallback_total``)."""
        r = r if r is not None else self.r
        t0 = time.perf_counter()
        block, decode_errs, keep = columns.decode_items(raw_tuples)
        flightrec.note_stage("decode", time.perf_counter() - t0)
        errors = {
            i: (str(e), int(e.status_code or 400))
            for i, e in decode_errs.items()
        }
        return self._check_block_core(
            block, keep, len(raw_tuples), errors, max_depth, r
        )

    def batch_check_items_columnar(self, items, max_depth: int, r=None):
        """Columnar core for callers that already hold RelationTuples
        (the gRPC BatchCheck servicer).  ``items`` entries are tuples or
        exceptions, same slot contract as :meth:`batch_check_items`;
        returns the ``(allowed, errors)`` pair of
        :meth:`batch_check_columnar`."""
        r = r if r is not None else self.r
        errors = {}
        good, keep = [], []
        for i, t in enumerate(items):
            if isinstance(t, Exception):
                code = getattr(t, "status_code", None) or 400
                errors[i] = (str(t), int(code))
            else:
                good.append(t)
                keep.append(i)
        t0 = time.perf_counter()
        block = columns.ColumnBlock.from_tuples(good)
        flightrec.note_stage("decode", time.perf_counter() - t0)
        return self._check_block_core(
            block, keep, len(items), errors, max_depth, r
        )

    def _check_block_core(self, block, keep, n, errors, max_depth, r):
        """Shared columnar dispatch: namespace validation (memoized per
        UNIQUE namespace — the scalar path probes the manager per item,
        same verdicts here with O(distinct) probes), id pre-encode, and
        the block engine call with the per-item error contract."""
        allowed = np.zeros(n, dtype=bool)
        met = r.metrics()
        met.counter(
            "keto_columnar_batches_total", 1,
            help="batch check requests served on the columnar path",
        )
        nm = r.read_only_mapper().namespaces
        known: dict = {}

        def probe(name):
            v = known.get(name)
            if v is None:
                try:
                    nm.get_namespace(name)
                    v = True
                except NotFoundError:
                    v = False
                except KetoAPIError as e:
                    v = e
                known[name] = v
            return v

        rows, orig = [], []
        for j in range(len(block)):
            v = probe(block.ns[j])
            if v is True and block.skind[j] == columns.SUBJ_SET:
                v = probe(block.sa[j])
            if v is True:
                rows.append(j)
                orig.append(keep[j])
            elif v is not False:
                # a typed namespace-manager error is that ITEM's error
                errors[keep[j]] = (
                    str(v), int(getattr(v, "status_code", None) or 400)
                )
            # v is False: unknown namespace => allowed=false, EXCLUDED
            # from the engine block (check/handler.go:169-171)
        if rows:
            sub = block if len(rows) == len(block) else block.take(rows)
            engine = r.check_engine()
            shadow = r.shadow()
            shadow_row, shadow_cur = (
                shadow.reserve_block(len(rows))
                if shadow is not None else (None, 0)
            )
            vocab = getattr(engine, "_vocab", None)
            if vocab is not None:
                t1 = time.perf_counter()
                # pre-encode OUTSIDE the wave: the coalescer's collector
                # thread then only refreshes recorded misses
                sub.encode_for(vocab)
                flightrec.note_stage("encode_ids", time.perf_counter() - t1)
            with r.tracer().span("check.Engine.CheckBlock"):
                t2 = time.perf_counter()
                try:
                    rem = deadline.remaining()
                    if rem is not None and rem <= 0:
                        raise DeadlineExceededError(
                            "deadline exceeded before batch dispatch"
                        )
                    # check_block FIRST: the coalescer facade forwards
                    # unknown attrs to its inner engine, so probing
                    # batch_check_block first would bypass the wave
                    cb = (getattr(engine, "check_block", None)
                          or getattr(engine, "batch_check_block", None))
                    if cb is not None:
                        verdicts, row_errs = cb(sub, max_depth)
                    elif getattr(engine, "batch_check", None) is not None:
                        verdicts, row_errs = columns.block_check_via_tuples(
                            engine, sub, max_depth
                        )
                    else:
                        verdicts = [
                            engine.check_is_member(sub[j], max_depth)
                            for j in range(len(sub))
                        ]
                        row_errs = {}
                    for j, i in enumerate(orig):
                        e = row_errs.get(j)
                        if e is None:
                            allowed[i] = bool(verdicts[j])
                        else:
                            errors[i] = (
                                str(e),
                                int(getattr(e, "status_code", None) or 500),
                            )
                except DeadlineExceededError as e:
                    # ONE deadline budget for the whole batch: every
                    # unanswered item gets its per-item 504 (partial
                    # results, the batch returns)
                    for i in orig:
                        errors[i] = (str(e), 504)
                except KetoAPIError:
                    # a typed error aborted the fused dispatch: answer
                    # each item individually so only the erroring items
                    # fail (still inside the one budget)
                    for j, i in enumerate(orig):
                        rem = deadline.remaining()
                        if rem is not None and rem <= 0:
                            errors[i] = ("deadline exceeded mid-batch", 504)
                            continue
                        met.counter(
                            "keto_columnar_fallback_total", 1,
                            help="columnar items re-answered on the "
                                 "scalar path",
                        )
                        try:
                            allowed[i] = bool(
                                engine.check_is_member(sub[j], max_depth)
                            )
                        except KetoAPIError as e2:
                            errors[i] = (
                                str(e2), int(e2.status_code or 500)
                            )
                finally:
                    flightrec.note_stage(
                        "wave_wait", time.perf_counter() - t2
                    )
            if (shadow_row is not None
                    and orig[shadow_row] not in errors):
                shadow.submit(
                    sub[shadow_row], max_depth,
                    bool(allowed[orig[shadow_row]]), cursor=shadow_cur,
                )
        answered = np.ones(n, dtype=bool)
        for i in errors:
            answered[i] = False
        n_true = int(allowed[answered].sum())
        n_false = int(answered.sum()) - n_true
        if n_true:
            met.counter(
                "keto_checks_total", n_true,
                help="authorization checks served", allowed="true",
            )
        if n_false:
            met.counter(
                "keto_checks_total", n_false,
                help="authorization checks served", allowed="false",
            )
        r.tracer().event(PERMISSIONS_CHECKED)
        return allowed, errors

    def snaptoken(self, r=None) -> str:
        """A real snaptoken (the Zanzibar zookie the reference stubs,
        check_service.proto:51-60): store version + changelog cursor +
        engine snapshot epoch + per-shard cursor vector, opaque base64 on
        the wire (ketotpu/consistency/tokens.py)."""
        r = r if r is not None else self.r
        return consistency.mint(r.store(), r._device_engine()).encode()

    # gRPC CheckService.Check
    def Check(self, request, context):
        try:
            md = _md(context)
            r = self.r.resolve(md)
            with flightrec.rpc_recording(
                r, "check", traceparent=md.get("traceparent"),
                detail="grpc Check",
            ):
                t0 = time.perf_counter()
                src = request.tuple if request.HasField("tuple") else request
                tuple_ = tuple_from_proto(src)
                flightrec.note_stage("parse", time.perf_counter() - t0)
                token = None
                if request.snaptoken or request.latest:
                    # the consistency modes (check_service.proto:51-66):
                    # `latest` forces a changelog drain into the engine's
                    # write-exact overlay (a full refresh() rebuild is
                    # stronger than needed and would let any latest=true
                    # client stall all traffic for a reprojection at
                    # 10M-tuple scale); `snaptoken` blocks until the
                    # engine is at-least-as-fresh, refusing with
                    # FAILED_PRECONDITION on budget expiry.
                    tb = time.perf_counter()
                    token = consistency.ensure_fresh(
                        r, request.snaptoken or None, bool(request.latest),
                        op="check",
                    )
                    flightrec.note_stage(
                        "barrier", time.perf_counter() - tb
                    )
                t1 = time.perf_counter()
                with cache_context.request_scope(
                    r, md, token=token, latest=bool(request.latest)
                ):
                    allowed = self.check_core(
                        tuple_, int(request.max_depth), r
                    )
                flightrec.note_stage("compute", time.perf_counter() - t1)
                flightrec.note(verdict=allowed)
                t2 = time.perf_counter()
                resp = check_service_pb2.CheckResponse(
                    allowed=allowed, snaptoken=self.snaptoken(r)
                )
                flightrec.note_stage("encode", time.perf_counter() - t2)
                return resp
        except Exception as e:  # noqa: BLE001 - mapped to status codes
            _abort(context, e)

    # gRPC CheckService.BatchCheck (EXTENSION — batch_service.proto)
    def BatchCheck(self, request, context):
        try:
            md = _md(context)
            r = self.r.resolve(md)
            # ONE flight-recorder span for the whole batch: the stage
            # vector decomposes the batch, not each item
            with flightrec.rpc_recording(
                r, "check", traceparent=md.get("traceparent"),
                detail=f"grpc BatchCheck n={len(request.tuples)}",
            ):
                t0 = time.perf_counter()
                items = []
                for p in request.tuples:
                    try:
                        items.append(tuple_from_proto(p))
                    except KetoAPIError as e:
                        items.append(e)
                flightrec.note_stage("parse", time.perf_counter() - t0)
                flightrec.note(batch=len(items))
                record_batch(r, "check", len(items))
                with batch_admission(r, len(items)):
                    token = None
                    if request.snaptoken or request.latest:
                        # one shared consistency mode: every verdict in
                        # the batch is computed against the same snapshot
                        tb = time.perf_counter()
                        token = consistency.ensure_fresh(
                            r, request.snaptoken or None,
                            bool(request.latest), op="check",
                        )
                        flightrec.note_stage(
                            "barrier", time.perf_counter() - tb
                        )
                    t1 = time.perf_counter()
                    columnar = bool(
                        r.config.get("engine.columnar_batch", True)
                    )
                    with cache_context.request_scope(
                        r, md, token=token, latest=bool(request.latest)
                    ):
                        if columnar:
                            allowed, errors = (
                                self.batch_check_items_columnar(
                                    items, int(request.max_depth), r
                                )
                            )
                        else:
                            results = self.batch_check_items(
                                items, int(request.max_depth), r
                            )
                flightrec.note_stage("compute", time.perf_counter() - t1)
                t2 = time.perf_counter()
                resp = batch_service_pb2.BatchCheckResponse(
                    snaptoken=self.snaptoken(r)
                )
                if columnar:
                    for i in range(len(items)):
                        item = resp.results.add()
                        err = errors.get(i)
                        if err is None:
                            item.allowed = bool(allowed[i])
                        else:
                            item.error, item.status = err[0], int(err[1])
                else:
                    for res in results:
                        item = resp.results.add()
                        if "allowed" in res:
                            item.allowed = res["allowed"]
                        else:
                            item.error = res["error"]
                            item.status = res["status"]
                flightrec.note_stage("encode", time.perf_counter() - t2)
                return resp
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    # gRPC CheckService.StreamCheck (EXTENSION — stream_service.proto):
    # one bidi stream per session.  The handshake admits the WHOLE
    # session (server/session.py broker, interactive class, tenant
    # resolved once); blocks then bypass the admission interceptor
    # (streaming handlers pass through it untouched) and verdict blocks
    # come back out of order as engine waves complete — `seq` is the
    # correlation key.
    def StreamCheck(self, request_iterator, context):
        from ketotpu.server.session import SessionRefused

        resp_t = stream_service_pb2.StreamCheckResponse
        md = _md(context)
        broker = self.r.session_broker()
        if broker is None or not broker.enabled:
            yield resp_t(error="streaming sessions disabled", status=503)
            return
        first = next(request_iterator, None)
        if first is None or not first.open:
            yield resp_t(
                error="first stream message must set open=true",
                status=400,
            )
            return
        try:
            s = broker.open(
                md,
                units=int(first.units),
                snaptoken=str(first.snaptoken or ""),
                latest=bool(first.latest),
                max_depth=int(first.max_depth),
                transport="grpc",
            )
        except SessionRefused as e:
            try:
                context.set_trailing_metadata(
                    (("retry-after", str(int(e.retry_after))),)
                )
            except Exception:  # noqa: BLE001 - hint is advisory
                pass
            yield resp_t(
                error=str(e), status=e.status,
                retry_after_s=int(e.retry_after),
            )
            return

        outq: "queue.Queue" = queue.Queue()

        def done(seq, allowed, n, errs, exc):
            outq.put((seq, allowed, n, errs, exc))

        def pump():
            # reads the client half of the stream; submit_items blocks
            # at the credit window, so an over-eager client parks HERE
            # and gRPC flow control pushes back
            try:
                for req in request_iterator:
                    if req.close:
                        break
                    seq = int(req.seq)
                    if seq in s.seqs:
                        done(seq, None, 0, {}, BadRequestError(
                            f"duplicate seq {seq}"))
                        continue
                    s.seqs.add(seq)
                    items = []
                    for p in req.tuples:
                        try:
                            items.append(tuple_from_proto(p))
                        except KetoAPIError as e:
                            items.append(e)
                    if not items or len(items) > s.max_block_rows:
                        done(seq, None, 0, {}, BadRequestError(
                            f"block of {len(items)} rows outside "
                            f"(0, {s.max_block_rows}]"))
                        continue
                    broker.submit_items(
                        s, seq, items, done,
                        max_depth=int(req.max_depth),
                    )
            except Exception:  # noqa: BLE001 - client went away
                pass
            finally:
                s.drain()
                outq.put(None)

        try:
            yield resp_t(
                session=s.sid, credits=s.credits,
                max_block_rows=s.max_block_rows,
            )
            t = threading.Thread(
                target=pump, name="keto-streamcheck-pump", daemon=True)
            t.start()
            while True:
                item = outq.get()
                if item is None:
                    return
                seq, allowed, n, errs, exc = item
                if exc is not None:
                    yield resp_t(
                        seq=seq, error=str(exc),
                        status=int(
                            getattr(exc, "status_code", None) or 500),
                    )
                    continue
                resp = resp_t(seq=seq, snaptoken=self.snaptoken(s.r))
                for i in range(n):
                    out = resp.results.add()
                    err = errs.get(i)
                    if err is None:
                        out.allowed = bool(allowed[i])
                    else:
                        out.error, out.status = err[0], int(err[1])
                yield resp
        finally:
            # abrupt cancel included: release the session's admission
            # grant exactly once, even with blocks still in flight
            broker.close(s)


class ExpandHandler:
    """`internal/expand/handler.go` — REST core + ExpandService servicer."""

    def __init__(self, registry):
        self.r = registry

    def expand_core(self, subject, max_depth: int, r=None):
        r = r if r is not None else self.r
        cache = r.result_cache()
        key = None
        cursor = 0
        if isinstance(subject, SubjectSet):
            r.read_only_mapper().from_subject_set(subject)  # ns check
            if cache is not None:
                # hot-spot shield for expansion trees: same snapshot-
                # versioned cache as checks, keyed on the expanded node
                key = cache_expand_key(subject, max_depth)
                t0 = time.perf_counter()
                hit = cache.lookup(key)
                flightrec.note_stage("cache", time.perf_counter() - t0)
                if hit is not None:
                    r.tracer().event(PERMISSIONS_EXPANDED)
                    return hit.value
                # stamp read BEFORE the build: a lower bound on the
                # changelog state the tree is computed from
                cursor = r.store().log_head
        with r.tracer().span("expand.Engine.BuildTree"):
            tree = r.expand_engine().build_tree(subject, max_depth)
        if key is not None:
            cache.insert(key, tree, cursor)
        r.tracer().event(PERMISSIONS_EXPANDED)
        return tree

    def batch_expand_items(self, subjects, max_depth: int, r=None):
        """Per-item batch expansion.  ``subjects`` entries are SubjectSets
        or exceptions (parse isolation, same contract as
        batch_check_items).  Returns one dict per item: ``{"tree": Tree}``
        (tree may be None: empty expansion, 404 on the single route), or
        ``{"error": str, "status": int}``.

        Expansion has no fused device batch, so items run sequentially
        inside the one RPC — which makes TRUE partial results on deadline
        natural: once the budget expires, every remaining item comes back
        as a per-item 504 and the answered prefix is kept."""
        r = r if r is not None else self.r
        out: list = []
        expired = False
        for s in subjects:
            if isinstance(s, Exception):
                code = getattr(s, "status_code", None) or 400
                out.append({"error": str(s), "status": int(code)})
                continue
            rem = deadline.remaining()
            if expired or (rem is not None and rem <= 0):
                expired = True
                out.append({
                    "error": "deadline exceeded before item expansion",
                    "status": 504,
                })
                continue
            try:
                out.append({"tree": self.expand_core(s, max_depth, r)})
            except DeadlineExceededError as e:
                expired = True
                out.append({"error": str(e), "status": 504})
            except KetoAPIError as e:
                out.append({"error": str(e), "status": e.status_code or 500})
        return out

    # gRPC ExpandService.Expand
    def Expand(self, request, context):
        try:
            which = request.subject.WhichOneof("ref")
            if which == "id":
                # subject-id expands to a leaf without the engine
                # (expand/handler.go:115-126)
                from ketotpu.proto import relation_tuples_pb2 as rts

                return expand_service_pb2.ExpandResponse(
                    tree=expand_service_pb2.SubjectTree(
                        node_type=expand_service_pb2.NodeType.NODE_TYPE_LEAF,
                        subject=rts.Subject(id=request.subject.id),
                    )
                )
            md = _md(context)
            r = self.r.resolve(md)
            with flightrec.rpc_recording(
                r, "expand", traceparent=md.get("traceparent"),
                detail="grpc Expand",
            ):
                t0 = time.perf_counter()
                s = request.subject.set
                subject = SubjectSet(s.namespace, s.object, s.relation)
                flightrec.note_stage("parse", time.perf_counter() - t0)
                token = None
                if request.snaptoken:
                    # ExpandRequest.snaptoken (expand_service.proto): the
                    # tree must be at-least-as-fresh as the token
                    tb = time.perf_counter()
                    token = consistency.ensure_fresh(
                        r, request.snaptoken, op="expand"
                    )
                    flightrec.note_stage(
                        "barrier", time.perf_counter() - tb
                    )
                t1 = time.perf_counter()
                with cache_context.request_scope(r, md, token=token):
                    tree = self.expand_core(
                        subject, int(request.max_depth), r
                    )
                flightrec.note_stage("compute", time.perf_counter() - t1)
                t2 = time.perf_counter()
                if tree is None:
                    resp = expand_service_pb2.ExpandResponse()
                else:
                    resp = expand_service_pb2.ExpandResponse(
                        tree=tree_to_proto(tree)
                    )
                flightrec.note_stage("encode", time.perf_counter() - t2)
                return resp
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    # gRPC ExpandService.BatchExpand (EXTENSION — batch_service.proto)
    def BatchExpand(self, request, context):
        try:
            md = _md(context)
            r = self.r.resolve(md)
            with flightrec.rpc_recording(
                r, "expand", traceparent=md.get("traceparent"),
                detail=f"grpc BatchExpand n={len(request.subjects)}",
            ):
                t0 = time.perf_counter()
                subjects = [
                    SubjectSet(s.namespace, s.object, s.relation)
                    for s in request.subjects
                ]
                flightrec.note_stage("parse", time.perf_counter() - t0)
                flightrec.note(batch=len(subjects))
                record_batch(r, "expand", len(subjects))
                with batch_admission(r, len(subjects)):
                    token = None
                    if request.snaptoken or request.latest:
                        tb = time.perf_counter()
                        token = consistency.ensure_fresh(
                            r, request.snaptoken or None,
                            bool(request.latest), op="expand",
                        )
                        flightrec.note_stage(
                            "barrier", time.perf_counter() - tb
                        )
                    t1 = time.perf_counter()
                    with cache_context.request_scope(
                        r, md, token=token, latest=bool(request.latest)
                    ):
                        results = self.batch_expand_items(
                            subjects, int(request.max_depth), r
                        )
                flightrec.note_stage("compute", time.perf_counter() - t1)
                t2 = time.perf_counter()
                resp = batch_service_pb2.BatchExpandResponse(
                    snaptoken=consistency.mint(
                        r.store(), r._device_engine()
                    ).encode()
                )
                for res in results:
                    item = resp.results.add()
                    if "tree" in res:
                        if res["tree"] is None:
                            item.error = "no relation tuple found"
                            item.status = 404
                        else:
                            item.tree.CopyFrom(tree_to_proto(res["tree"]))
                    else:
                        item.error = res["error"]
                        item.status = res["status"]
                flightrec.note_stage("encode", time.perf_counter() - t2)
                return resp
        except Exception as e:  # noqa: BLE001
            _abort(context, e)


def _wait_replicated(r) -> None:
    """Semi-sync durability (durability.replication): after the store
    commit, hold this write's ack until the warm standby's tail cursor
    covers the committed changelog head.  Async mode costs one config
    read; a timed-out wait degrades to async for this write (counted by
    the gate) rather than failing a committed transaction."""
    if str(r.config.get("durability.replication", "async")) != "semi-sync":
        return
    gate_fn = getattr(r, "durability_gate", None)
    if gate_fn is None:
        return  # derived/remote registries without the gate seam
    head = getattr(r.store(), "log_head", None)
    t0 = time.perf_counter()
    replicated = gate_fn().wait_replicated(head)
    r.metrics().observe(
        "keto_replication_wait_seconds", time.perf_counter() - t0,
        help="write-path wait for the standby replication ack (semi-sync)",
        replicated=str(bool(replicated)).lower(),
    )


class RelationTupleHandler:
    """`internal/relationtuple/{read_server,transact_server}.go` — tuple
    CRUD over ReadService + WriteService and the REST admin routes."""

    def __init__(self, registry):
        self.r = registry

    # -- cores --------------------------------------------------------------

    def list_core(self, query, page_size: int, page_token: str, r=None):
        r = r if r is not None else self.r
        with r.tracer().span("relationtuple.Manager.GetRelationTuples"):
            if query is not None and query.namespace is not None:
                # FromQuery namespace resolution (uuid_mapping.go:82-90)
                r.read_only_mapper().from_query(query)
            tuples, next_token = r.store().get_relation_tuples(
                query, page_size=page_size or 100, page_token=page_token or ""
            )
        return tuples, next_token

    def transact_core(self, inserts, deletes, r=None):
        r = r if r is not None else self.r
        with r.tracer().span("relationtuple.Manager.TransactRelationTuples"):
            if inserts or deletes:
                r.mapper().from_tuple(*inserts, *deletes)  # validate + ns
            r.store().transact_relation_tuples(inserts, deletes)
        _wait_replicated(r)
        r.tracer().event(RELATIONTUPLES_CHANGED)
        r.metrics().counter(
            "keto_relationtuples_writes_total", 1, help="tuple transactions"
        )

    def delete_all_core(self, query, r=None) -> int:
        r = r if r is not None else self.r
        with r.tracer().span("relationtuple.Manager.DeleteAllRelationTuples"):
            if query is not None and query.namespace is not None:
                r.read_only_mapper().from_query(query)
            n = r.store().delete_all_relation_tuples(query)
        if n:
            _wait_replicated(r)
        r.tracer().event(RELATIONTUPLES_DELETED)
        return n

    # -- gRPC ReadService ---------------------------------------------------

    def ListRelationTuples(self, request, context):
        try:
            if request.HasField("relation_query"):
                query = query_from_proto(request.relation_query)
            elif request.HasField("query"):
                q = request.query
                query = RelationQuery(
                    namespace=q.namespace or None,
                    object=q.object or None,
                    relation=q.relation or None,
                )
                if q.HasField("subject"):
                    from ketotpu.api.proto_codec import subject_from_proto

                    query = query.with_subject(subject_from_proto(q.subject))
            else:
                raise BadRequestError("you must provide a query")
            r = self.r.resolve(_md(context))
            if request.snaptoken:
                # list rows come straight from the store, so only the
                # store's changelog head must cover the token (no engine
                # drain) — use_engine=False skips the device path
                consistency.ensure_fresh(
                    r, request.snaptoken, op="list", use_engine=False
                )
            tuples, next_token = self.list_core(
                query, int(request.page_size), request.page_token, r,
            )
            return read_service_pb2.ListRelationTuplesResponse(
                relation_tuples=[tuple_to_proto(t) for t in tuples],
                next_page_token=next_token,
            )
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    # -- Leopard listing cores (reverse-query APIs) -------------------------

    def list_objects_core(
        self, namespace, relation, subject, page_size, page_token, r=None
    ):
        """Objects a subject reaches in ``namespace#relation`` through the
        closure (ketotpu/leopard/): answered from the index when clean,
        host-oracle enumeration otherwise.  Returns (objects, next_token)."""
        r = r if r is not None else self.r
        if not namespace or not relation:
            raise BadRequestError(
                "list-objects requires namespace, relation and subject"
            )
        if subject is None:
            raise BadRequestError(
                "list-objects requires namespace, relation and subject"
            )
        with r.tracer().span("leopard.Engine.ListObjects"):
            q = RelationQuery(namespace=namespace, relation=relation)
            r.read_only_mapper().from_query(q)  # unknown ns => 404
            objs, next_token = r.list_engine().list_objects(
                namespace, relation, subject,
                page_size=page_size, page_token=page_token or "",
            )
        r.metrics().counter(
            "keto_list_requests_total", 1,
            help="listing (reverse-query) requests served", op="list_objects",
        )
        return objs, next_token

    def list_subjects_core(
        self, namespace, object_, relation, page_size, page_token, r=None
    ):
        """Subjects reaching ``namespace:object#relation`` (the node's
        closure element set).  Returns (subjects, next_token)."""
        r = r if r is not None else self.r
        if not namespace or not object_ or not relation:
            raise BadRequestError(
                "list-subjects requires namespace, object and relation"
            )
        with r.tracer().span("leopard.Engine.ListSubjects"):
            q = RelationQuery(namespace=namespace, relation=relation)
            r.read_only_mapper().from_query(q)
            subs, next_token = r.list_engine().list_subjects(
                namespace, object_, relation,
                page_size=page_size, page_token=page_token or "",
            )
        r.metrics().counter(
            "keto_list_requests_total", 1,
            help="listing (reverse-query) requests served", op="list_subjects",
        )
        return subs, next_token

    # -- gRPC ReadService: Leopard listing RPCs -----------------------------

    def _list_query(self, request):
        if request.HasField("relation_query"):
            return query_from_proto(request.relation_query)
        raise BadRequestError("you must provide a relation_query")

    def ListObjects(self, request, context):
        try:
            md = _md(context)
            r = self.r.resolve(md)
            with flightrec.rpc_recording(
                r, "list_objects", traceparent=md.get("traceparent"),
                detail="grpc ListObjects",
            ):
                t0 = time.perf_counter()
                q = self._list_query(request)
                flightrec.note_stage("parse", time.perf_counter() - t0)
                t1 = time.perf_counter()
                objs, next_token = self.list_objects_core(
                    q.namespace, q.relation, q.subject(),
                    int(request.page_size), request.page_token, r,
                )
                flightrec.note_stage("compute", time.perf_counter() - t1)
                flightrec.note(results=len(objs))
                t2 = time.perf_counter()
                subject = q.subject()
                resp = read_service_pb2.ListRelationTuplesResponse(
                    relation_tuples=[
                        tuple_to_proto(RelationTuple(
                            q.namespace, o, q.relation, subject
                        ))
                        for o in objs
                    ],
                    next_page_token=next_token,
                )
                flightrec.note_stage("encode", time.perf_counter() - t2)
                return resp
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def ListSubjects(self, request, context):
        try:
            md = _md(context)
            r = self.r.resolve(md)
            with flightrec.rpc_recording(
                r, "list_subjects", traceparent=md.get("traceparent"),
                detail="grpc ListSubjects",
            ):
                t0 = time.perf_counter()
                q = self._list_query(request)
                flightrec.note_stage("parse", time.perf_counter() - t0)
                t1 = time.perf_counter()
                subs, next_token = self.list_subjects_core(
                    q.namespace, q.object, q.relation,
                    int(request.page_size), request.page_token, r,
                )
                flightrec.note_stage("compute", time.perf_counter() - t1)
                flightrec.note(results=len(subs))
                t2 = time.perf_counter()
                resp = read_service_pb2.ListRelationTuplesResponse(
                    relation_tuples=[
                        tuple_to_proto(RelationTuple(
                            q.namespace, q.object, q.relation, s
                        ))
                        for s in subs
                    ],
                    next_page_token=next_token,
                )
                flightrec.note_stage("encode", time.perf_counter() - t2)
                return resp
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    # -- gRPC WriteService --------------------------------------------------

    def TransactRelationTuples(self, request, context):
        try:
            inserts, deletes = [], []
            for delta in request.relation_tuple_deltas:
                t = tuple_from_proto(delta.relation_tuple)
                if delta.action == write_service_pb2.RelationTupleDelta.ACTION_INSERT:
                    inserts.append(t)
                elif delta.action == write_service_pb2.RelationTupleDelta.ACTION_DELETE:
                    deletes.append(t)
            r = self.r.resolve(_md(context))
            self.transact_core(inserts, deletes, r)
            # one token per requested delta — inserts AND deletes (the old
            # code returned len(inserts) copies, so delete-only
            # transactions got none and mixed ones the wrong count).  All
            # deltas commit in one store transaction, so every token is
            # the same post-commit cursor; per-entry attribution is
            # ill-defined anyway (a delete may expand to several log rows,
            # or none for a no-op).
            token = consistency.mint(r.store(), r._device_engine()).encode()
            return write_service_pb2.TransactRelationTuplesResponse(
                snaptokens=[token] * (len(inserts) + len(deletes))
            )
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def DeleteRelationTuples(self, request, context):
        try:
            if request.HasField("relation_query"):
                query = query_from_proto(request.relation_query)
            elif request.HasField("query"):
                q = request.query
                query = RelationQuery(
                    namespace=q.namespace or None,
                    object=q.object or None,
                    relation=q.relation or None,
                )
                if q.HasField("subject"):
                    from ketotpu.api.proto_codec import subject_from_proto

                    query = query.with_subject(subject_from_proto(q.subject))
            else:
                raise BadRequestError("invalid request")
            self.delete_all_core(query, self.r.resolve(_md(context)))
            return write_service_pb2.DeleteRelationTuplesResponse()
        except Exception as e:  # noqa: BLE001
            _abort(context, e)


class NamespaceHandler:
    """`internal/namespace/namespacehandler/handler.go` — list namespaces."""

    def __init__(self, registry):
        self.r = registry

    def list_core(self):
        return self.r.namespace_manager().namespaces()

    def ListNamespaces(self, request, context):
        try:
            return namespaces_service_pb2.ListNamespacesResponse(
                namespaces=[
                    namespaces_service_pb2.Namespace(name=ns.name)
                    for ns in self.list_core()
                ]
            )
        except Exception as e:  # noqa: BLE001
            _abort(context, e)


class SyntaxHandler:
    """`internal/schema/handler.go` — OPL syntax check."""

    def __init__(self, registry):
        self.r = registry

    def check_core(self, content: bytes):
        _, errors = opl_parse(content.decode("utf-8", errors="replace"))
        return errors

    def Check(self, request, context):
        errors = self.check_core(request.content)
        return syntax_service_pb2.CheckResponse(
            parse_errors=[
                syntax_service_pb2.ParseError(
                    message=e.msg,
                    start=syntax_service_pb2.SourcePosition(
                        line=e.start.line, column=e.start.column
                    ),
                    end=syntax_service_pb2.SourcePosition(
                        line=e.end.line, column=e.end.column
                    ),
                )
                for e in errors
            ]
        )


class VersionHandler:
    """`rts.VersionServiceServer` registered on every gRPC port
    (daemon.go:505,521,538)."""

    def __init__(self, registry):
        self.r = registry

    def GetVersion(self, request, context):
        return version_pb2.GetVersionResponse(version=self.r.version)


class WatchHandler:
    """WatchService servicer: the Zanzibar Watch API
    (ketotpu/consistency/watch.py) as a gRPC server-stream on the read
    port.  Streaming handlers pass through both interceptors untouched
    (server/interceptors.py), so this RPC is exempt from in-flight
    admission control BY DESIGN — a stream parked on a heartbeat would
    pin an admission slot forever; the hub's watch.max_subscribers cap
    bounds subscribers instead (excess subscribes abort with
    RESOURCE_EXHAUSTED)."""

    def __init__(self, registry):
        self.r = registry

    def Watch(self, request, context):
        try:
            md = _md(context)
            r = self.r.resolve(md)
            hub = r.watch_hub()
            with flightrec.rpc_recording(
                r, "watch", traceparent=md.get("traceparent"),
                detail="grpc Watch",
            ):
                # the recorded stage is subscription setup (decode the
                # resume token + replay the missed changelog suffix into
                # the queue); the tail of the stream is unbounded and
                # lives outside the record
                t0 = time.perf_counter()
                sub = hub.subscribe(
                    snaptoken=request.snaptoken or None,
                    namespace=request.namespace or None,
                )
                flightrec.note_stage("parse", time.perf_counter() - t0)
                flightrec.note(resume=bool(request.snaptoken))
        except Exception as e:  # noqa: BLE001 - mapped to status codes
            _abort(context, e)
            return
        heartbeat_s = float(
            self.r.config.get("watch.heartbeat_ms", 15000) or 15000
        ) / 1000.0
        try:
            for ev in sub.events(heartbeat_s):
                if not context.is_active():
                    break
                resp = watch_service_pb2.WatchRelationTuplesResponse(
                    event=ev.kind,
                    action=ev.action or "",
                    snaptoken=ev.snaptoken or "",
                )
                if ev.tuple is not None:
                    resp.relation_tuple.CopyFrom(tuple_to_proto(ev.tuple))
                yield resp
        finally:
            hub.unsubscribe(sub)
