"""gRPC server interceptors: per-RPC access logging + duration metrics.

The reference chains logging/metrics middleware onto every gRPC server
(`internal/driver/daemon.go:450-486`); this is the same seam for the
Python servers.  `AccessLogInterceptor` wraps every unary handler to

* observe ``keto_grpc_request_duration_seconds{method}`` on the shared
  Metrics registry, and
* emit one INFO access line per RPC (method, status, duration, peer)
  when ``log.request_log`` is enabled — health-check RPCs are metered
  but not logged, like the REST access log's health exclusion.

Embedder-supplied interceptors (ketoctx ``grpc_interceptors``) still run;
this one is prepended so the duration covers the whole chain.
"""

from __future__ import annotations

import time

import grpc


class AccessLogInterceptor(grpc.ServerInterceptor):
    """Per-RPC access log + duration histogram for unary methods."""

    def __init__(self, registry):
        self.registry = registry

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None or handler.unary_unary is None:
            return handler  # streaming/unknown: pass through untouched
        method = handler_call_details.method
        registry = self.registry
        inner = handler.unary_unary

        def wrapped(request, context):
            t0 = time.perf_counter()
            status = "OK"
            try:
                return inner(request, context)
            except Exception:
                status = "ERROR"
                raise
            finally:
                dt = time.perf_counter() - t0
                # abort()/set_code() paths: report the code the handler set
                code = getattr(context, "code", lambda: None)()
                if code is not None and code != grpc.StatusCode.OK:
                    status = getattr(code, "name", str(code))
                registry.metrics().observe(
                    "keto_grpc_request_duration_seconds", dt,
                    help="gRPC request duration by full method name",
                    method=method,
                )
                if (
                    not method.startswith("/grpc.health.")
                    and bool(registry.config.get("log.request_log", True))
                ):
                    registry.logger().info(
                        "grpc request", extra={"fields": {
                            "method": method,
                            "status": status,
                            "duration_ms": round(dt * 1000.0, 3),
                            "peer": context.peer(),
                        }},
                    )

        return grpc.unary_unary_rpc_method_handler(
            wrapped,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )
