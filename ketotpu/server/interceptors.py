"""gRPC server interceptors: per-RPC access logging + duration metrics.

The reference chains logging/metrics middleware onto every gRPC server
(`internal/driver/daemon.go:450-486`); this is the same seam for the
Python servers.  `AccessLogInterceptor` wraps every unary handler to

* observe ``keto_grpc_request_duration_seconds{method}`` on the shared
  Metrics registry, and
* emit one INFO access line per RPC (method, status, duration, peer)
  when ``log.request_log`` is enabled — health-check RPCs are metered
  but not logged, like the REST access log's health exclusion.

Embedder-supplied interceptors (ketoctx ``grpc_interceptors``) still run;
this one is prepended so the duration covers the whole chain.
"""

from __future__ import annotations

import time

import grpc

from ketotpu import deadline, flightrec
from ketotpu.server import overload


class AdmissionInterceptor(grpc.ServerInterceptor):
    """In-flight admission + deadline binding for unary methods.

    Before the handler runs this interceptor (a) tries to acquire one
    slot from the registry's shared :class:`AdmissionController`, shedding
    with ``RESOURCE_EXHAUSTED`` when the port is saturated, and (b) binds
    the RPC's ``context.time_remaining()`` as the thread's deadline budget
    so every blocking hop downstream (coalescer slot wait, owner socket,
    oracle fallback) is bounded by what the client granted.  Health RPCs
    are exempt — an overloaded server must still answer probes.
    """

    def __init__(self, registry):
        self.registry = registry

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None or handler.unary_unary is None:
            return handler  # streaming/unknown: pass through untouched
        method = handler_call_details.method
        if method.startswith("/grpc.health."):
            return handler
        registry = self.registry
        inner = handler.unary_unary
        op = method.rsplit("/", 1)[-1].lower()
        klass = overload.classify_grpc_op(op)

        def wrapped(request, context):
            ctl = registry.admission()
            token = ctl.try_acquire(klass=klass)
            if not token:
                m = registry.metrics()
                m.counter(
                    "keto_requests_shed_total", 1.0,
                    help="requests refused by admission control",
                    transport="grpc", klass=klass,
                )
                m.observe(
                    flightrec.STAGE_METRIC, 0.0,
                    help="per-RPC stage wall time decomposition",
                    op=op, stage="shed",
                )
                # the trailing-metadata twin of the REST Retry-After
                # header: load-derived + jittered backoff hint
                context.set_trailing_metadata(
                    (("retry-after", registry.retry_after_hint()),)
                )
                context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    f"in-flight limit reached ({ctl.limit}); retry later",
                )
            try:
                with deadline.scope(context.time_remaining()):
                    return inner(request, context)
            finally:
                ctl.release(token)

        return grpc.unary_unary_rpc_method_handler(
            wrapped,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )


class AccessLogInterceptor(grpc.ServerInterceptor):
    """Per-RPC access log + duration histogram for unary methods."""

    def __init__(self, registry):
        self.registry = registry

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None or handler.unary_unary is None:
            return handler  # streaming/unknown: pass through untouched
        method = handler_call_details.method
        registry = self.registry
        inner = handler.unary_unary

        def wrapped(request, context):
            t0 = time.perf_counter()
            status = "OK"
            try:
                return inner(request, context)
            except Exception:
                status = "ERROR"
                raise
            finally:
                dt = time.perf_counter() - t0
                # abort()/set_code() paths: report the code the handler set
                code = getattr(context, "code", lambda: None)()
                if code is not None and code != grpc.StatusCode.OK:
                    status = getattr(code, "name", str(code))
                registry.metrics().observe(
                    "keto_grpc_request_duration_seconds", dt,
                    help="gRPC request duration by full method name",
                    method=method,
                )
                if (
                    not method.startswith("/grpc.health.")
                    and bool(registry.config.get("log.request_log", True))
                ):
                    registry.logger().info(
                        "grpc request", extra={"fields": {
                            "method": method,
                            "status": status,
                            "duration_ms": round(dt * 1000.0, 3),
                            "peer": context.peer(),
                        }},
                    )

        return grpc.unary_unary_rpc_method_handler(
            wrapped,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )
