"""Adaptive overload control: AIMD limit, brownout ladder, retry budgets,
circuit breakers.

Zanzibar survives hotspots with request prioritization, load shedding and
throttled retries (SURVEY §3/§5); this module is that loop closed for the
TPU serving stack.  The SLO burn-rate engine and wave ledger provide the
pressure *signals* — this plane turns them into *actuation*:

* :class:`OverloadController` — a background tick (watchdog-style thread,
  directly tickable in tests) that

  - **AIMD-adjusts** ``AdmissionController.limit`` between a configured
    floor and ceiling: additive growth while wave wait and fast-window
    SLO burn stay under target, multiplicative shrink on latency
    inflation or burn, published as ``keto_admission_limit``;
  - drives the **brownout ladder** (normal → brownout-1: shed
    batch/list → brownout-2: interactive-only → full shed) off fast
    burn + shed pressure, every transition edge-logged and counted in
    ``keto_overload_transitions_total``;
  - computes the cooperative **Retry-After hint** — load-derived and
    jittered so shed clients do not stampede back in lockstep.

* :class:`RetryBudget` — token bucket capping retries to a fraction of
  successes (client SDK and the owner RPC wire), so retry storms cannot
  multiply offered load; exhaustion counts into
  ``keto_retry_budget_exhausted_total``.

* :class:`CircuitBreaker` — windowed error-rate breaker for the worker
  wire and DCN peer lanes: trips open on failure bursts, fails fast to
  the existing oracle/replica degrade paths (verdicts stay exact), and
  half-open probes to recover.  State in ``keto_breaker_state``, trips
  in ``keto_breaker_trips_total``.

Priority classification for both transports lives here too so REST and
gRPC agree on what sheds first.
"""

from __future__ import annotations

import math
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .admission import (
    CLASS_BACKGROUND,
    CLASS_BATCH,
    CLASS_BULK,
    CLASS_INTERACTIVE,
    STAGE_NAMES,
    AdmissionController,
)

__all__ = [
    "OverloadController", "RetryBudget", "CircuitBreaker",
    "classify_rest_path", "classify_grpc_op",
    "BREAKER_CLOSED", "BREAKER_OPEN", "BREAKER_HALF_OPEN",
]


# -- priority classification --------------------------------------------------

# exact REST front doors; anything unlisted (admin CRUD, OPTIONS-able
# surfaces) rides in the bulk class — it is neither latency-critical nor
# weight-amplified
_REST_CLASSES = {
    "/relation-tuples/check": CLASS_INTERACTIVE,
    "/relation-tuples/check/openapi": CLASS_INTERACTIVE,
    "/relation-tuples/check/batch": CLASS_BATCH,
    "/relation-tuples/batch/check": CLASS_BATCH,
    "/relation-tuples/batch/expand": CLASS_BATCH,
    "/relation-tuples/expand": CLASS_BULK,
    "/relation-tuples/list-objects": CLASS_BULK,
    "/relation-tuples/list-subjects": CLASS_BULK,
    "/relation-tuples/watch": CLASS_BACKGROUND,
}


def classify_rest_path(path: str) -> str:
    """Priority class for a REST front door (debug/probes never get here —
    they are admission-exempt upstream)."""
    return _REST_CLASSES.get(path, CLASS_BULK)


def classify_grpc_op(op: str) -> str:
    """Priority class for a gRPC method suffix (already lowercased by the
    admission interceptor)."""
    if "stream" in op:
        # streaming check sessions (server/session.py): admitted ONCE at
        # the handshake under the interactive ceiling, which is what
        # lets ESTABLISHED sessions keep draining through brownout-2
        # (new sessions are refused at the handshake itself)
        return CLASS_INTERACTIVE
    if "batch" in op:
        return CLASS_BATCH
    if op == "check":
        return CLASS_INTERACTIVE
    if "watch" in op or "bootstrap" in op or "subscribe" in op:
        return CLASS_BACKGROUND
    return CLASS_BULK


# -- cooperative retry budget -------------------------------------------------

class RetryBudget:
    """Token bucket capping retries to a fraction of successes.

    Every success deposits ``ratio`` tokens (capped at ``burst``); every
    retry withdraws one whole token.  A client that only ever fails runs
    dry after ``burst`` retries and stops amplifying — which is the
    point: under a real outage retries are pure extra load.
    """

    def __init__(self, ratio: float = 0.1, burst: float = 10.0,
                 lane: str = "sdk", metrics=None):
        self.ratio = float(ratio)
        self.burst = float(burst)
        self.lane = lane
        self.tokens = float(burst)
        self.exhausted = 0
        self._metrics = metrics
        self._lock = threading.Lock()

    def record_success(self) -> None:
        with self._lock:
            self.tokens = min(self.burst, self.tokens + self.ratio)

    def allow_retry(self) -> bool:
        with self._lock:
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                return True
            self.exhausted += 1
        if self._metrics is not None:
            self._metrics.counter(
                "keto_retry_budget_exhausted_total", 1.0,
                help="retries refused because the token bucket ran dry",
                lane=self.lane,
            )
        return False

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {"lane": self.lane, "tokens": round(self.tokens, 3),
                    "burst": self.burst, "ratio": self.ratio,
                    "exhausted": self.exhausted}


# -- circuit breaker ----------------------------------------------------------

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"

_BREAKER_CODES = {BREAKER_CLOSED: 0, BREAKER_OPEN: 1, BREAKER_HALF_OPEN: 2}


class CircuitBreaker:
    """Windowed error-rate breaker with a single half-open probe.

    Closed: everything flows, outcomes accumulate in a sliding window.
    Once the window holds ``min_volume`` samples and the failure ratio
    reaches ``failure_ratio``, the breaker trips OPEN: callers fail fast
    into their degrade path instead of eating a timeout.  After
    ``cooldown_s`` one probe is let through half-open; success closes
    the breaker, failure re-opens it for another cooldown.
    """

    def __init__(self, lane: str, *, window_s: float = 10.0,
                 min_volume: int = 8, failure_ratio: float = 0.5,
                 cooldown_s: float = 2.0, metrics=None, logger=None,
                 clock=time.monotonic):
        self.lane = lane
        self.window_s = float(window_s)
        self.min_volume = int(min_volume)
        self.failure_ratio = float(failure_ratio)
        self.cooldown_s = float(cooldown_s)
        self.state = BREAKER_CLOSED
        self.trips = 0
        self._events: deque = deque(maxlen=512)  # (ts, ok)
        self._opened_at = 0.0
        self._probe_out = False
        self._metrics = metrics
        self._logger = logger
        self._clock = clock
        self._lock = threading.Lock()

    def _set_state(self, state: str) -> None:
        # caller holds the lock
        if state == self.state:
            return
        prev, self.state = self.state, state
        if self._logger is not None:
            self._logger.warning(
                "breaker %s: %s -> %s", self.lane, prev, state,
            )
        if self._metrics is not None:
            self._metrics.gauge(
                "keto_breaker_state", _BREAKER_CODES[state],
                help="circuit breaker state (0=closed 1=open 2=half_open)",
                lane=self.lane,
            )

    def _prune(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()

    def allow(self) -> bool:
        """True when a call may proceed; False = fail fast, lane is open."""
        now = self._clock()
        with self._lock:
            if self.state == BREAKER_OPEN:
                if now - self._opened_at < self.cooldown_s:
                    return False
                self._set_state(BREAKER_HALF_OPEN)
                self._probe_out = True
                return True
            if self.state == BREAKER_HALF_OPEN:
                if self._probe_out:
                    return False
                self._probe_out = True
                return True
            return True

    def record_success(self) -> None:
        now = self._clock()
        with self._lock:
            self._probe_out = False
            if self.state != BREAKER_CLOSED:
                self._events.clear()
                self._set_state(BREAKER_CLOSED)
            self._events.append((now, True))
            self._prune(now)

    def record_failure(self) -> None:
        now = self._clock()
        with self._lock:
            self._probe_out = False
            if self.state == BREAKER_HALF_OPEN:
                # the probe failed: straight back to open, fresh cooldown
                self._opened_at = now
                self._set_state(BREAKER_OPEN)
                return
            self._events.append((now, False))
            self._prune(now)
            if self.state != BREAKER_CLOSED:
                return
            volume = len(self._events)
            if volume < self.min_volume:
                return
            failures = sum(1 for _, ok in self._events if not ok)
            if failures / volume >= self.failure_ratio:
                self.trips += 1
                self._opened_at = now
                self._set_state(BREAKER_OPEN)
                if self._metrics is not None:
                    self._metrics.counter(
                        "keto_breaker_trips_total", 1.0,
                        help="circuit breaker trips (closed -> open)",
                        lane=self.lane,
                    )

    def state_code(self) -> int:
        return _BREAKER_CODES[self.state]

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            volume = len(self._events)
            failures = sum(1 for _, ok in self._events if not ok)
            return {"lane": self.lane, "state": self.state,
                    "trips": self.trips, "window_volume": volume,
                    "window_failures": failures}


# -- the overload controller --------------------------------------------------

class OverloadController:
    """AIMD admission limit + brownout ladder + Retry-After hints.

    Runs a watchdog-style daemon thread ticking every ``interval_s``;
    :meth:`tick` is also directly callable so tests drive it
    deterministically.  All actuation lands on the shared
    :class:`AdmissionController` (``limit`` and ``stage``), which the
    hot admission path reads without ever touching this object.
    """

    def __init__(self, registry, ctl: AdmissionController, *,
                 floor: int = 64, ceiling: int = 8192, increase: int = 64,
                 decrease: float = 0.8, target_wait_ms: float = 25.0,
                 interval_s: float = 0.5, burn_enter: float = 2.0,
                 burn_exit: float = 1.0, hold_s: float = 10.0,
                 retry_after_max_s: int = 30):
        self._r = registry
        self._ctl = ctl
        self.floor = max(1, int(floor))
        self.ceiling = max(self.floor, int(ceiling))
        self.increase = max(1, int(increase))
        self.decrease = min(0.99, max(0.1, float(decrease)))
        self.target_wait_ms = float(target_wait_ms)
        self.interval_s = max(0.05, float(interval_s))
        self.burn_enter = float(burn_enter)
        self.burn_exit = float(burn_exit)
        self.hold_s = float(hold_s)
        self.retry_after_max_s = max(1, int(retry_after_max_s))

        self.transitions: deque = deque(maxlen=64)
        self._breakers: List[CircuitBreaker] = []
        self._budgets: List[RetryBudget] = []
        self._last_shed = ctl.shed
        self._last_shed_cap = ctl.shed_capacity
        self._last_waves: Optional[int] = None
        self._shed_rate = 0.0  # units/s over the last tick
        self._last_signals: Dict[str, object] = {}
        self._calm_since: Optional[float] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        m = self._metrics()
        if m is not None:
            # pre-register the transition vocabulary so scrapes show the
            # counters at 0 before the first brownout
            for direction in ("up", "down"):
                m.counter("keto_overload_transitions_total", 0.0,
                          help="brownout ladder stage transitions",
                          direction=direction)

    # -- plumbing ------------------------------------------------------------

    def _metrics(self):
        try:
            return self._r.metrics()
        except Exception:
            return None

    def _logger(self):
        try:
            return self._r.logger()
        except Exception:
            return None

    @property
    def stage(self) -> int:
        return self._ctl.stage

    @property
    def stage_name(self) -> str:
        return STAGE_NAMES[min(self._ctl.stage, len(STAGE_NAMES) - 1)]

    def register_breaker(self, breaker: CircuitBreaker) -> None:
        with self._lock:
            if breaker not in self._breakers:
                self._breakers.append(breaker)

    def register_budget(self, budget: RetryBudget) -> None:
        with self._lock:
            if budget not in self._budgets:
                self._budgets.append(budget)

    def breakers(self) -> List[CircuitBreaker]:
        """Registered breakers plus any lanes built after this
        controller (worker wire, DCN peers) — pulled from the registry
        so late-built lanes still show up in gauges and /debug."""
        with self._lock:
            found = list(self._breakers)
        try:
            lanes = self._r.breaker_lanes()
        except Exception:
            lanes = []
        for br in lanes:
            if br not in found:
                found.append(br)
        return found

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None or not self._ctl.enabled:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="keto-overload", daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # pragma: no cover - belt and braces
                log = self._logger()
                if log is not None:
                    log.exception("overload tick failed")

    # -- signals + actuation -------------------------------------------------

    def _signals(self) -> Dict[str, object]:
        wait_p50 = None
        waves = None
        try:
            ledger = self._r.wave_ledger()
            stats = ledger.stats() if ledger is not None else {}
            wait_p50 = stats.get("window_wait_ms_p50")
            waves = stats.get("waves_recorded")
        except Exception:
            pass
        burn = 0.0
        try:
            slo = self._r.slo()
            if slo is not None:
                # advance the ring first: the burn engine only folds new
                # counter deltas on sample(), and the watchdog's 5s
                # cadence is too coarse for a 500ms control loop
                slo.sample()
                burn = float(slo.max_burn("fast"))
        except Exception:
            pass
        return {"wait_p50_ms": wait_p50, "fast_burn": round(burn, 4),
                "waves_recorded": waves}

    def tick(self, now: Optional[float] = None) -> Dict[str, object]:
        """One control-loop step: sample signals, AIMD the limit, walk
        the brownout ladder.  Returns the signal dict (tests assert on
        it)."""
        now = time.monotonic() if now is None else now
        ctl = self._ctl
        if not ctl.enabled:
            return {}
        sig = self._signals()
        shed_now = ctl.shed
        shed_delta = max(0, shed_now - self._last_shed)
        self._last_shed = shed_now
        cap_now = ctl.shed_capacity
        cap_delta = max(0, cap_now - self._last_shed_cap)
        self._last_shed_cap = cap_now
        self._shed_rate = shed_delta / self.interval_s
        inflight, limit = ctl.inflight, ctl.limit
        burn = float(sig["fast_burn"])
        wait = sig["wait_p50_ms"]
        # the wave ledger's wait percentile is computed over its RING,
        # which holds old waves forever: once admission stops (full
        # shed), the signal freezes at its worst and would wedge both
        # the AIMD limit and the ladder.  Only trust it while new waves
        # actually landed since the last tick.
        waves = sig.get("waves_recorded")
        wait_fresh = True
        if waves is not None:
            wait_fresh = waves != self._last_waves
            self._last_waves = waves
        lat_bad = (wait_fresh and wait is not None
                   and wait > self.target_wait_ms) \
            or burn >= self.burn_enter

        # AIMD: multiplicative shrink on latency inflation / burn,
        # additive growth while constrained and healthy
        if lat_bad:
            new = max(self.floor, int(limit * self.decrease))
        elif shed_delta > 0 or inflight >= max(1, int(limit * 0.8)):
            new = min(self.ceiling, limit + self.increase)
        else:
            new = limit
        if new != limit:
            ctl.limit = new

        # brownout ladder: escalate while burning AND organically
        # shedding.  Only CAPACITY sheds (would not fit under the raw
        # limit) count as pressure — class-cap refusals at an elevated
        # stage are the ladder's own doing, and counting them would wedge
        # full-shed forever: every probe it refuses would read as fresh
        # overload.  Step down one stage per hold_s once capacity
        # pressure stops and wave wait is back under target.  The SLO
        # burn ring has minutes of memory, so it gates ENTRY only;
        # requiring it to cool before stepping down would hold a
        # brownout long after the storm ends.
        stage = ctl.stage
        wait_ok = (wait is None or not wait_fresh
                   or wait <= self.target_wait_ms)
        if burn >= self.burn_enter and cap_delta > 0:
            self._calm_since = None
            if stage < 3:
                self._transition(stage, stage + 1, now, sig, cap_delta)
        elif cap_delta == 0 and wait_ok:
            if self._calm_since is None:
                self._calm_since = now
            elif now - self._calm_since >= self.hold_s and stage > 0:
                self._transition(stage, stage - 1, now, sig, cap_delta)
                self._calm_since = now  # re-arm: one step per hold_s
        else:
            self._calm_since = None

        sig.update(shed_delta=shed_delta,
                   shed_capacity_delta=cap_delta,
                   shed_rate=round(self._shed_rate, 2),
                   inflight=inflight, limit=ctl.limit, stage=ctl.stage)
        self._last_signals = sig
        m = self._metrics()
        if m is not None:
            m.gauge("keto_admission_limit", float(ctl.limit),
                    help="current adaptive in-flight admission limit")
            m.gauge("keto_overload_stage", float(ctl.stage),
                    help="brownout ladder stage (0=normal .. 3=full shed)")
        return sig

    def _transition(self, old: int, new: int, now: float,
                    sig: Dict[str, object], shed_delta: int) -> None:
        self._ctl.stage = new
        entry = {
            "t": time.time(), "from": old, "to": new,
            "from_name": STAGE_NAMES[old], "to_name": STAGE_NAMES[new],
            "fast_burn": sig.get("fast_burn"),
            "wait_p50_ms": sig.get("wait_p50_ms"),
            "shed_delta": shed_delta,
        }
        self.transitions.append(entry)
        direction = "up" if new > old else "down"
        m = self._metrics()
        if m is not None:
            m.counter("keto_overload_transitions_total", 1.0,
                      help="brownout ladder stage transitions",
                      direction=direction)
            m.gauge("keto_overload_stage", float(new),
                    help="brownout ladder stage (0=normal .. 3=full shed)")
        log = self._logger()
        if log is not None:
            log.warning(
                "overload ladder %s: %s -> %s "
                "(burn=%s wait_p50_ms=%s shed_delta=%d)",
                direction, STAGE_NAMES[old], STAGE_NAMES[new],
                sig.get("fast_burn"), sig.get("wait_p50_ms"), shed_delta,
            )

    def force_stage(self, stage: int, reason: str = "forced") -> None:
        """Jump the ladder (operator/test override) with a logged edge."""
        stage = max(0, min(3, int(stage)))
        old = self._ctl.stage
        if stage == old:
            return
        self._transition(old, stage, time.monotonic(),
                         {"fast_burn": reason, "wait_p50_ms": None}, 0)

    # -- cooperative retry hint ----------------------------------------------

    def retry_after(self) -> int:
        """Load-derived, jittered Retry-After seconds (integer >= 1).

        Grows with ladder stage and recent shed rate; +-25% jitter keeps
        a shed cohort from stampeding back in the same second.
        """
        base = 1.0 + 2.0 * self._ctl.stage + min(4.0, self._shed_rate / 50.0)
        val = base * random.uniform(0.75, 1.25)
        return max(1, min(self.retry_after_max_s, int(math.ceil(val))))

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        ctl = self._ctl.snapshot()
        with self._lock:
            breakers = [b.snapshot() for b in self._breakers]
            budgets = [b.snapshot() for b in self._budgets]
        return {
            "stage": ctl["stage"],
            "stage_name": ctl["stage_name"],
            "admission": ctl,
            "limits": {"floor": self.floor, "ceiling": self.ceiling,
                       "increase": self.increase, "decrease": self.decrease,
                       "target_wait_ms": self.target_wait_ms},
            "signals": dict(self._last_signals),
            "retry_after_hint": self.retry_after(),
            "breakers": breakers,
            "retry_budgets": budgets,
            "transitions": list(self.transitions),
        }
