"""REST routes over the handler cores (httprouter/negroni analog).

Implements the reference's HTTP surface with its status-code quirks:

read port (`daemon.go:329-366`):
  GET/POST /relation-tuples/check            403-mirror (handler.go:121-154)
  GET/POST /relation-tuples/check/openapi    always 200 (handler.go:99-110)
  GET      /relation-tuples/expand           (expand/handler.go:62-111)
  GET      /relation-tuples                  (read_server.go:110-199)
  GET      /namespaces                       (namespacehandler/handler.go:39)
write port (`daemon.go:367-403`):
  PUT      /admin/relation-tuples            201 + Location (transact_server.go:134-176)
  DELETE   /admin/relation-tuples            204, query-validated (:188-243)
  PATCH    /admin/relation-tuples            204 (:245-309)
opl port (`daemon.go:405-440`):
  POST     /opl/syntax/check                 (schema/handler.go:38-45)
every port (healthx + metrics, `registry_default.go:128-182`):
  GET /health/alive, /health/ready, /version, /metrics/prometheus

Errors are herodot-shaped JSON: ``{"error": {"code", "status", "message"}}``.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import urlencode

from ketotpu import consistency, flightrec
from ketotpu.cache import context as cache_context
from ketotpu.engine import columns
from ketotpu.api.types import (
    BadRequestError,
    KetoAPIError,
    NotFoundError,
    RelationQuery,
    RelationTuple,
    SubjectSet,
)
from ketotpu.observability import RELATIONTUPLES_CREATED

_STATUS_TEXT = {
    200: "OK",
    201: "Created",
    204: "No Content",
    400: "Bad Request",
    403: "Forbidden",
    404: "Not Found",
    405: "Method Not Allowed",
    412: "Precondition Failed",
    429: "Too Many Requests",
    500: "Internal Server Error",
    503: "Service Unavailable",
    504: "Gateway Timeout",
}

# requests that must work even when admission control is shedding: probes
# and scrapes are how operators see the overload.  The watch stream is
# exempt BY DESIGN, not oversight: a long-lived SSE stream parked on a
# heartbeat would pin an admission slot forever and starve point reads;
# the watch hub's own watch.max_subscribers cap bounds subscribers
# instead (excess subscribes get 429 from the hub).
_ADMISSION_EXEMPT = {
    "/health/alive", "/health/ready", "/version", "/metrics/prometheus",
    "/relation-tuples/watch",
    # the introspection probes exist to diagnose overload — shedding them
    # while shedding traffic would blind the operator exactly when the
    # surfaces matter most
    "/debug/flight-recorder", "/debug/waves", "/debug/compiles",
    "/debug/profile", "/debug/projection", "/debug/mesh",
    "/debug", "/debug/trace", "/debug/divergence", "/debug/handoff",
    "/debug/slo", "/debug/fleet", "/debug/incidents", "/debug/overload",
    "/debug/tenants",
}

# REST paths that get the full stage decomposition (flightrec context);
# everything else still gets the http duration histogram + access log
_RPC_OPS = {
    "/relation-tuples/check": "check",
    "/relation-tuples/check/openapi": "check",
    "/relation-tuples/check/batch": "check",
    "/relation-tuples/batch/check": "check",
    "/relation-tuples/batch/expand": "expand",
    "/relation-tuples/expand": "expand",
    "/relation-tuples/list-objects": "list_objects",
    "/relation-tuples/list-subjects": "list_subjects",
    "/relation-tuples/watch": "watch",
}

# admin DELETE rejects unknown query params (internal/x/validate, used at
# transact_server.go:193-199); these are ketoapi.RelationQueryKeys
_QUERY_KEYS = {
    "namespace", "object", "relation",
    "subject_id", "subject_set.namespace", "subject_set.object",
    "subject_set.relation",
}


def _flatten_query(qs: Dict[str, list]) -> Dict[str, str]:
    return {k: v[0] for k, v in qs.items() if v}


def _consistency_params(q: Dict[str, str]):
    """(snaptoken, latest) read-consistency query params.  `latest` takes
    the usual REST boolean spellings; anything else is a client bug."""
    token = q.get("snaptoken") or None
    raw = q.get("latest")
    if raw is None:
        return token, False
    if raw.lower() in ("true", "1", "yes", ""):
        return token, True
    if raw.lower() in ("false", "0", "no"):
        return token, False
    raise BadRequestError(
        f"unable to parse 'latest' query parameter as bool: {raw!r}"
    )


def _batch_consistency(body: dict, q: Dict[str, str]):
    """(snaptoken, latest) for a batch request: ONE consistency mode for
    the whole batch, from the JSON body (preferred) or query params."""
    token, latest = _consistency_params(q)
    if body.get("snaptoken"):
        token = str(body["snaptoken"])
    if body.get("latest") is not None:
        latest = bool(body["latest"])
    return token, latest


class StreamingResponse:
    """Route payload for long-lived streaming responses (the SSE watch
    stream): instead of buffering a body, the HTTP handler writes chunks
    as ``iterator`` yields them and closes the connection afterwards."""

    def __init__(self, iterator, content_type: str = "text/event-stream"):
        self.iterator = iterator
        self.content_type = content_type


def _max_depth(q: Dict[str, str]) -> int:
    """x/max_depth.go:13-24 parity incl. the bad-request error text.

    The reference parses with Go's base-0 syntax (strconv.ParseInt(s, 0, 0)):
    hex "0x10" is 16 and bare leading-zero "010" is octal 8.  Python's
    int(s, 0) matches except that it rejects the bare-leading-zero octal
    form as ambiguous, so that case is handled explicitly."""
    if "max-depth" not in q:
        return 0
    s = q["max-depth"]
    try:
        return int(s, 0)
    except ValueError:
        core = s.lstrip("+-")
        if core.startswith("0") and core.isdigit():
            try:
                v = int(core, 8)
            except ValueError:  # "089": invalid octal in Go base-0 too
                pass
            else:
                return -v if s.startswith("-") else v
        raise BadRequestError(
            f"unable to parse 'max-depth' query parameter to int: "
            f"invalid syntax {s!r}"
        ) from None


def cors_headers(
    cors: Dict, origin: Optional[str], *,
    request_method: Optional[str] = None, preflight: bool = False,
) -> Optional[Dict[str, str]]:
    """rs/cors-shaped decision (the reference wires rs/cors per port,
    `internal/driver/daemon.go:230-265` + `embedx/config.schema.json:
    214-259`): response headers for an allowed origin, None otherwise."""
    import fnmatch

    if not cors or origin is None:
        return None
    allowed = any(
        o == "*" or fnmatch.fnmatch(origin, o)
        for o in cors["allowed_origins"]
    )
    if not allowed:
        return None
    h = {"Vary": "Origin"}
    wildcard = "*" in cors["allowed_origins"] and not cors["allow_credentials"]
    h["Access-Control-Allow-Origin"] = "*" if wildcard else origin
    if cors["allow_credentials"]:
        h["Access-Control-Allow-Credentials"] = "true"
    if preflight:
        methods = [m.upper() for m in cors["allowed_methods"]]
        if request_method and request_method.upper() not in methods:
            return None
        h["Access-Control-Allow-Methods"] = ", ".join(methods)
        h["Access-Control-Allow-Headers"] = ", ".join(cors["allowed_headers"])
        if cors.get("max_age"):
            h["Access-Control-Max-Age"] = str(cors["max_age"])
    elif cors.get("exposed_headers"):
        h["Access-Control-Expose-Headers"] = ", ".join(
            cors["exposed_headers"]
        )
    return h


class Router:
    """Method+path exact-match routing table shared by all ports."""

    def __init__(self, registry, endpoint: str):
        self.r = registry
        self.endpoint = endpoint
        cors_for = getattr(registry.config, "cors_config", None)
        self.cors = cors_for(endpoint) if cors_for else None
        self.routes: Dict[Tuple[str, str], Callable] = {}
        # one-line operator docs per route; /debug derives its index from
        # these so a new surface can never be forgotten from the listing
        self.route_docs: Dict[Tuple[str, str], str] = {}
        self._register_common()

    def add(self, method: str, path: str, fn: Callable,
            describe: Optional[str] = None) -> None:
        self.routes[(method, path)] = fn
        if describe:
            self.route_docs[(method, path)] = describe

    def debug_surfaces(self) -> Dict[str, str]:
        """{path: one-liner} for every routed /debug/* surface (the
        /debug index body) — generated from the routing table, so the
        index and the routes cannot drift apart."""
        surfaces: Dict[str, str] = {}
        for (method, path) in sorted(self.routes):
            if path == "/debug" or not path.startswith("/debug/"):
                continue
            doc = self.route_docs.get((method, path), "")
            if method != "GET" and not doc.startswith(method):
                doc = f"{method}: {doc}" if doc else method
            surfaces[path] = doc
        return surfaces

    # -- common routes (healthx + metrics on every router) -------------------

    def _register_common(self) -> None:
        self.add("GET", "/health/alive", self._alive)
        self.add("GET", "/health/ready", self._ready)
        self.add("GET", "/version", self._version)
        self.add("GET", "/metrics/prometheus", self._metrics)

    def _alive(self, req) -> Tuple[int, object]:
        return 200, {"status": "ok"}

    def _ready(self, req) -> Tuple[int, object]:
        health = self.r.health()
        errors = {k: v for k, v in health.items() if v != "ok"}
        if not errors:
            return 200, {"status": "ok"}
        # degraded-only (device engine on CPU fallback, worker respawning):
        # still ready — answering traffic is the point of degrading — but
        # surfaced so `status --block` can tell degraded from down
        if all(str(v).startswith("degraded") for v in errors.values()):
            return 200, {"status": "degraded", "degraded": errors}
        return 503, {"errors": errors}

    def _version(self, req) -> Tuple[int, object]:
        return 200, {"version": self.r.version}

    def _metrics(self, req) -> Tuple[int, object]:
        sample = getattr(self.r, "sample_engine_metrics", None)
        if sample is not None:
            sample()  # refresh device-engine gauges at scrape time
        return 200, ("text/plain; version=0.0.4", self.r.metrics().exposition())

    # -- dispatch ------------------------------------------------------------

    def dispatch(self, method: str, path: str, req) -> Tuple[int, object, Dict]:
        try:
            # embedder middlewares run outermost (negroni-style chain,
            # ketoctx WithHTTPMiddlewares); each gets a zero-arg `next`
            chain = lambda: self._route(method, path, req)  # noqa: E731
            for mw in reversed(self.r.options.rest_middlewares):
                chain = (lambda m, nxt: lambda: m(method, path, req, nxt))(
                    mw, chain
                )
            return chain()
        except KetoAPIError as e:
            code = e.status_code or 500
            # shed responses carry the backoff hint the reference's
            # rate-limit middlewares send — load-derived + jittered so a
            # shed cohort does not stampede back in lockstep
            headers = (
                {"Retry-After": self.r.retry_after_hint()}
                if code in (429, 503) else {}
            )
            return code, _error_body(code, str(e)), headers
        except Exception as e:  # noqa: BLE001 - the panic-recovery interceptor
            self.r.logger().exception("handler panic: %s", e)
            return 500, _error_body(500, str(e)), {}

    def _route(self, method: str, path: str, req) -> Tuple[int, object, Dict]:
        fn = self.routes.get((method, path))
        if fn is None:
            known_methods = [m for (m, p) in self.routes if p == path]
            if known_methods:
                return 405, _error_body(405, "method not allowed"), {}
            return 404, _error_body(404, "route not found"), {}
        out = fn(req)
        if len(out) == 2:
            status, body = out
            headers: Dict[str, str] = {}
        else:
            status, body, headers = out
        return status, body, headers


def _error_body(code: int, message: str) -> dict:
    return {
        "error": {
            "code": code,
            "status": _STATUS_TEXT.get(code, "error"),
            "message": message,
        }
    }


class Request:
    """Parsed request handed to route functions."""

    def __init__(
        self,
        query: Dict[str, str],
        body: bytes,
        headers: Optional[Dict[str, str]] = None,
    ):
        self.query = query
        self.body = body
        self.headers = headers or {}  # lower-cased names

    def json(self):
        try:
            return json.loads(self.body.decode("utf-8") or "null")
        except (ValueError, UnicodeDecodeError) as e:
            raise BadRequestError(f"could not unmarshal json: {e}") from None


# -- route construction per port ---------------------------------------------


def read_router(registry) -> Router:
    from ketotpu.server.handlers import (
        CheckHandler,
        ExpandHandler,
        NamespaceHandler,
        RelationTupleHandler,
    )

    rt = Router(registry, "read")
    check = CheckHandler(registry)
    expand = ExpandHandler(registry)
    tuples = RelationTupleHandler(registry)
    namespaces = NamespaceHandler(registry)

    def get_check(mirror: bool):
        def handler(req):
            tuple_ = RelationTuple.from_url_query(req.query)
            token, latest = _consistency_params(req.query)
            allowed = check.check_rest(
                tuple_, _max_depth(req.query), req.headers,
                snaptoken=token, latest=latest,
            )
            status = 403 if (mirror and not allowed) else 200
            return status, {"allowed": allowed}

        return handler

    def post_check(mirror: bool):
        def handler(req):
            tuple_ = RelationTuple.from_json(req.json() or {})
            token, latest = _consistency_params(req.query)
            allowed = check.check_rest(
                tuple_, _max_depth(req.query), req.headers,
                snaptoken=token, latest=latest,
            )
            status = 403 if (mirror and not allowed) else 200
            return status, {"allowed": allowed}

        return handler

    rt.add("GET", "/relation-tuples/check", get_check(mirror=True))
    rt.add("POST", "/relation-tuples/check", post_check(mirror=True))
    rt.add("GET", "/relation-tuples/check/openapi", get_check(mirror=False))
    rt.add("POST", "/relation-tuples/check/openapi", post_check(mirror=False))

    def post_check_batch(req):
        # EXTENSION endpoint (no reference counterpart): one request, many
        # verdicts, answered by the engine's batched device dispatch
        body = req.json()
        if not isinstance(body, dict) or not isinstance(
            body.get("tuples"), list
        ):
            raise BadRequestError('expected {"tuples": [...]}')
        tuples_in = [RelationTuple.from_json(d or {}) for d in body["tuples"]]
        r = registry.resolve(req.headers)
        token, latest = _consistency_params(req.query)
        decoded = None
        if token or latest:
            decoded = consistency.ensure_fresh(r, token, latest, op="check")
        with cache_context.request_scope(r, req.headers, token=decoded,
                                         latest=latest):
            results = check.batch_check_core(
                tuples_in, _max_depth(req.query), r
            )
        return 200, {
            "results": [{"allowed": a} for a in results],
            "snaptoken": check.snaptoken(r),
        }

    rt.add("POST", "/relation-tuples/check/batch", post_check_batch)

    def post_batch_check(req):
        # batch front door (ISSUE 7): per-item verdicts/errors, one shared
        # consistency mode + snaptoken, per-item admission accounting.
        # Supersedes /relation-tuples/check/batch (kept for compat).
        from ketotpu.server.handlers import batch_admission, record_batch

        body = req.json()
        if not isinstance(body, dict) or not isinstance(
            body.get("tuples"), list
        ):
            raise BadRequestError('expected {"tuples": [...]}')
        raw = body["tuples"]
        r = registry.resolve(req.headers)
        # COLUMNAR by default (ISSUE 9): the raw tuples list is decoded
        # once into string columns, answered as one block through the
        # engine, and the response frame is scattered from the verdict
        # array in two bytes.join passes — engine.columnar_batch=false
        # restores the per-item scalar path.
        columnar = bool(r.config.get("engine.columnar_batch", True))
        token, latest = _batch_consistency(body, req.query)
        depth = body.get("max_depth")
        depth = int(depth) if depth is not None else _max_depth(req.query)
        flightrec.note(batch=len(raw))
        record_batch(r, "check", len(raw))
        with batch_admission(r, len(raw)):
            decoded = None
            if token or latest:
                decoded = consistency.ensure_fresh(
                    r, token, latest, op="check"
                )
            with cache_context.request_scope(r, req.headers, token=decoded,
                                             latest=latest):
                if columnar:
                    allowed, errors = check.batch_check_columnar(
                        raw, depth, r
                    )
                else:
                    items = []
                    for d in raw:
                        try:
                            # a bad tuple becomes ITS item's error, not
                            # the batch's
                            items.append(RelationTuple.from_json(d or {}))
                        except KetoAPIError as e:
                            items.append(e)
                    results = check.batch_check_items(items, depth, r)
        if not columnar:
            return 200, {
                "results": results,
                "snaptoken": check.snaptoken(r),
            }
        t0 = time.perf_counter()
        frags = columns.verdict_fragments(allowed)
        for i, err in errors.items():
            frags[i] = columns.error_fragment(err[0], err[1])
        data = columns.render_batch_body(frags, check.snaptoken(r))
        flightrec.note_stage("respond", time.perf_counter() - t0)
        return 200, ("application/json", data)

    rt.add("POST", "/relation-tuples/batch/check", post_batch_check)

    def post_batch_expand(req):
        from ketotpu.server.handlers import batch_admission, record_batch

        body = req.json()
        if not isinstance(body, dict) or not isinstance(
            body.get("subjects"), list
        ):
            raise BadRequestError('expected {"subjects": [...]}')
        items = []
        for d in body["subjects"]:
            if not isinstance(d, dict):
                items.append(BadRequestError("subject must be an object"))
                continue
            items.append(SubjectSet(
                namespace=str(d.get("namespace", "")),
                object=str(d.get("object", "")),
                relation=str(d.get("relation", "")),
            ))
        r = registry.resolve(req.headers)
        token, latest = _batch_consistency(body, req.query)
        depth = body.get("max_depth")
        depth = int(depth) if depth is not None else _max_depth(req.query)
        flightrec.note(batch=len(items))
        record_batch(r, "expand", len(items))
        with batch_admission(r, len(items)):
            decoded = None
            if token or latest:
                decoded = consistency.ensure_fresh(
                    r, token, latest, op="expand"
                )
            with cache_context.request_scope(r, req.headers, token=decoded,
                                             latest=latest):
                results = expand.batch_expand_items(items, depth, r)
        enc = []
        for res in results:
            if "tree" in res:
                if res["tree"] is None:
                    enc.append({
                        "error": "no relation tuple found", "status": 404,
                    })
                else:
                    enc.append({"tree": res["tree"].to_json()})
            else:
                enc.append(res)
        return 200, {
            "results": enc,
            "snaptoken": consistency.mint(
                r.store(), r._device_engine()
            ).encode(),
        }

    rt.add("POST", "/relation-tuples/batch/expand", post_batch_expand)

    def get_expand(req):
        subject = SubjectSet(
            namespace=req.query.get("namespace", ""),
            object=req.query.get("object", ""),
            relation=req.query.get("relation", ""),
        )
        r = registry.resolve(req.headers)
        token, latest = _consistency_params(req.query)
        decoded = None
        if token or latest:
            decoded = consistency.ensure_fresh(r, token, latest, op="expand")
        with cache_context.request_scope(r, req.headers, token=decoded,
                                         latest=latest):
            tree = expand.expand_core(subject, _max_depth(req.query), r)
        if tree is None:
            return 404, _error_body(404, "no relation tuple found")
        return 200, tree.to_json()

    rt.add("GET", "/relation-tuples/expand", get_expand)

    def get_relations(req):
        query = RelationQuery.from_url_query(req.query)
        page_size = 0
        if "page_size" in req.query:
            try:
                page_size = int(req.query["page_size"])
            except ValueError as e:
                raise BadRequestError(str(e)) from None
        r = registry.resolve(req.headers)
        token, latest = _consistency_params(req.query)
        if token or latest:
            # list reads the store directly, so the barrier only needs
            # the store to have reached the token — not the device view
            consistency.ensure_fresh(
                r, token, latest, op="list", use_engine=False
            )
        out, next_token = tuples.list_core(
            query, page_size, req.query.get("page_token", ""), r,
        )
        return 200, {
            "relation_tuples": [t.to_json() for t in out],
            "next_page_token": next_token,
        }

    rt.add("GET", "/relation-tuples", get_relations)

    def _page_args(req):
        page_size = 0
        if "page_size" in req.query:
            try:
                page_size = int(req.query["page_size"])
            except ValueError as e:
                raise BadRequestError(str(e)) from None
        return page_size, req.query.get("page_token", "")

    def get_list_objects(req):
        # Leopard reverse query: objects the subject reaches in
        # namespace#relation through the closure index (host-oracle
        # fallback on dirty sets).  Rows come back as full relation
        # tuples so clients reuse the ListRelationTuples decoding.
        query = RelationQuery.from_url_query(req.query)
        page_size, page_token = _page_args(req)
        objs, next_token = tuples.list_objects_core(
            query.namespace, query.relation, query.subject(),
            page_size, page_token, registry.resolve(req.headers),
        )
        subject = query.subject()
        return 200, {
            "relation_tuples": [
                RelationTuple(
                    query.namespace, o, query.relation, subject
                ).to_json()
                for o in objs
            ],
            "objects": objs,
            "next_page_token": next_token,
        }

    def get_list_subjects(req):
        query = RelationQuery.from_url_query(req.query)
        page_size, page_token = _page_args(req)
        subs, next_token = tuples.list_subjects_core(
            query.namespace, query.object, query.relation,
            page_size, page_token, registry.resolve(req.headers),
        )
        return 200, {
            "relation_tuples": [
                RelationTuple(
                    query.namespace, query.object, query.relation, s
                ).to_json()
                for s in subs
            ],
            "next_page_token": next_token,
        }

    rt.add("GET", "/relation-tuples/list-objects", get_list_objects)
    rt.add("GET", "/relation-tuples/list-subjects", get_list_subjects)

    def get_namespaces(req):
        return 200, {
            "namespaces": [{"name": ns.name} for ns in namespaces.list_core()]
        }

    rt.add("GET", "/namespaces", get_namespaces)

    def get_watch(req):
        # EXTENSION endpoint: Zanzibar Watch over SSE.  Subscribe before
        # returning so subscribe-time errors (bad token, subscriber cap)
        # still come back as ordinary JSON error bodies; only once the
        # stream is live do errors degrade to a dropped connection.
        r = registry.resolve(req.headers)
        hub = r.watch_hub()
        sub = hub.subscribe(
            snaptoken=req.query.get("snaptoken") or None,
            namespace=req.query.get("namespace") or None,
        )
        flightrec.note(resume=bool(req.query.get("snaptoken")))
        heartbeat_s = (
            float(r.config.get("watch.heartbeat_ms", 15000) or 15000)
            / 1000.0
        )

        def gen():
            try:
                # SSE comment line: flushes proxy buffers and lets the
                # client see the stream is open before the first event
                yield b": watch stream open\n\n"
                for ev in sub.events(heartbeat_s):
                    data = {"snaptoken": ev.snaptoken or ""}
                    if ev.kind == consistency.DELTA:
                        data["action"] = ev.action
                        data["relation_tuple"] = ev.tuple.to_json()
                    yield (
                        f"event: {ev.kind}\n"
                        f"data: {json.dumps(data)}\n\n"
                    ).encode("utf-8")
            finally:
                hub.unsubscribe(sub)

        return 200, StreamingResponse(gen())

    rt.add("GET", "/relation-tuples/watch", get_watch)
    return rt


def write_router(registry) -> Router:
    from ketotpu.server.handlers import RelationTupleHandler

    rt = Router(registry, "write")
    tuples = RelationTupleHandler(registry)

    def _post_write_token(r) -> str:
        # post-commit snaptoken, echoed in a response header so REST
        # writers can do read-your-writes without a second round trip
        return consistency.mint(r.store(), r._device_engine()).encode()

    def put_tuple(req):
        tuple_ = RelationTuple.from_json(req.json() or {})
        r = registry.resolve(req.headers)
        tuples.transact_core([tuple_], [], r)
        registry.tracer().event(RELATIONTUPLES_CREATED)
        # urlencode: raw values in a header invite response splitting
        location = "/relation-tuples?" + urlencode(tuple_.to_url_query())
        return 201, tuple_.to_json(), {
            "Location": location,
            "X-Keto-Snaptoken": _post_write_token(r),
        }

    def delete_tuples(req):
        # validate.All parity (transact_server.go:193-199)
        extra = set(req.query) - _QUERY_KEYS
        if extra:
            raise BadRequestError(
                f"unexpected query parameters: {sorted(extra)}"
            )
        if "namespace" not in req.query:
            raise BadRequestError("required query parameter 'namespace' is missing")
        if req.body:
            raise BadRequestError("the request body must be empty")
        query = RelationQuery.from_url_query(req.query)
        r = registry.resolve(req.headers)
        tuples.delete_all_core(query, r)
        return 204, None, {"X-Keto-Snaptoken": _post_write_token(r)}

    def patch_tuples(req):
        deltas = req.json()
        if not isinstance(deltas, list):
            raise BadRequestError("expected a JSON list of patch deltas")
        inserts, deletes = [], []
        for d in deltas:
            if not isinstance(d, dict) or d.get("relation_tuple") is None:
                raise BadRequestError("relation_tuple is missing")
            t = RelationTuple.from_json(d["relation_tuple"])
            action = d.get("action")
            if action == "insert":
                inserts.append(t)
            elif action == "delete":
                deletes.append(t)
            else:
                raise BadRequestError(f"unknown action {action}")
        r = registry.resolve(req.headers)
        tuples.transact_core(inserts, deletes, r)
        return 204, None, {"X-Keto-Snaptoken": _post_write_token(r)}

    rt.add("PUT", "/admin/relation-tuples", put_tuple)
    rt.add("DELETE", "/admin/relation-tuples", delete_tuples)
    rt.add("PATCH", "/admin/relation-tuples", patch_tuples)

    # -- tenant lifecycle (ketotpu/tenancy/): admin-port surface ----------

    def _plane():
        plane = registry.tenant_plane()
        if plane is None:
            raise NotFoundError(
                "tenancy is not enabled (set tenancy.enabled with the "
                "in-memory dsn)"
            )
        return plane

    def post_tenant(req):
        body = req.json() or {}
        nid = body.get("id")
        if not isinstance(nid, str) or not nid:
            raise BadRequestError("'id' is required")
        plane = _plane()
        out = plane.create(nid)
        opl = body.get("opl")
        if isinstance(opl, str) and opl.strip():
            out["opl"] = plane.set_opl(nid, opl)
        return (201 if out.get("created") else 200), out

    def get_tenants(req):
        return 200, {"tenants": _plane().catalog()}

    def delete_tenant(req):
        nid = req.query.get("id", "")
        if not nid:
            raise BadRequestError("required query parameter 'id' is missing")
        return 200, _plane().delete(nid)

    def post_tenant_opl(req):
        body = req.json() or {}
        nid = body.get("id")
        if not isinstance(nid, str) or not nid:
            raise BadRequestError("'id' is required")
        source = body.get("opl", "")
        if not isinstance(source, str):
            raise BadRequestError("'opl' must be a string (empty clears)")
        return 200, _plane().set_opl(nid, source)

    rt.add("POST", "/admin/tenants", post_tenant)
    rt.add("GET", "/admin/tenants", get_tenants)
    rt.add("DELETE", "/admin/tenants", delete_tenant)
    rt.add("POST", "/admin/tenants/opl", post_tenant_opl)
    return rt


def opl_router(registry) -> Router:
    from ketotpu.server.handlers import SyntaxHandler

    rt = Router(registry, "opl")
    syntax = SyntaxHandler(registry)

    def post_syntax(req):
        errors = syntax.check_core(req.body)
        return 200, {"errors": [e.to_json() for e in errors]}

    rt.add("POST", "/opl/syntax/check", post_syntax)
    return rt


def metrics_router(registry) -> Router:
    rt = Router(registry, "metrics")

    def get_flight_recorder(req):
        # debug surface on the metrics port only (admin-port hygiene):
        # the N slowest recent requests with their stage vectors, plus
        # the hot-spot shield's top-K hottest keys (count-min estimates)
        rec = registry.flight_recorder()
        rc = registry.result_cache()
        return 200, {
            "slowest": rec.snapshot(),
            "hot_keys": rc.hot_keys() if rc is not None else [],
            # a slow-check investigation usually starts with "was a
            # compaction in flight?" — ride the projection state along
            "projection": registry.projection_stats(),
        }

    rt.add("GET", "/debug/flight-recorder", get_flight_recorder,
           describe="N slowest recent requests with stage vectors + "
                    "hot keys")

    def get_waves(req):
        # wave ledger (ketotpu/waveledger.py): the last N dispatched
        # waves.  ?wave=<id> joins from a flight-recorder entry's wave=
        # field back to its wave; ?n= bounds the listing.  Each entry's
        # slowest[] traceparents join the other direction.
        ledger = registry.wave_ledger()
        wave = req.query.get("wave")
        n = req.query.get("n")
        try:
            wave = int(wave) if wave is not None else None
            n = int(n) if n is not None else None
        except ValueError:
            raise BadRequestError("wave and n must be integers")
        return 200, {
            "stats": ledger.stats(),
            "waves": ledger.snapshot(n=n, wave=wave),
        }

    rt.add("GET", "/debug/waves", get_waves,
           describe="wave ledger: recent device dispatch windows "
                    "(?wave=<id>)")

    def get_compiles(req):
        # XLA compile observatory (ketotpu/compilewatch.py): totals per
        # entry point + the bounded compile event log; `warm` tells
        # whether the next compile would fire the after-warm alarm
        return 200, registry.compile_watch().snapshot()

    rt.add("GET", "/debug/compiles", get_compiles,
           describe="XLA compile observatory: totals + bounded event log")

    def get_projection(req):
        # projection/compaction observability (engine/tpu.py): snapshot
        # generation, fold/rebuild/compaction counters, overlay occupancy
        # and the cursor triple (snap <= served <= log); {} when the
        # engine kind has no device projection
        return 200, registry.projection_stats()

    rt.add("GET", "/debug/projection", get_projection,
           describe="device projection: generation, folds, overlay, "
                    "cursors")

    def get_mesh(req):
        # sharded-serving state (parallel/meshengine.py): per-shard
        # batches/fallbacks/replica keys/down flags, the published
        # replica map, the replication/rebalance/failover counters, and
        # — on a multi-host topology — per-peer rows (id, liveness,
        # heartbeat age, shards owned, replica keys, frontier round
        # trips) so `status --debug` explains a degraded topology;
        # {} when the engine is not sharded
        eng = registry.check_engine()
        eng = getattr(eng, "inner", eng)
        stats_fn = getattr(eng, "mesh_stats", None)
        if stats_fn is None:
            return 200, {}
        peers_fn = getattr(eng, "peer_stats", None)
        return 200, {
            **stats_fn(),
            "shards": eng.shard_stats(),
            "replica_map": [
                {"ns": k[0], "obj": k[1], "replicas": list(v)}
                for k, v in sorted(eng._replica_map.items())
            ],
            "hosts": peers_fn() if peers_fn is not None else [],
        }

    rt.add("GET", "/debug/mesh", get_mesh,
           describe="sharded serving: per-shard state + replica map")

    def post_profile(req):
        # on-demand jax.profiler capture: config-gated (403 unarmed),
        # single-flight (409 while a capture runs), seconds clamped
        from ketotpu.profiler import ProfilerBusy, ProfilerDisabled

        try:
            seconds = float(req.query.get("seconds", "5"))
        except ValueError:
            raise BadRequestError("seconds must be a number")
        try:
            artifact = registry.profiler().capture(seconds)
        except ProfilerDisabled as e:
            return 403, {"error": {"code": 403, "message": str(e)}}
        except ProfilerBusy as e:
            return 409, {"error": {"code": 409, "message": str(e)}}
        return 200, artifact

    rt.add("POST", "/debug/profile", post_profile,
           describe="POST: on-demand jax.profiler capture (config-gated)")

    def post_handoff(req):
        # deliberate takeover (rolling restart): tells the warm-standby
        # follower attached to this registry to promote itself NOW instead
        # of waiting out the heartbeat-miss budget.  409 when no standby
        # machinery is wired (a plain owner/daemon process).
        fn = getattr(registry, "handoff_fn", None)
        if fn is None:
            return 409, {"error": {
                "code": 409,
                "message": "no standby attached to this process; handoff"
                           " is served by the follower's metrics port",
            }}
        reason = str(req.query.get("reason", "handoff") or "handoff")
        return 200, dict(fn(reason) or {}, reason=reason)

    rt.add("POST", "/debug/handoff", post_handoff,
           describe="POST: promote the attached warm standby now "
                    "(rolling restart; 409 when none)")

    def get_debug_index(req):
        # one stop for "what can I look at?": every debug surface on this
        # port with a one-liner, so an operator paging through an incident
        # doesn't need the README open to find the next probe.  Generated
        # from the routing table (Router.debug_surfaces) so adding a
        # surface automatically lists it here.
        return 200, {"surfaces": rt.debug_surfaces()}

    rt.add("GET", "/debug", get_debug_index)

    def get_trace(req):
        # the request-anatomy observatory's read side: newest promoted
        # traces (tail-sampled: slow/shed/deadline/error/divergence), or
        # one stitched cross-process timeline via ?trace=<id>
        ts = registry.trace_store()
        if ts is None:
            return 200, {"enabled": False, "traces": []}
        tid = req.query.get("trace")
        if tid:
            ent = ts.get(tid)
            if ent is None:
                raise NotFoundError(f"trace {tid!r} not held")
            return 200, ent
        n = req.query.get("n")
        try:
            n = int(n) if n is not None else 0
        except ValueError:
            raise BadRequestError("n must be an integer")
        return 200, {
            "enabled": True,
            "stats": ts.stats(),
            "traces": ts.promoted(n=n),
        }

    rt.add("GET", "/debug/trace", get_trace,
           describe="tail-sampled promoted traces (?trace=<id> for one "
                    "stitched timeline)")

    def get_divergence(req):
        # shadow-verification plane: the divergence ledger (each record
        # names the lying tier, wave, generation, and trace id) + sampler
        # stats; {} stats when the plane is off (workers, config)
        sh = registry.shadow()
        if sh is None:
            return 200, {"enabled": False, "divergences": [], "stats": {}}
        return 200, {
            "enabled": True,
            "stats": sh.stats(),
            "divergences": sh.ledger(),
        }

    rt.add("GET", "/debug/divergence", get_divergence,
           describe="shadow-verification divergence ledger + sampler "
                    "stats")

    def get_slo(req):
        # SLO burn-rate engine (ketotpu/slo.py): per-op availability and
        # latency-compliance SLIs over the fast (~5 min) and slow (~1 h)
        # windows, with the burn rate against the configured objectives
        slo = registry.slo()
        if slo is None:
            return 200, {"enabled": False}
        slo.sample()
        return 200, {"enabled": True, **slo.snapshot()}

    rt.add("GET", "/debug/slo", get_slo,
           describe="SLO burn rates: per-op availability/latency SLIs "
                    "over fast + slow windows")

    def get_fleet(req):
        # fleet health: this host's digest plus the last digest each DCN
        # peer shipped on its heartbeat.  A peer that has never sent one
        # (a pre-fleet-health binary) renders "unavailable" rather than
        # erroring — mixed-version meshes happen during rollouts.
        local = registry.health_digest()
        link = registry.hostlink()
        if link is None:
            return 200, {"multihost": False, "local": local, "peers": []}
        peers = []
        for row in link.peer_rows():
            digest = row.get("digest")
            peers.append({
                "peer": row.get("peer"),
                "addr": row.get("addr"),
                "down": row.get("down"),
                "heartbeat_age_s": row.get("heartbeat_age_s"),
                "digest": (
                    digest if isinstance(digest, dict) else "unavailable"
                ),
            })
        return 200, {"multihost": True, "local": local, "peers": peers}

    rt.add("GET", "/debug/fleet", get_fleet,
           describe="per-host health digests: local + last heartbeat "
                    "digest from every DCN peer")

    def get_incidents(req):
        # regression watchdog (ketotpu/watchdog.py): bounded incident
        # records, newest first; each names the firing rule, the detail
        # that tripped it, and the trace ids it force-promoted
        wd = registry.watchdog()
        if wd is None:
            return 200, {"enabled": False, "incidents": []}
        n = req.query.get("n")
        try:
            n = int(n) if n is not None else 0
        except ValueError:
            raise BadRequestError("n must be an integer")
        return 200, {
            "enabled": True,
            "stats": wd.stats(),
            "incidents": wd.incidents(n=n),
        }

    rt.add("GET", "/debug/incidents", get_incidents,
           describe="watchdog incidents: rule, detail, force-promoted "
                    "trace ids (newest first)")

    def get_overload(req):
        # overload-control plane (server/overload.py): ladder stage,
        # adaptive admission limit + per-class caps, AIMD signal sample,
        # breaker/retry-budget state and the recent transition log
        ov = registry.overload()
        if ov is None:
            ctl = registry.admission()
            return 200, {
                "enabled": False,
                "admission": ctl.snapshot() if ctl is not None else {},
            }
        return 200, {"enabled": True, **ov.snapshot()}

    rt.add("GET", "/debug/overload", get_overload,
           describe="overload plane: brownout stage, adaptive limit, "
                    "class caps, breakers, transitions")

    def get_tenants_debug(req):
        plane = registry.tenant_plane()
        if plane is None:
            return 200, {"enabled": False}
        return 200, {
            "enabled": True,
            **plane.stats(),
            "tenants": plane.catalog(),
        }

    rt.add("GET", "/debug/tenants", get_tenants_debug,
           describe="tenant plane: per-tenant tuples/traffic/quota "
                    "occupancy, OPL overrides, capacity")
    return rt


# -- HTTP server ------------------------------------------------------------


def make_http_server(router: Router, host: str, port: int,
                     reuse_port: bool = False, ssl_ctx=None):
    """Build the REST front end: an asyncio event-loop server (see
    server/aio.py) behind the lifecycle surface the daemon drives
    (``server_address`` / ``serve_forever`` / ``shutdown`` /
    ``server_close``).  ``reuse_port`` binds SO_REUSEPORT for the
    multi-process worker topology; ``ssl_ctx`` terminates TLS in the
    event loop (per-connection handshakes never block the accept loop).
    """
    from ketotpu.server.aio import AsyncHTTPServer

    return AsyncHTTPServer(
        router, host, port, reuse_port=reuse_port, ssl_ctx=ssl_ctx,
    )
