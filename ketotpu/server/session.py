"""Streaming check sessions: admit once, pump columnar blocks, verdicts
come back out of order as engine waves complete.

The north-star serving plane (ROADMAP top open item; SURVEY §3.2 names
the hot path to shortcut, §7 the design stance): every batch RPC still
pays per-request HTTP/gRPC framing plus admission re-entry.  A SESSION
amortizes the serving shell across a persistent connection,
Zanzibar-style — the client is admitted ONCE at the handshake
(session-scoped units under the PR 16 interactive class, tenant-resolved
per PR 17), then pumps check blocks with per-block sequence numbers.
Per-block traffic never re-enters the admission controller; backpressure
is a CREDIT window (max blocks in flight per session) enforced by the
reader simply not reading past it, so TCP flow control pushes back on
the client.

Two transports share this module:

* the raw TCP **session lane** (:class:`SessionLane`): `server/wire.py`
  frames carrying the exact `check_cols` columnar encoding the worker
  wire already uses (``skind`` uint8 + `pack_strcol` ns/obj/rel/sa/sb/sc)
  — no per-item tuple materialization, no HTTP parse;
* the gRPC ``CheckService.StreamCheck`` bidi RPC (handlers.py), which
  parses proto tuples per block but shares the same session broker, so
  admission/brownout/credit semantics are identical.

Brownout (PR 16): NEW sessions are refused at brownout stage >= 2 with a
Retry-After hint; established sessions keep draining because the
interactive class keeps a non-zero ceiling through stage 2 and blocks
never re-enter admission.

Lane protocol (all frames are wire.send_frame meta+arrays):

  client -> {"op": "hello", "v": 1, "units": U, "snaptoken": S,
             "latest": bool, "max_depth": D}
  server -> {"op": "hello", "ok": true, "session": sid, "credits": C,
             "max_block_rows": R}
          | {"op": "hello", "ok": false, "error": msg, "status": code,
             "retry_after": secs}            # then the server closes
  client -> {"op": "block", "seq": n, "n": rows [, "max_depth": D]
             [, "deadline_ms": T]}
             + arrays {"skind": uint8} and strcols ns/obj/rel/sa/sb/sc
  server -> {"op": "verdicts", "seq": n, "n": rows,
             "errs": [[row, msg, status], ...], "snaptoken": S}
             + arrays {"ok": uint8}          # OUT OF ORDER across seqs
          | {"op": "error", "seq": n, "error": msg, "status": code}
  client -> {"op": "ping"}   server -> {"op": "pong"}
  client -> {"op": "end"}    server drains in-flight blocks, then
  server -> {"op": "bye", "blocks": B, "rows": N}
"""

from __future__ import annotations

import select
import socket
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional

import numpy as np

from ketotpu import consistency, deadline, flightrec
from ketotpu.api.types import KetoAPIError
from ketotpu.cache import context as cache_context
from ketotpu.engine import columns
from ketotpu.server import wire
from ketotpu.server.admission import CLASS_INTERACTIVE

# lane frame caps: meta is a small dict (64 MB default is absurd for an
# untrusted client lane); the binary part carries the packed string
# columns of ONE block, so 256 MB bounds even pathological ids
_LANE_MAX_META = 8 << 20
_LANE_MAX_BIN = 256 << 20

_STRCOLS = ("ns", "obj", "rel", "sa", "sb", "sc")


class SessionRefused(Exception):
    """Handshake refusal: maps to 429/503/507 + Retry-After on both
    transports (the lane hello-nack and the gRPC handshake response)."""

    def __init__(self, msg: str, status: int = 429,
                 retry_after: float = 1.0):
        super().__init__(msg)
        self.status = int(status)
        self.retry_after = float(retry_after)


class Session:
    """One admitted streaming session (transport-agnostic state)."""

    def __init__(self, sid: str, r, *, token: int, units: int,
                 credits: int, max_block_rows: int, snaptoken: str = "",
                 latest: bool = False, max_depth: int = 0,
                 ctoken=None, transport: str = "lane"):
        self.sid = sid
        self.r = r                      # tenant-resolved registry
        self.token = token              # admission grant (released once)
        self.units = units
        self.credits = credits
        self.max_block_rows = max_block_rows
        self.snaptoken = snaptoken
        self.latest = latest
        self.max_depth = max_depth
        self.ctoken = ctoken            # consistency token from the
        self.transport = transport      # handshake barrier (snaptoken mode)
        self.created = time.monotonic()
        self.blocks = 0
        self.rows = 0
        self.closed = False
        self.inflight = 0
        self.seqs: set = set()
        # the credit window: the reader thread blocks here instead of
        # reading ahead, so TCP backpressure IS the flow control
        self._window = threading.Semaphore(credits)
        self._lock = threading.Lock()

    def drain(self, timeout: float = 60.0) -> bool:
        """Block until every in-flight block completed (acquire the whole
        credit window, then hand it back)."""
        got = 0
        deadline_t = time.monotonic() + timeout
        try:
            for _ in range(self.credits):
                left = deadline_t - time.monotonic()
                if left <= 0 or not self._window.acquire(timeout=left):
                    return False
                got += 1
            return True
        finally:
            for _ in range(got):
                self._window.release()


class SessionBroker:
    """Owns every live session for one server: handshake admission,
    block dispatch, and the `keto_session_*` vocabulary.

    Dispatch runs on a small shared pool — blocks from MANY sessions
    interleave into the coalescer's waves as first-class column groups
    (engine.check_block), which is where out-of-order completion comes
    from: a small block's wave can land while a big one is still packing.
    """

    def __init__(self, registry):
        self.r = registry
        cfg = registry.config
        self.enabled = bool(cfg.get("session.enabled", True))
        self.max_sessions = int(cfg.get("session.max_sessions", 256))
        self.credits = int(cfg.get("session.credits", 8))
        self.max_block_rows = int(cfg.get("session.max_block_rows", 4096))
        self.units = int(cfg.get("session.units", 256))
        self.idle_timeout_ms = int(cfg.get("session.idle_timeout_ms", 30000))
        workers = int(cfg.get("session.dispatch_workers", 4))
        self._sessions: Dict[str, Session] = {}
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, workers),
            thread_name_prefix="keto-session",
        )
        # lazy: handlers imports this module for the StreamCheck servicer
        from ketotpu.server.handlers import CheckHandler
        self._check = CheckHandler(registry)

    # -- lifecycle ----------------------------------------------------

    def open(self, md: Optional[dict] = None, *, units: int = 0,
             snaptoken: str = "", latest: bool = False,
             max_depth: int = 0, transport: str = "lane") -> Session:
        """Handshake: tenant-resolve, brownout gate, session cap, ONE
        admission acquire for the whole session.  Raises
        :class:`SessionRefused` with the Retry-After hint on refusal."""
        met = self.r.metrics()

        def refuse(reason: str, msg: str, status: int) -> SessionRefused:
            met.counter(
                "keto_session_refused_total", 1.0,
                help="streaming session handshakes refused",
                reason=reason, transport=transport,
            )
            return SessionRefused(
                msg, status=status, retry_after=self._retry_after())

        try:
            r = self.r.resolve(md or {})
        except Exception as e:  # noqa: BLE001 - unknown tenant etc.
            code = getattr(e, "status_code", None) or 403
            raise refuse("tenant", str(e), int(code)) from e
        with self._lock:
            live = len(self._sessions)
        if live >= self.max_sessions:
            raise refuse(
                "cap",
                f"session cap reached ({self.max_sessions}); retry later",
                507,
            )
        ctl = self.r.admission()
        if ctl is not None and ctl.enabled:
            # PR 16 brownout ladder: stage >= 2 sheds everything but
            # ESTABLISHED interactive traffic — a new session is new
            # load, so the handshake is the shed point
            if int(getattr(ctl, "stage", 0)) >= 2:
                raise refuse(
                    "brownout",
                    "brownout: new sessions refused; retry later", 503,
                )
            weight = int(units) or self.units
            token = ctl.try_acquire(weight, klass=CLASS_INTERACTIVE)
            if not token:
                raise refuse(
                    "admission",
                    f"in-flight limit reached ({ctl.limit}); "
                    f"session of {weight} units refused", 429,
                )
        else:
            weight, token = int(units) or self.units, 0
        ctoken = None
        try:
            if snaptoken:
                # at-least-as-fresh is monotonic: one barrier at the
                # handshake covers every block in the session
                ctoken = consistency.ensure_fresh(
                    r, snaptoken, False, op="stream")
        except Exception:
            if token and ctl is not None:
                ctl.release(token)
            raise
        sid = uuid.uuid4().hex[:16]
        s = Session(
            sid, r, token=token, units=weight, credits=self.credits,
            max_block_rows=self.max_block_rows, snaptoken=snaptoken,
            latest=latest, max_depth=max_depth, ctoken=ctoken,
            transport=transport,
        )
        with self._lock:
            self._sessions[sid] = s
            live = len(self._sessions)
        met.counter(
            "keto_session_open_total", 1.0,
            help="streaming sessions opened", transport=transport,
        )
        met.gauge(
            "keto_session_active", float(live),
            help="streaming sessions currently open",
        )
        return s

    def close(self, s: Session) -> None:
        """Release the session's admission grant exactly once — including
        on abrupt disconnect with blocks still in flight (the dispatch
        jobs finish against the engine; their completion callbacks just
        have nowhere to write)."""
        with self._lock:
            if self._sessions.pop(s.sid, None) is None:
                return
            live = len(self._sessions)
        s.closed = True
        if s.token:
            ctl = self.r.admission()
            if ctl is not None:
                ctl.release(s.token)
            s.token = 0
        met = self.r.metrics()
        met.gauge(
            "keto_session_active", float(live),
            help="streaming sessions currently open",
        )
        met.observe(
            "keto_session_blocks", float(s.blocks),
            help="blocks served per streaming session",
        )

    def active(self) -> int:
        with self._lock:
            return len(self._sessions)

    def shutdown(self) -> None:
        with self._lock:
            live = list(self._sessions.values())
        for s in live:
            self.close(s)
        self._pool.shutdown(wait=False)

    def _retry_after(self) -> float:
        try:
            hint = self.r.retry_after_hint()
            return max(1.0, float(hint))
        except Exception:  # noqa: BLE001 - hint is advisory
            return 1.0

    # -- block dispatch -----------------------------------------------

    def submit_cols(self, s: Session, seq: int, meta: dict, arrays: dict,
                    done: Callable) -> None:
        """Lane path: dispatch one columnar block.  Decode happens on the
        dispatch thread (off the reader), the verdict callback runs there
        too — out of order across seqs by construction."""

        def build():
            cols = {k: wire.unpack_strcol(arrays, k) for k in _STRCOLS}
            skind_arr = arrays.get("skind")
            if skind_arr is None:
                raise ValueError("block frame missing skind")
            skind = [int(v) for v in np.asarray(skind_arr).reshape(-1)]
            n = len(skind)
            for k, col in cols.items():
                if len(col) != n:
                    raise ValueError(
                        f"block column {k!r} has {len(col)} rows, "
                        f"skind has {n}")
            block = columns.ColumnBlock(
                cols["ns"], cols["obj"], cols["rel"], skind,
                cols["sa"], cols["sb"], cols["sc"],
            )
            return block, list(range(n)), n, {}

        self._submit(s, seq, build, int(meta.get("max_depth", 0)),
                     float(meta.get("deadline_ms", 0)) / 1000.0, done)

    def submit_items(self, s: Session, seq: int, items: List,
                     done: Callable, *, max_depth: int = 0) -> None:
        """gRPC path: items are RelationTuples or per-slot exceptions
        (the BatchCheck slot contract)."""

        def build():
            errors: dict = {}
            good, keep = [], []
            for i, t in enumerate(items):
                if isinstance(t, Exception):
                    code = getattr(t, "status_code", None) or 400
                    errors[i] = (str(t), int(code))
                else:
                    good.append(t)
                    keep.append(i)
            block = columns.ColumnBlock.from_tuples(good)
            return block, keep, len(items), errors

        self._submit(s, seq, build, max_depth, 0.0, done)

    def _submit(self, s: Session, seq: int, build: Callable,
                max_depth: float, deadline_s: float,
                done: Callable) -> None:
        """Acquire one credit (BLOCKS the caller — that is the
        backpressure), then run the block on the dispatch pool.  `done`
        is called exactly once with (seq, allowed, n, errors, exc)."""
        s._window.acquire()
        with s._lock:
            s.inflight += 1

        def run():
            t_start = time.perf_counter()
            try:
                with flightrec.rpc_recording(
                    s.r, "stream",
                    detail=f"session {s.sid} block seq={seq}",
                ):
                    t0 = time.perf_counter()
                    block, keep, n, errors = build()
                    flightrec.note_stage(
                        "decode", time.perf_counter() - t0)
                    flightrec.note(batch=n, seq=seq)
                    token = s.ctoken
                    if s.latest:
                        # latest mode re-arms per block: "fully fresh"
                        # must cover writes that landed mid-session
                        tb = time.perf_counter()
                        token = consistency.ensure_fresh(
                            s.r, None, True, op="stream")
                        flightrec.note_stage(
                            "barrier", time.perf_counter() - tb)
                    t1 = time.perf_counter()
                    depth = int(max_depth) or s.max_depth
                    with deadline.scope(
                        deadline_s if deadline_s > 0 else None
                    ), cache_context.request_scope(
                        s.r, {}, token=token, latest=s.latest
                    ):
                        allowed, errs = self._check._check_block_core(
                            block, keep, n, errors, depth, s.r)
                    flightrec.note_stage(
                        "compute", time.perf_counter() - t1)
                with s._lock:
                    s.blocks += 1
                    s.rows += n
                met = s.r.metrics()
                met.counter(
                    "keto_session_blocks_total", 1.0,
                    help="streaming check blocks served",
                    transport=s.transport,
                )
                met.observe(
                    "keto_session_block_rows", float(n),
                    help="rows per streaming check block",
                )
                met.observe(
                    "keto_session_block_seconds",
                    time.perf_counter() - t_start,
                    help="streaming block latency (decode to verdict)",
                )
                done(seq, allowed, n, errs, None)
            except Exception as e:  # noqa: BLE001 - block-level isolation
                done(seq, None, 0, {}, e)
            finally:
                with s._lock:
                    s.inflight -= 1
                s._window.release()

        try:
            self._pool.submit(run)
        except RuntimeError as e:
            # broker torn down while a connection thread still pumped
            # blocks: answer the block instead of killing the thread
            with s._lock:
                s.inflight -= 1
            s._window.release()
            done(seq, None, 0, {},
                 KetoAPIError(f"session broker shut down: {e}",
                              status_code=503))

    def snaptoken(self, s: Session) -> str:
        return self._check.snaptoken(s.r)


class _LaneReader:
    """Exact-read adapter over a raw socket for `wire.recv_frame`.

    A plain socket timeout poisons Python's BufferedReader ("cannot read
    from timed out object"), so idle expiry is done here with select
    ticks instead: no data for `idle_timeout` seconds AND nothing in
    flight raises socket.timeout; a session mid-compile (inflight > 0)
    just keeps waiting — the kernel still pushes back on writes."""

    _TICK = 1.0

    def __init__(self, conn: socket.socket, idle_timeout: float):
        self._conn = conn
        self._idle_timeout = idle_timeout
        self.inflight_fn: Callable[[], int] = lambda: 0

    def read(self, n: int) -> bytes:
        buf = bytearray()
        idle = 0.0
        while len(buf) < n:
            try:
                r, _, _ = select.select(
                    [self._conn], [], [], self._TICK)
            except (OSError, ValueError):
                break               # socket closed under us
            if not r:
                idle += self._TICK
                if (self._idle_timeout > 0
                        and idle >= self._idle_timeout
                        and self.inflight_fn() <= 0):
                    raise socket.timeout("session lane idle expiry")
                continue
            chunk = self._conn.recv(n - len(buf))
            if not chunk:
                break               # EOF: recv_frame maps short reads
            idle = 0.0
            buf += chunk
        return bytes(buf)

    def close(self) -> None:
        """recv_frame never closes; the lane owns the socket."""


# -- the raw TCP session lane ----------------------------------------------


class SessionLane:
    """Threaded TCP acceptor speaking wire.py frames (protocol at module
    top).  SO_REUSEPORT-capable so N front-door processes can share one
    lane port (`serve --front-doors N`)."""

    def __init__(self, broker: SessionBroker, host: str, port: int, *,
                 reuse_port: bool = False, front_door: str = ""):
        self.broker = broker
        self.host = host
        self.port = port
        self.reuse_port = reuse_port
        self.front_door = front_door    # door index label, "" standalone
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conns: set = set()
        self._lock = threading.Lock()
        self._stopping = False

    def start(self) -> None:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if self.reuse_port and hasattr(socket, "SO_REUSEPORT"):
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind((self.host, self.port))
        s.listen(128)
        self.port = s.getsockname()[1]
        self._sock = s
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="keto-session-lane", daemon=True)
        self._accept_thread.start()
        if self.front_door:
            self.broker.r.metrics().gauge(
                "keto_front_door_up", 1.0,
                help="front-door process liveness", door=self.front_door,
            )

    def stop(self) -> None:
        self._stopping = True
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    @property
    def address(self):
        return (self.host, self.port)

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="keto-session-conn", daemon=True,
            ).start()

    # -- per-connection protocol --------------------------------------

    def _serve_conn(self, conn: socket.socket) -> None:
        broker = self.broker
        if self.front_door:
            broker.r.metrics().counter(
                "keto_front_door_conns_total", 1.0,
                help="session-lane connections accepted per front door",
                door=self.front_door,
            )
        wlock = threading.Lock()

        def send(meta: dict, arrays: Optional[dict] = None) -> bool:
            with wlock:
                try:
                    wire.send_frame(conn, meta, arrays)
                    return True
                except OSError:
                    return False

        session: Optional[Session] = None
        rfile = _LaneReader(conn, broker.idle_timeout_ms / 1000.0)
        try:
            got = wire.recv_frame(
                rfile, max_meta=_LANE_MAX_META, max_bin=_LANE_MAX_BIN)
            if got is None:
                return
            hello, _arrays, _nb = got
            if hello.get("op") != "hello":
                send({"op": "error", "error": "expected hello frame",
                      "status": 400})
                return
            md = {str(k).lower(): str(v)
                  for k, v in (hello.get("metadata") or {}).items()}
            try:
                session = broker.open(
                    md,
                    units=int(hello.get("units", 0)),
                    snaptoken=str(hello.get("snaptoken", "") or ""),
                    latest=bool(hello.get("latest", False)),
                    max_depth=int(hello.get("max_depth", 0)),
                    transport="lane",
                )
            except SessionRefused as e:
                send({"op": "hello", "ok": False, "error": str(e),
                      "status": e.status, "retry_after": e.retry_after})
                return
            rfile.inflight_fn = lambda: session.inflight
            send({
                "op": "hello", "ok": True, "session": session.sid,
                "credits": session.credits,
                "max_block_rows": session.max_block_rows,
            })

            def done(seq, allowed, n, errs, exc):
                if session.closed:
                    return
                if exc is not None:
                    send({
                        "op": "error", "seq": int(seq), "error": str(exc),
                        "status": int(
                            getattr(exc, "status_code", None) or 500),
                    })
                    return
                send(
                    {
                        "op": "verdicts", "seq": int(seq), "n": int(n),
                        "errs": [
                            [int(i), str(m), int(c)]
                            for i, (m, c) in sorted(errs.items())
                        ],
                        "snaptoken": broker.snaptoken(session),
                    },
                    {"ok": np.asarray(allowed, dtype=np.uint8)},
                )

            while True:
                got = wire.recv_frame(
                    rfile, max_meta=_LANE_MAX_META, max_bin=_LANE_MAX_BIN)
                if got is None:
                    return              # client vanished mid-stream
                meta, arrays, _nb = got
                op = meta.get("op")
                if op == "ping":
                    send({"op": "pong", "session": session.sid})
                    continue
                if op == "end":
                    session.drain()
                    send({"op": "bye", "blocks": session.blocks,
                          "rows": session.rows})
                    return
                if op != "block":
                    send({"op": "error",
                          "error": f"unknown op {op!r}", "status": 400})
                    continue
                seq = int(meta.get("seq", -1))
                n = int(meta.get("n", 0))
                if seq < 0 or seq in session.seqs:
                    send({"op": "error", "seq": seq,
                          "error": "bad or duplicate seq", "status": 400})
                    continue
                if n <= 0 or n > session.max_block_rows:
                    send({"op": "error", "seq": seq,
                          "error": (f"block of {n} rows exceeds "
                                    f"max_block_rows="
                                    f"{session.max_block_rows}"),
                          "status": 400})
                    continue
                session.seqs.add(seq)
                # blocks past the credit window park HERE (submit_cols
                # acquires a credit before returning) — the lane stops
                # reading and the kernel pushes back on the client
                broker.submit_cols(session, seq, meta, arrays, done)
        except (wire.WireError, socket.timeout, ValueError):
            # desync/truncation/oversize or idle expiry: the connection
            # is unrecoverable — drop it (the client replays unacked
            # blocks on a fresh session)
            return
        except OSError:
            return
        finally:
            if session is not None:
                broker.close(session)
            try:
                rfile.close()
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                self._conns.discard(conn)
