"""Length-prefixed binary framing for the worker↔owner engine wire.

Replaces newline-delimited JSON: each frame is

    !II header (meta_len, bin_len) | meta JSON | packed binary payload

where the payload is the concatenation of zero or more numpy arrays
described by the meta's ``_arrays`` manifest (``[name, dtype, shape,
nbytes]`` per entry, in payload order).  A 4096-item check batch rides
as ONE frame carrying an ``int32 (n, 4)`` id matrix instead of 4096
JSON strings — one owner round-trip per worker batch.

Payloads at or above a size threshold can ride a **shared-memory ring**
instead of the socket: the sender parks the bytes in a
``multiprocessing.shared_memory`` segment it owns (grown as needed,
reused across calls) and the frame's meta carries ``_shm`` =
``{"name", "nbytes"}`` with ``bin_len == 0`` on the wire.  The receiver
attaches the segment once and copies the bytes out.  Strict
request/response framing makes the single segment safe: the sender
never writes the next payload before it has read the response to the
previous one.  The socket remains the control channel either way, so a
lost peer degrades to ordinary connection errors.

The unix socket is a trusted same-host channel; frames carry JSON +
raw little-endian arrays, never pickle.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, Optional, Tuple

import numpy as np

HEADER = struct.Struct("!II")

#: refuse absurd frames outright — a desynced stream otherwise turns a
#: garbage length prefix into a multi-gigabyte allocation
MAX_META = 64 * 1024 * 1024
MAX_BIN = 1024 * 1024 * 1024


class WireError(ValueError):
    """Framing violation: the stream is desynced or the peer is not
    speaking this protocol.  Callers treat it like a transport error
    (discard the connection)."""


def pack_arrays(arrays: Optional[Dict[str, np.ndarray]]):
    """(manifest, payload bytes) for the meta's ``_arrays`` key."""
    if not arrays:
        return None, b""
    manifest = []
    chunks = []
    for name, arr in arrays.items():
        a = np.ascontiguousarray(arr)
        raw = a.tobytes()
        manifest.append([name, str(a.dtype), list(a.shape), len(raw)])
        chunks.append(raw)
    return manifest, b"".join(chunks)


def unpack_arrays(manifest, payload: bytes) -> Dict[str, np.ndarray]:
    arrays: Dict[str, np.ndarray] = {}
    off = 0
    for name, dtype, shape, nbytes in manifest:
        if off + nbytes > len(payload):
            raise WireError("array manifest overruns the frame payload")
        arrays[name] = np.frombuffer(
            payload, dtype=np.dtype(dtype), count=-1 if not shape else int(
                np.prod(shape, dtype=np.int64)
            ), offset=off,
        ).reshape(shape)
        off += nbytes
    return arrays


def pack_strcol(arrays: Dict[str, np.ndarray], name: str, col) -> None:
    """Pack a string column into ``arrays`` as two entries: ``<name>_b``
    (one utf-8 blob) + ``<name>_o`` (int32 end offsets, len n+1).  A
    4096-item column crosses the wire as two contiguous buffers instead
    of 4096 JSON strings — the columnar check op's carrier."""
    enc = [s.encode("utf-8") for s in col]
    offs = np.zeros(len(enc) + 1, dtype=np.int32)
    if enc:
        offs[1:] = np.cumsum([len(b) for b in enc])
    arrays[name + "_b"] = np.frombuffer(b"".join(enc), dtype=np.uint8)
    arrays[name + "_o"] = offs


def unpack_strcol(arrays: Dict[str, np.ndarray], name: str) -> list:
    """Inverse of :func:`pack_strcol`; raises WireError on a malformed
    offsets/blob pair (desynced or hostile peer)."""
    blob = arrays.get(name + "_b")
    offs = arrays.get(name + "_o")
    if blob is None or offs is None or offs.ndim != 1 or len(offs) < 1:
        raise WireError(f"string column {name!r} missing or misshapen")
    raw = blob.tobytes()
    offs = offs.astype(np.int64)
    if offs[0] != 0 or offs[-1] != len(raw) or np.any(np.diff(offs) < 0):
        raise WireError(f"string column {name!r} offsets are inconsistent")
    return [
        raw[offs[i]:offs[i + 1]].decode("utf-8")
        for i in range(len(offs) - 1)
    ]


def pack_tuplecols(
    arrays: Dict[str, np.ndarray], prefix: str, rows
) -> None:
    """Pack relation tuples as four string columns (``<prefix>_ns/obj/rel``
    plus the subject in its canonical string form) — the replication
    bootstrap/tail carrier.  A 10M-row bootstrap crosses the wire as eight
    contiguous buffers (and takes the shared-memory hop past the
    threshold) instead of 10M JSON strings."""
    pack_strcol(arrays, f"{prefix}_ns", [t.namespace for t in rows])
    pack_strcol(arrays, f"{prefix}_obj", [t.object for t in rows])
    pack_strcol(arrays, f"{prefix}_rel", [t.relation for t in rows])
    pack_strcol(arrays, f"{prefix}_subj", [str(t.subject) for t in rows])


def unpack_tuplecols(arrays: Dict[str, np.ndarray], prefix: str) -> list:
    """Inverse of :func:`pack_tuplecols`: a list of RelationTuple."""
    from ketotpu.api.types import RelationTuple, subject_from_string

    ns = unpack_strcol(arrays, f"{prefix}_ns")
    obj = unpack_strcol(arrays, f"{prefix}_obj")
    rel = unpack_strcol(arrays, f"{prefix}_rel")
    subj = unpack_strcol(arrays, f"{prefix}_subj")
    if not (len(ns) == len(obj) == len(rel) == len(subj)):
        raise WireError(f"tuple columns {prefix!r} have mismatched lengths")
    return [
        RelationTuple(
            namespace=n, object=o, relation=r,
            subject=subject_from_string(s),
        )
        for n, o, r, s in zip(ns, obj, rel, subj)
    ]


def pack_changes(
    arrays: Dict[str, np.ndarray], prefix: str, entries
) -> None:
    """Pack changelog entries ``[(op, tuple)]`` (op = +1 insert / -1
    delete) as the tuple columns plus an int8 op column."""
    arrays[f"{prefix}_op"] = np.array(
        [op for op, _ in entries], dtype=np.int8
    )
    pack_tuplecols(arrays, prefix, [t for _, t in entries])


def unpack_changes(arrays: Dict[str, np.ndarray], prefix: str) -> list:
    """Inverse of :func:`pack_changes`."""
    ops = arrays.get(f"{prefix}_op")
    if ops is None or ops.ndim != 1:
        raise WireError(f"change column {prefix!r}_op missing or misshapen")
    tuples = unpack_tuplecols(arrays, prefix)
    if len(ops) != len(tuples):
        raise WireError(f"change columns {prefix!r} have mismatched lengths")
    return [(int(op), t) for op, t in zip(ops, tuples)]


class ShmRing:
    """Sender-owned shared-memory segment for large frame payloads,
    reused (and grown) across calls; unlinked on close."""

    def __init__(self):
        self._seg = None

    def place(self, payload: bytes) -> dict:
        from multiprocessing import shared_memory

        n = len(payload)
        if self._seg is None or self._seg.size < n:
            if self._seg is not None:
                self._close_seg(unlink=True)
            # grow in powers of two: reuse beats precise sizing
            size = 1 << max(12, (n - 1).bit_length())
            self._seg = shared_memory.SharedMemory(create=True, size=size)
        self._seg.buf[:n] = payload
        return {"name": self._seg.name, "nbytes": n}

    def _close_seg(self, unlink: bool) -> None:
        seg, self._seg = self._seg, None
        if seg is None:
            return
        try:
            seg.close()
            if unlink:
                seg.unlink()
        except (OSError, FileNotFoundError):
            pass

    def close(self) -> None:
        self._close_seg(unlink=True)


class ShmCache:
    """Receiver-side attachment cache: one attach per segment name."""

    def __init__(self):
        self._segs: dict = {}

    def read(self, desc: dict) -> bytes:
        from multiprocessing import shared_memory

        name, n = desc["name"], int(desc["nbytes"])
        seg = self._segs.get(name)
        if seg is None:
            seg = shared_memory.SharedMemory(name=name)
            # the SENDER owns the segment's lifetime; keep this process's
            # resource tracker from unlinking it on exit (3.12 tracks
            # attachments too — the known premature-unlink footgun)
            try:
                from multiprocessing import resource_tracker

                resource_tracker.unregister(seg._name, "shared_memory")
            except Exception:  # noqa: BLE001 - tracker quirks vary
                pass
            self._segs[name] = seg
        if n > seg.size:
            raise WireError("shm descriptor exceeds segment size")
        return bytes(seg.buf[:n])

    def close(self) -> None:
        segs, self._segs = self._segs, {}
        for seg in segs.values():
            try:
                seg.close()
            except (OSError, BufferError):
                pass


def send_frame(
    sock, meta: dict,
    arrays: Optional[Dict[str, np.ndarray]] = None, *,
    ring: Optional[ShmRing] = None, shm_threshold: int = 0,
) -> int:
    """Write one frame; returns bytes that crossed the SOCKET (shm
    payload bytes intentionally excluded — that is the point)."""
    manifest, payload = pack_arrays(arrays)
    if manifest is not None:
        meta = dict(meta, _arrays=manifest)
    if (ring is not None and shm_threshold > 0
            and len(payload) >= shm_threshold):
        meta = dict(meta, _shm=ring.place(payload))
        payload = b""
    raw_meta = json.dumps(meta).encode("utf-8")
    frame = HEADER.pack(len(raw_meta), len(payload)) + raw_meta + payload
    sock.sendall(frame)
    return len(frame)


def recv_frame(
    rfile, *, shm_cache: Optional[ShmCache] = None,
    max_meta: int = 0, max_bin: int = 0,
) -> Optional[Tuple[dict, Dict[str, np.ndarray], int]]:
    """Read one frame from a buffered file object; None on clean EOF.
    Returns (meta, arrays, socket_bytes_read).

    ``max_meta``/``max_bin`` tighten the global caps per channel: the
    cross-host PeerLink lane faces untrusted networks and refuses frames
    a same-host worker wire would still accept (0 keeps the defaults).
    """
    head = rfile.read(HEADER.size)
    if not head:
        return None
    if len(head) < HEADER.size:
        raise WireError("truncated frame header")
    meta_len, bin_len = HEADER.unpack(head)
    meta_cap = min(MAX_META, max_meta) if max_meta > 0 else MAX_META
    bin_cap = min(MAX_BIN, max_bin) if max_bin > 0 else MAX_BIN
    if meta_len > meta_cap or bin_len > bin_cap:
        raise WireError(
            f"frame sizes out of range (meta={meta_len}, bin={bin_len})"
        )
    raw_meta = rfile.read(meta_len)
    if len(raw_meta) < meta_len:
        raise WireError("truncated frame meta")
    try:
        meta = json.loads(raw_meta)
    except ValueError as e:
        raise WireError(f"frame meta is not JSON: {e}") from None
    payload = b""
    if bin_len:
        payload = rfile.read(bin_len)
        if len(payload) < bin_len:
            raise WireError("truncated frame payload")
    shm_desc = meta.pop("_shm", None)
    if shm_desc is not None:
        if shm_cache is None:
            raise WireError("unexpected shm frame on this channel")
        payload = shm_cache.read(shm_desc)
    manifest = meta.pop("_arrays", None)
    arrays = unpack_arrays(manifest, payload) if manifest else {}
    return meta, arrays, HEADER.size + meta_len + bin_len
