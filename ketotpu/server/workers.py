"""Multi-process serving: SO_REUSEPORT workers around one device owner.

The single-process daemon tops out on the Python wire stack (proto +
HTTP + GIL) long before the engine does — round 3 measured ~74 RPS
through the daemon against ~19k checks/s on-device.  The reference
scales by running on multi-core Go; the Python analog is processes:

* **one device owner** holds the real `DeviceCheckEngine` (a JAX device
  belongs to one process) and serves batched check/expand over a unix
  domain socket (`EngineHostServer`);
* **N workers** each run the full gRPC/REST daemon on the SAME public
  ports via ``SO_REUSEPORT`` (the kernel load-balances accepted
  connections) with a `RemoteCheckEngine` that forwards batches to the
  owner.  The owner's coalescer merges concurrent single checks from
  ALL workers into shared device waves, so cross-process fan-in feeds
  bigger (faster) batches, not contention.

Workers and owner share one durable store DSN (sqlite file / postgres);
writes land in the store from any worker and reach the device through
the owner's ordinary change-log drain.  A ``memory`` DSN cannot be
shared across processes and is refused.

Wire protocol: newline-delimited JSON over the unix socket — tuples in
their canonical string form (`RelationTuple.from_string` round-trips),
typed errors re-raised client-side by status code.  The socket is a
trusted same-host channel (mode 0700 directory recommended); no pickle.
"""

from __future__ import annotations

import json
import os
import random
import socket
import socketserver
import subprocess
import threading
import time
from typing import Callable, List, Optional, Sequence

from ketotpu import deadline, faults, flightrec
from ketotpu.cache import SingleFlight
from ketotpu.cache import check_key as cache_check_key
from ketotpu.cache import context as cache_context
from ketotpu.api.types import (
    DeadlineExceededError,
    KetoAPIError,
    RelationTuple,
    Subject,
    SubjectID,
    SubjectSet,
    Tree,
)


def _encode_subject(s: Subject) -> str:
    return s.unique_id()


def _decode_subject(u: str) -> Subject:
    if u.startswith("set:"):
        return SubjectSet.from_string(u[4:])
    return SubjectID(u[3:] if u.startswith("id:") else u)


class EngineHostServer:
    """The device owner's unix-socket engine service."""

    def __init__(self, registry, path: str,
                 health_fn: Optional[Callable[[], dict]] = None):
        self.registry = registry
        self.path = path
        self.health_fn = health_fn
        if os.path.exists(path):
            os.unlink(path)

        host = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    try:
                        faults.inject("owner_handler")
                        req = json.loads(line)
                        resp = host._serve_one(req)
                    except Exception as e:  # noqa: BLE001
                        resp = {"error": {
                            "msg": str(e),
                            "status": getattr(e, "status_code", 500),
                        }}
                    self.wfile.write(json.dumps(resp).encode() + b"\n")
                    self.wfile.flush()

        class Srv(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True
            allow_reuse_address = True

        self._srv = Srv(path, Handler)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True,
            name="engine-host",
        )

    def start(self) -> "EngineHostServer":
        self._thread.start()
        return self

    def is_alive(self) -> bool:
        return self._thread.is_alive()

    def restart(self) -> "EngineHostServer":
        """Replace a dead host with a fresh one on the same socket path.

        The supervisor calls this when the serving thread died; pooled
        worker connections to the old socket fail and reconnect through
        their backoff path."""
        try:
            self._srv.server_close()
        except OSError:
            pass
        fresh = EngineHostServer(self.registry, self.path, self.health_fn)
        return fresh.start()

    def _serve_one(self, req):
        op = req.get("op")
        # workers forward their RPC's traceparent so the owner-side spans
        # (coalescer wave, device dispatch) stitch into the same trace
        tp = req.pop("traceparent", None)
        # workers forward the remaining budget; bind it so the coalescer
        # slot wait and oracle-fallback loop on the owner side stay inside
        # what the worker's client granted
        ms = req.pop("deadline_ms", None)
        # a worker serving X-Keto-Cache: bypass forwards the flag so the
        # owner-side probe/insert (engine pre-dispatch, coalescer) see the
        # bypass too — the escape hatch must hold across the process hop
        bypass = bool(req.pop("cache_bypass", False))
        with deadline.scope(None if ms is None else ms / 1000.0):
            if bypass:
                with cache_context.scope(bypass=True):
                    return self._serve_op(req, op, tp)
            return self._serve_op(req, op, tp)

    def _serve_op(self, req, op, tp):
        r = self.registry
        if op == "check":
            with flightrec.rpc_recording(
                r, "check", traceparent=tp, detail="worker->owner check"
            ):
                t0 = time.perf_counter()
                tuples = [RelationTuple.from_string(s) for s in req["tuples"]]
                flightrec.note_stage("parse", time.perf_counter() - t0)
                eng = r.check_engine()
                depth = int(req.get("depth", 0))
                # cursor piggyback for the workers' local caches: the store
                # head read BEFORE the compute is a lower bound on the state
                # every verdict in this response is computed from — the
                # engine's dispatch drains the changelog to at least this
                # position (oracle engines read the live store outright).
                # Workers stamp their cache entries with it and advance
                # their staleness fence.
                cur = r.store().log_head
                if len(tuples) == 1:
                    # single-check RPCs from the workers MUST go through
                    # check_is_member: that is the coalescer's enqueue point,
                    # so concurrent singles from every worker merge into one
                    # shared device wave.  batch_check passes straight
                    # through the coalescer (it is already batched) — routing
                    # singles there made each RPC its own device dispatch.
                    ok = [bool(eng.check_is_member(tuples[0], depth))]
                    flightrec.note(verdict=ok[0])
                    return {"ok": ok, "cursor": int(cur)}
                batch = getattr(eng, "batch_check", None)
                if batch is not None:
                    ok = batch(tuples, depth)
                else:  # oracle engine: sequential surface only
                    ok = [eng.check_is_member(t, depth) for t in tuples]
                return {"ok": [bool(v) for v in ok], "cursor": int(cur)}
        if op == "expand":
            with flightrec.rpc_recording(
                r, "expand", traceparent=tp, detail="worker->owner expand"
            ):
                subject = _decode_subject(req["subject"])
                tree = r.expand_engine().build_tree(
                    subject, int(req.get("depth", 0))
                )
                return {"tree": tree.to_json() if tree is not None else None}
        if op == "list_objects":
            with flightrec.rpc_recording(
                r, "list_objects", traceparent=tp,
                detail="worker->owner list_objects",
            ):
                objs, next_token = r.list_engine().list_objects(
                    req["namespace"], req["relation"],
                    _decode_subject(req["subject"]),
                    page_size=int(req.get("page_size", 0)),
                    page_token=req.get("page_token", ""),
                )
                return {"objects": list(objs), "next_page_token": next_token}
        if op == "list_subjects":
            with flightrec.rpc_recording(
                r, "list_subjects", traceparent=tp,
                detail="worker->owner list_subjects",
            ):
                subs, next_token = r.list_engine().list_subjects(
                    req["namespace"], req["object"], req["relation"],
                    page_size=int(req.get("page_size", 0)),
                    page_token=req.get("page_token", ""),
                )
                return {
                    "subjects": [_encode_subject(s) for s in subs],
                    "next_page_token": next_token,
                }
        if op == "barrier":
            # freshness barrier forwarded from a worker: the worker can
            # see the shared store but not the device engine, so the
            # owner runs ensure_fresh (token + mode as wire fields); a
            # StaleSnapshotError (412) rides the ordinary wire-error
            # path and re-raises typed on the worker side
            from ketotpu import consistency

            with flightrec.rpc_recording(
                r, "barrier", traceparent=tp, detail="worker->owner barrier"
            ):
                t0 = time.perf_counter()
                consistency.ensure_fresh(
                    r,
                    req.get("snaptoken") or None,
                    bool(req.get("latest")),
                    op=str(req.get("rpc") or "check"),
                )
                flightrec.note_stage("barrier", time.perf_counter() - t0)
                return {"ok": True}
        if op == "ping":
            return {"pong": True}
        if op == "health":
            # owner-side readiness for the workers' health surface: the
            # worker cannot see the device engine directly, so degraded
            # state (CPU fallback, respawning workers) flows over the wire
            fn = self.health_fn
            return {"health": dict(fn()) if fn is not None else {}}
        raise ValueError(f"unknown op {op!r}")

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        try:
            os.unlink(self.path)
        except OSError:
            pass


class _Conn:
    def __init__(self, path: str):
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.connect(path)
        self.rfile = self.sock.makefile("rb")
        self.lock = threading.Lock()
        self.broken = False

    def close(self) -> None:
        self.broken = True
        try:
            self.sock.close()
        except OSError:
            pass

    def call(self, req, timeout: Optional[float] = None) -> dict:
        """One request/response on this connection.

        Any transport error — timeout, EOF, decode failure — marks the
        connection broken and closes it: the wire is strictly one
        response per request, so after a partial exchange the NEXT call
        on this socket would read THIS request's late response (the
        desync bug).  Only a decoded typed error keeps the connection —
        the exchange completed, the stream is still aligned.
        """
        if self.broken:
            raise ConnectionError("connection already discarded")
        try:
            with self.lock:
                self.sock.settimeout(timeout)
                self.sock.sendall(json.dumps(req).encode() + b"\n")
                line = self.rfile.readline()
            if not line:
                raise ConnectionError("engine host closed the connection")
            resp = json.loads(line)
        except Exception:
            self.close()
            raise
        if "error" in resp:
            err = KetoAPIError(resp["error"]["msg"])
            err.status_code = resp["error"].get("status", 500)
            raise err
        return resp


class RemoteCheckEngine:
    """check.Engine surface forwarding to the device owner's socket.

    A tiny per-thread connection pool: each serving thread keeps its own
    connection (requests on one connection are serialized), so worker
    concurrency maps 1:1 onto owner-side handler threads — which is
    exactly what feeds the owner's coalescer bigger waves.

    Connection errors retry on a fresh connection with capped exponential
    backoff + jitter (the owner may be mid-respawn); a TIMEOUT does not
    retry — the budget is spent and the caller gets DEADLINE_EXCEEDED."""

    #: reconnect schedule: base*2^n jittered, capped — tuned so a worker
    #: rides out an owner respawn without stampeding the fresh socket
    retry_attempts = 5
    backoff_base = 0.025
    backoff_cap = 0.25

    def __init__(self, path: str, *, rpc_timeout: float = 30.0,
                 cache=None, metrics=None):
        self.path = path
        # budget for calls with no request deadline: a wedged owner must
        # surface as an error, not hang every worker thread (<=0 disables)
        self.rpc_timeout = rpc_timeout
        # hot-spot shield, worker side: this process's own ResultCache over
        # the shared store — a hot key answered here never crosses the
        # socket at all.  Verdicts coming back from the owner are stamped
        # with the owner's piggybacked changelog cursor, and that cursor
        # also advances the local staleness fence (the owner broadcasting
        # its drain position to every worker that talks to it).
        self.cache = cache
        self._flight = SingleFlight(metrics=metrics)
        self.reconnects = 0  # observability: retried transport failures
        self._local = threading.local()

    def _conn(self) -> _Conn:
        c = getattr(self._local, "conn", None)
        if c is None or c.broken:
            c = self._local.conn = _Conn(self.path)
        return c

    def _discard(self) -> None:
        c = getattr(self._local, "conn", None)
        if c is not None:
            c.close()
        self._local.conn = None

    def _call(self, req) -> dict:
        tp = flightrec.current_traceparent()
        if tp:
            req = dict(req, traceparent=tp)
        budget = deadline.remaining()
        if budget is not None:
            if budget <= 0:
                raise DeadlineExceededError(
                    "deadline exceeded before owner RPC"
                )
            # forward the remaining budget so the owner bounds ITS waits
            req = dict(req, deadline_ms=deadline.deadline_ms())
        timeout = budget
        if timeout is None and self.rpc_timeout > 0:
            timeout = self.rpc_timeout
        t0 = time.perf_counter()
        try:
            last: Optional[BaseException] = None
            for attempt in range(self.retry_attempts):
                try:
                    if faults.should("socket_drop"):
                        self._discard()
                        raise ConnectionError("injected owner-socket drop")
                    return self._conn().call(req, timeout=timeout)
                except KetoAPIError:
                    raise
                except TimeoutError:
                    # budget spent waiting on the owner: retrying cannot
                    # beat the deadline, answer DEADLINE_EXCEEDED now
                    self._discard()
                    raise DeadlineExceededError(
                        f"owner RPC exceeded {timeout:.3f}s"
                    ) from None
                except (ConnectionError, OSError, ValueError) as e:
                    # ValueError covers a JSON decode failure: the stream
                    # desynced, the connection is already discarded
                    last = e
                    self._discard()
                    if attempt + 1 >= self.retry_attempts:
                        break
                    self.reconnects += 1
                    delay = min(
                        self.backoff_cap, self.backoff_base * (2 ** attempt)
                    )
                    delay *= 0.5 + random.random() * 0.5  # decorrelate
                    left = deadline.remaining()
                    if left is not None:
                        if left <= 0:
                            raise DeadlineExceededError(
                                "deadline exceeded during owner reconnect"
                            ) from e
                        delay = min(delay, left)
                    time.sleep(delay)
            raise ConnectionError(
                f"owner RPC failed after {self.retry_attempts} attempts: {last}"
            ) from last
        finally:
            flightrec.note_stage("worker_rpc", time.perf_counter() - t0)

    def batch_check(
        self, queries: Sequence[RelationTuple], rest_depth: int = 0
    ) -> List[bool]:
        if not queries:
            return []
        bypass = cache_context.bypassed()
        cache = None if bypass else self.cache
        results: List[Optional[bool]] = [None] * len(queries)
        miss = list(range(len(queries)))
        if cache is not None:
            hits = cache.lookup_many(
                [cache_check_key(q, rest_depth) for q in queries]
            )
            miss = [i for i, h in enumerate(hits) if h is None]
            for i, h in enumerate(hits):
                if h is not None:
                    results[i] = bool(h.value)
            if not miss:
                return [bool(v) for v in results]
        req = {
            "op": "check",
            "tuples": [str(queries[i]) for i in miss],
            "depth": rest_depth,
        }
        if bypass:
            req["cache_bypass"] = True
        resp = self._call(req)
        cur = resp.get("cursor")
        if cache is not None and cur is not None:
            cache.advance_fence(int(cur))
            for i, v in zip(miss, resp["ok"]):
                cache.insert(
                    cache_check_key(queries[i], rest_depth), bool(v), int(cur)
                )
        for i, v in zip(miss, resp["ok"]):
            results[i] = bool(v)
        return [bool(v) for v in results]

    def check(self, r: RelationTuple, rest_depth: int = 0) -> bool:
        return self.batch_check([r], rest_depth)[0]

    def check_is_member(self, r: RelationTuple, rest_depth: int = 0) -> bool:
        if cache_context.bypassed():
            return self.check(r, rest_depth)
        # worker-side singleflight: a thundering herd on one hot key in
        # THIS process collapses to one owner RPC; followers park
        # deadline-aware and share the leader's verdict (the leader's
        # batch_check also lands it in the local cache for the next wave)
        value, _led = self._flight.do(
            (str(r), int(rest_depth)),
            lambda: self.check(r, rest_depth),
            default_timeout=self.rpc_timeout if self.rpc_timeout > 0 else None,
        )
        return bool(value)

    def consistency_barrier(
        self, snaptoken: Optional[str] = None, latest: bool = False,
        op: str = "check",
    ) -> None:
        """Run the freshness barrier on the device owner
        (ketotpu/consistency/barrier.py routes here when the engine is
        remote).  Raises the owner's typed refusal — StaleSnapshotError
        412 — through the wire-error path."""
        req = {"op": "barrier", "rpc": op}
        if snaptoken:
            req["snaptoken"] = snaptoken
        if latest:
            req["latest"] = True
        self._call(req)


class RemoteExpandEngine:
    """expand.Engine surface forwarding to the device owner."""

    def __init__(self, path: str, check: Optional[RemoteCheckEngine] = None):
        self._remote = check if check is not None else RemoteCheckEngine(path)

    def build_tree(self, subject: Subject, max_depth: int = 0) -> Optional[Tree]:
        resp = self._remote._call({
            "op": "expand",
            "subject": _encode_subject(subject),
            "depth": max_depth,
        })
        if resp["tree"] is None:
            return None
        return Tree.from_json(resp["tree"])


class RemoteListEngine:
    """Listing-engine surface forwarding to the device owner (the Leopard
    closure index lives with the device; workers only relay)."""

    def __init__(self, path: str, check: Optional[RemoteCheckEngine] = None):
        self._remote = check if check is not None else RemoteCheckEngine(path)

    def list_objects(
        self, namespace: str, relation: str, subject: Subject,
        *, page_size: int = 0, page_token: str = "",
    ):
        resp = self._remote._call({
            "op": "list_objects",
            "namespace": namespace,
            "relation": relation,
            "subject": _encode_subject(subject),
            "page_size": page_size,
            "page_token": page_token,
        })
        return list(resp["objects"]), resp.get("next_page_token", "")

    def list_subjects(
        self, namespace: str, object: str, relation: str,
        *, page_size: int = 0, page_token: str = "",
    ):
        resp = self._remote._call({
            "op": "list_subjects",
            "namespace": namespace,
            "object": object,
            "relation": relation,
            "page_size": page_size,
            "page_token": page_token,
        })
        subs = [_decode_subject(u) for u in resp["subjects"]]
        return subs, resp.get("next_page_token", "")


def engine_host_readiness(path: str, timeout: float = 1.0):
    """Readiness-check factory for worker registries: probe the owner.

    Unreachable owner -> raise (the worker cannot serve checks at all);
    reachable owner with degraded health values -> return the degraded
    string so the worker's health surface mirrors the owner's.
    """

    def probe():
        conn = _Conn(path)
        try:
            resp = conn.call({"op": "health"}, timeout=timeout)
        finally:
            conn.close()
        health = resp.get("health", {})
        bad = {k: v for k, v in health.items() if v != "ok"}
        if not bad:
            return "ok"
        if all(str(v).startswith("degraded") for v in bad.values()):
            return "degraded: owner " + "; ".join(
                f"{k}={v}" for k, v in sorted(bad.items())
            )
        raise ConnectionError(
            "owner unhealthy: " + "; ".join(
                f"{k}={v}" for k, v in sorted(bad.items())
            )
        )

    return probe


class WorkerSupervisor:
    """Respawn dead serve processes with capped backoff + jitter.

    ``serve --workers`` hands this every worker subprocess (and polls the
    owner's engine-host thread itself).  A dead worker is respawned after
    a jittered backoff that grows with its recent death count; while any
    respawn is pending the supervisor's ``state()`` reports ``degraded``
    (surfaced through health + ``status --block``).  A worker that keeps
    dying — ``max_rapid_deaths`` exits inside ``rapid_window`` seconds —
    makes the supervisor give up (``poll`` returns an exit code) instead
    of flapping forever: at that point the failure is systemic, not
    transient.
    """

    def __init__(
        self,
        spawn: Callable[[int], "subprocess.Popen"],
        count: int,
        *,
        max_rapid_deaths: int = 5,
        rapid_window: float = 30.0,
        backoff_base: float = 0.5,
        backoff_cap: float = 5.0,
        log: Optional[Callable[[str], None]] = None,
    ):
        self._spawn = spawn
        self.count = count
        self.max_rapid_deaths = max_rapid_deaths
        self.rapid_window = rapid_window
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self._log = log or (lambda msg: None)
        self.procs: List[Optional["subprocess.Popen"]] = [None] * count
        self.respawns = 0  # observability: successful respawn count
        self._deaths: List[float] = []  # monotonic stamps, pruned to window
        self._death_counts = [0] * count
        self._respawn_at: List[Optional[float]] = [None] * count

    def start(self) -> "WorkerSupervisor":
        for i in range(self.count):
            self.procs[i] = self._spawn(i)
        return self

    def _record_death(self, i: int, rc) -> Optional[int]:
        now = time.monotonic()
        self._deaths.append(now)
        self._deaths = [t for t in self._deaths if now - t < self.rapid_window]
        self._death_counts[i] += 1
        if len(self._deaths) >= self.max_rapid_deaths:
            self._log(
                f"worker {i} exited rc={rc}; {len(self._deaths)} deaths in "
                f"{self.rapid_window:.0f}s — giving up"
            )
            return 1
        delay = min(
            self.backoff_cap,
            self.backoff_base * (2 ** (self._death_counts[i] - 1)),
        )
        delay *= 0.5 + random.random() * 0.5
        self._respawn_at[i] = now + delay
        self._log(
            f"worker {i} exited rc={rc}; respawning in {delay:.1f}s"
        )
        return None

    def poll(self) -> Optional[int]:
        """One supervision step. Returns an exit code to give up with,
        or None to keep serving."""
        now = time.monotonic()
        for i, p in enumerate(self.procs):
            if p is not None and p.poll() is not None:
                rc = self._record_death(i, p.returncode)
                if rc is not None:
                    return rc
                self.procs[i] = None
            if self.procs[i] is None and self._respawn_at[i] is not None:
                if now >= self._respawn_at[i]:
                    self._respawn_at[i] = None
                    self.procs[i] = self._spawn(i)
                    self.respawns += 1
                    self._log(f"worker {i} respawned")
        return None

    def state(self) -> str:
        """Health-check value: 'ok', or 'degraded: ...' while respawning."""
        down = [
            i for i, p in enumerate(self.procs)
            if p is None or p.poll() is not None
        ]
        if not down:
            return "ok"
        return "degraded: respawning worker(s) " + ",".join(map(str, down))

    def terminate(self) -> None:
        for p in self.procs:
            if p is not None and p.poll() is None:
                p.terminate()
        for p in self.procs:
            if p is not None:
                try:
                    p.wait(timeout=5)
                except Exception:  # noqa: BLE001
                    p.kill()
