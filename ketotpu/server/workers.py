"""Multi-process serving: SO_REUSEPORT workers around one device owner.

The single-process daemon tops out on the Python wire stack (proto +
HTTP + GIL) long before the engine does — round 3 measured ~74 RPS
through the daemon against ~19k checks/s on-device.  The reference
scales by running on multi-core Go; the Python analog is processes:

* **one device owner** holds the real `DeviceCheckEngine` (a JAX device
  belongs to one process) and serves batched check/expand over a unix
  domain socket (`EngineHostServer`);
* **N workers** each run the full gRPC/REST daemon on the SAME public
  ports via ``SO_REUSEPORT`` (the kernel load-balances accepted
  connections) with a `RemoteCheckEngine` that forwards batches to the
  owner.  The owner's coalescer merges concurrent single checks from
  ALL workers into shared device waves, so cross-process fan-in feeds
  bigger (faster) batches, not contention.

Workers and owner share one durable store DSN (sqlite file / postgres);
writes land in the store from any worker and reach the device through
the owner's ordinary change-log drain.  A ``memory`` DSN cannot be
shared across processes and is refused.

Wire protocol (server/wire.py): length-prefixed binary frames — a JSON
meta section plus packed numpy arrays, with an optional shared-memory
hop for large payloads.  A worker pre-encodes tuples it has seen before
as ``int32 (n, 4)`` id rows against a MIRROR of the owner's vocabulary
(learned from responses, invalidated by a vocab epoch counter when the
owner's engine swaps vocabularies on snapshot resume); unseen tuples
ride as canonical strings and come back with their id rows so the next
batch sends ids.  One owner round-trip per worker batch, whatever the
batch size.  Typed errors re-raise client-side by status code.  The
socket is a trusted same-host channel (mode 0700 directory
recommended); no pickle.
"""

from __future__ import annotations

import itertools
import os
import random
import socket
import socketserver
import subprocess
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ketotpu import deadline, faults, flightrec
from ketotpu.cache import SingleFlight
from ketotpu.cache import check_key as cache_check_key
from ketotpu.cache import context as cache_context
from ketotpu.engine import columns as colmod
from ketotpu.server import wire
from ketotpu.api.types import (
    DeadlineExceededError,
    KetoAPIError,
    RelationTuple,
    Subject,
    SubjectID,
    SubjectSet,
    Tree,
)

#: a worker's vocab mirror is bounded; on overflow it simply resets and
#: relearns (the owner remains the source of truth either way)
_MIRROR_CAP = 262144


def _encode_subject(s: Subject) -> str:
    return s.unique_id()


def _decode_subject(u: str) -> Subject:
    if u.startswith("set:"):
        return SubjectSet.from_string(u[4:])
    return SubjectID(u[3:] if u.startswith("id:") else u)


class _Reverse:
    """Incremental id -> string view over an append-only Interner.

    ``Interner.strings()`` copies the whole table; at 10M subjects that
    is milliseconds per call.  Insertion order is id order, so the view
    only ever EXTENDS from the interner's dict."""

    def __init__(self, interner):
        self._interner = interner
        self._rev: List[str] = []

    def get(self, i: int) -> Optional[str]:
        if i < 0:
            return None
        if i >= len(self._rev):
            ids = self._interner._ids
            if len(ids) > len(self._rev):
                try:
                    self._rev.extend(
                        itertools.islice(ids.keys(), len(self._rev), None)
                    )
                except RuntimeError:
                    # the engine thread interned mid-iteration; fall back
                    # to a consistent full copy
                    self._rev = self._interner.strings()
        if i >= len(self._rev):
            return None
        return self._rev[i]


class EngineHostServer:
    """The device owner's unix-socket engine service."""

    def __init__(self, registry, path: str,
                 health_fn: Optional[Callable[[], dict]] = None):
        self.registry = registry
        self.path = path
        self.health_fn = health_fn
        self._shm_threshold = int(
            registry.config.get("engine.wire_shm_threshold", 262144)
        )
        # vocab epoch: bumped whenever the device engine swaps vocabulary
        # objects (snapshot resume, store-vocab adoption) so worker id
        # mirrors learned against the old id space get invalidated
        self._vocab_obj = None
        self._vepoch = 0
        self._rev: Optional[dict] = None
        # live accepted connections: stop() severs them so an attached
        # standby observes the owner's death exactly as a kill -9 would
        # (shutdown() alone only stops the accept loop)
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        if os.path.exists(path):
            os.unlink(path)

        host = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                with host._conns_lock:
                    host._conns.add(self.connection)
                ring = wire.ShmRing()
                shm_cache = wire.ShmCache()
                try:
                    while True:
                        try:
                            got = wire.recv_frame(
                                self.rfile, shm_cache=shm_cache
                            )
                        except wire.WireError:
                            break  # desynced peer: drop the connection
                        if got is None:
                            break
                        meta, arrays, nread = got
                        host._wire_count("rx", nread)
                        if faults.should("worker_error"):
                            # chaos: the owner wedges mid-exchange — the
                            # request dies with NO response frame, so the
                            # worker sees a transport failure (the lane
                            # fault the worker-wire breaker trips on, as
                            # opposed to owner_handler's typed error
                            # frame riding back on a healthy wire)
                            break
                        try:
                            faults.inject("owner_handler")
                            resp, resp_arrays = host._serve_frame(
                                meta, arrays
                            )
                        except Exception as e:  # noqa: BLE001
                            resp, resp_arrays = {"error": {
                                "msg": str(e),
                                "status": getattr(e, "status_code", 500),
                            }}, None
                        try:
                            sent = wire.send_frame(
                                self.connection, resp, resp_arrays,
                                ring=ring,
                                shm_threshold=host._shm_threshold,
                            )
                        except OSError:
                            break
                        host._wire_count("tx", sent)
                finally:
                    with host._conns_lock:
                        host._conns.discard(self.connection)
                    ring.close()
                    shm_cache.close()

        class Srv(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True
            allow_reuse_address = True

        self._srv = Srv(path, Handler)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True,
            name="engine-host",
        )

    def start(self) -> "EngineHostServer":
        self._thread.start()
        return self

    def is_alive(self) -> bool:
        return self._thread.is_alive()

    def restart(self) -> "EngineHostServer":
        """Replace a dead host with a fresh one on the same socket path.

        The supervisor calls this when the serving thread died; pooled
        worker connections to the old socket fail and reconnect through
        their backoff path."""
        try:
            self._srv.server_close()
        except OSError:
            pass
        fresh = EngineHostServer(self.registry, self.path, self.health_fn)
        return fresh.start()

    def _wire_count(self, direction: str, nbytes: int) -> None:
        self.registry.metrics().counter(
            "keto_wire_bytes_total", float(nbytes),
            help="engine-wire socket bytes by direction", dir=direction,
        )

    def _vocab_state(self):
        """(vocab, epoch) of the owner's device engine, tracking object
        identity: a swapped vocab (checkpoint resume) bumps the epoch."""
        try:
            eng = self.registry._device_engine()
        except Exception:  # noqa: BLE001 - oracle/remote registries
            eng = None
        vocab = getattr(eng, "_vocab", None)
        if vocab is None:
            return None, 0
        if vocab is not self._vocab_obj:
            self._vocab_obj = vocab
            self._vepoch += 1
            self._rev = {
                "ns": _Reverse(vocab.namespaces),
                "obj": _Reverse(vocab.objects),
                "rel": _Reverse(vocab.relations),
                "subj": _Reverse(vocab.subjects),
            }
        return vocab, self._vepoch

    def _serve_frame(self, meta, arrays) -> Tuple[dict, Optional[dict]]:
        op = meta.get("op")
        # workers forward their RPC's traceparent so the owner-side spans
        # (coalescer wave, device dispatch) stitch into the same trace
        tp = meta.pop("traceparent", None)
        # workers forward the remaining budget; bind it so the coalescer
        # slot wait and oracle-fallback loop on the owner side stay inside
        # what the worker's client granted.  ONE budget covers the whole
        # batch — items never re-arm their own timers.
        ms = meta.pop("deadline_ms", None)
        # a worker serving X-Keto-Cache: bypass forwards the flag so the
        # owner-side probe/insert (engine pre-dispatch, coalescer) see the
        # bypass too — the escape hatch must hold across the process hop
        bypass = bool(meta.pop("cache_bypass", False))
        with deadline.scope(None if ms is None else ms / 1000.0):
            if bypass:
                with cache_context.scope(bypass=True):
                    return self._serve_op(meta, arrays, op, tp)
            return self._serve_op(meta, arrays, op, tp)

    def _decode_batch(self, meta, arrays):
        """Rebuild the worker's tuple batch from id rows + strings.
        Returns (tuples, vepoch, stale) — stale means the worker sent id
        rows minted against a different vocab epoch and must resend."""
        n = int(meta.get("n", 0))
        pos_ids = meta.get("pos_ids") or []
        pos_str = meta.get("pos_str") or []
        strs = meta.get("tuples") or []
        if not pos_ids and not pos_str and strs:
            # plain all-strings batch with no position map
            pos_str = list(range(len(strs)))
            n = n or len(strs)
        vocab, vepoch = self._vocab_state()
        ids = arrays.get("ids") if arrays else None
        if pos_ids:
            if vocab is None or int(meta.get("vepoch", 0)) != vepoch:
                return None, vepoch, True
            if ids is None or ids.shape != (len(pos_ids), 4):
                raise ValueError("id rows missing or misshapen")
        tuples: List[Optional[RelationTuple]] = [None] * n
        if pos_ids:
            rev = self._rev
            for row, pos in zip(np.asarray(ids, dtype=np.int64), pos_ids):
                ns = rev["ns"].get(int(row[0]))
                obj = rev["obj"].get(int(row[1]))
                rel = rev["rel"].get(int(row[2]))
                subj = rev["subj"].get(int(row[3]))
                if ns is None or obj is None or rel is None or subj is None:
                    raise ValueError("id row outside the owner vocabulary")
                tuples[int(pos)] = RelationTuple(
                    ns, obj, rel, _decode_subject(subj)
                )
        for s, pos in zip(strs, pos_str):
            tuples[int(pos)] = RelationTuple.from_string(s)
        if any(t is None for t in tuples):
            raise ValueError("batch positions do not cover the batch")
        return tuples, vepoch, False

    def _learn_rows(self, meta, vepoch):
        """Id rows for the string-sent tuples so the worker can mirror
        them: only fully-known rows (no -1 anywhere) are learnable."""
        vocab = self._vocab_obj if vepoch else None
        pos_str = meta.get("pos_str") or []
        strs = meta.get("tuples") or []
        if vocab is None or not strs:
            return [], np.zeros((0, 4), dtype=np.int32)
        if not pos_str:
            pos_str = list(range(len(strs)))
        learn_pos, rows = [], []
        for s, pos in zip(strs, pos_str):
            try:
                t = RelationTuple.from_string(s)
            except Exception:  # noqa: BLE001 - unparseable never mirrors
                continue
            row = (
                vocab.namespaces.lookup(t.namespace),
                vocab.objects.lookup(t.object),
                vocab.relations.lookup(t.relation),
                vocab.subjects.lookup(t.subject.unique_id()),
            )
            if min(row) >= 0:
                learn_pos.append(int(pos))
                rows.append(row)
        return learn_pos, np.asarray(rows, dtype=np.int32).reshape(-1, 4)

    def _serve_op(self, meta, arrays, op, tp):
        r = self.registry
        if op == "check":
            with flightrec.rpc_recording(
                r, "check", traceparent=tp, detail="worker->owner check"
            ):
                t0 = time.perf_counter()
                tuples, vepoch, stale = self._decode_batch(meta, arrays)
                if stale:
                    # the worker's id mirror predates the current vocab:
                    # one extra round trip (strings) re-learns it
                    return {"stale_vocab": vepoch}, None
                flightrec.note_stage("parse", time.perf_counter() - t0)
                eng = r.check_engine()
                depth = int(meta.get("depth", 0))
                # cursor piggyback for the workers' local caches: the store
                # head read BEFORE the compute is a lower bound on the state
                # every verdict in this response is computed from — the
                # engine's dispatch drains the changelog to at least this
                # position (oracle engines read the live store outright).
                # Workers stamp their cache entries with it and advance
                # their staleness fence.
                cur = r.store().log_head
                # the shadow plane lives owner-side only (workers relay):
                # sample worker-routed traffic here, where the verdict and
                # the authoritative store are both in-process
                shadow = r.shadow()
                srow, scur = (
                    shadow.reserve_block(len(tuples))
                    if shadow is not None else (None, 0)
                )
                if len(tuples) == 1:
                    # single-check RPCs from the workers MUST go through
                    # check_is_member: that is the coalescer's enqueue point,
                    # so concurrent singles from every worker merge into one
                    # shared device wave.
                    ok = [bool(eng.check_is_member(tuples[0], depth))]
                    flightrec.note(verdict=ok[0])
                else:
                    batch = getattr(eng, "batch_check", None)
                    if batch is not None:
                        ok = [bool(v) for v in batch(tuples, depth)]
                    else:  # oracle engine: sequential surface only
                        ok = [
                            bool(eng.check_is_member(t, depth))
                            for t in tuples
                        ]
                if srow is not None:
                    shadow.submit(tuples[srow], depth, ok[srow], cursor=scur)
                learn_pos, learn_ids = self._learn_rows(meta, vepoch)
                resp = {
                    "cursor": int(cur),
                    "vepoch": vepoch,
                    "learn_pos": learn_pos,
                    # owner-side span buffer rides home so the worker's
                    # request context shows both processes in one trace
                    "spans": flightrec.export_spans(),
                }
                out = {"ok": np.asarray(ok, dtype=np.uint8)}
                if len(learn_pos):
                    out["learn_ids"] = learn_ids
                return resp, out
        if op == "check_cols":
            # columnar batch: the worker's decoded string columns arrive
            # as packed utf-8 blobs (wire.pack_strcol), become ONE
            # ColumnBlock, and ride the owner's wave as a single column
            # group — no per-item tuple materialization on the hot path
            with flightrec.rpc_recording(
                r, "check", traceparent=tp, detail="worker->owner check_cols"
            ):
                t0 = time.perf_counter()
                cols = {
                    k: wire.unpack_strcol(arrays, k)
                    for k in ("ns", "obj", "rel", "sa", "sb", "sc")
                }
                skind_arr = arrays.get("skind")
                if skind_arr is None:
                    raise ValueError("check_cols frame missing skind")
                skind = [int(v) for v in np.asarray(skind_arr).reshape(-1)]
                block = colmod.ColumnBlock(
                    cols["ns"], cols["obj"], cols["rel"], skind,
                    cols["sa"], cols["sb"], cols["sc"],
                )
                flightrec.note_stage("parse", time.perf_counter() - t0)
                flightrec.note(batch=len(block))
                eng = r.check_engine()
                depth = int(meta.get("depth", 0))
                cur = r.store().log_head
                shadow = r.shadow()
                srow, scur = (
                    shadow.reserve_block(len(block))
                    if shadow is not None else (None, 0)
                )
                # check_block FIRST: the coalescer facade forwards unknown
                # attrs to its inner engine (see handlers._check_block_core)
                cb = (getattr(eng, "check_block", None)
                      or getattr(eng, "batch_check_block", None))
                if cb is not None:
                    allowed, errs = cb(block, depth)
                else:
                    allowed, errs = colmod.block_check_via_tuples(
                        eng, block, depth
                    )
                if srow is not None and srow not in errs:
                    shadow.submit(
                        block[srow], depth, bool(allowed[srow]), cursor=scur
                    )
                resp = {
                    "cursor": int(cur),
                    "errs": [
                        [int(i), str(e),
                         int(getattr(e, "status_code", None) or 500)]
                        for i, e in errs.items()
                    ],
                    "spans": flightrec.export_spans(),
                }
                return resp, {"ok": np.asarray(allowed, dtype=np.uint8)}
        if op == "expand":
            with flightrec.rpc_recording(
                r, "expand", traceparent=tp, detail="worker->owner expand"
            ):
                subject = _decode_subject(meta["subject"])
                tree = r.expand_engine().build_tree(
                    subject, int(meta.get("depth", 0))
                )
                return {
                    "tree": tree.to_json() if tree is not None else None
                }, None
        if op == "list_objects":
            with flightrec.rpc_recording(
                r, "list_objects", traceparent=tp,
                detail="worker->owner list_objects",
            ):
                objs, next_token = r.list_engine().list_objects(
                    meta["namespace"], meta["relation"],
                    _decode_subject(meta["subject"]),
                    page_size=int(meta.get("page_size", 0)),
                    page_token=meta.get("page_token", ""),
                )
                return {
                    "objects": list(objs), "next_page_token": next_token,
                }, None
        if op == "list_subjects":
            with flightrec.rpc_recording(
                r, "list_subjects", traceparent=tp,
                detail="worker->owner list_subjects",
            ):
                subs, next_token = r.list_engine().list_subjects(
                    meta["namespace"], meta["object"], meta["relation"],
                    page_size=int(meta.get("page_size", 0)),
                    page_token=meta.get("page_token", ""),
                )
                return {
                    "subjects": [_encode_subject(s) for s in subs],
                    "next_page_token": next_token,
                }, None
        if op == "barrier":
            # freshness barrier forwarded from a worker: the worker can
            # see the shared store but not the device engine, so the
            # owner runs ensure_fresh (token + mode as wire fields); a
            # StaleSnapshotError (412) rides the ordinary wire-error
            # path and re-raises typed on the worker side
            from ketotpu import consistency

            with flightrec.rpc_recording(
                r, "barrier", traceparent=tp, detail="worker->owner barrier"
            ):
                t0 = time.perf_counter()
                consistency.ensure_fresh(
                    r,
                    meta.get("snaptoken") or None,
                    bool(meta.get("latest")),
                    op=str(meta.get("rpc") or "check"),
                )
                flightrec.note_stage("barrier", time.perf_counter() - t0)
                return {"ok": True}, None
        if op == "repl_bootstrap":
            # warm-standby bootstrap: one frame carries the owner's device
            # projection (the checkpoint codec's flat array dict — no
            # re-projection on the standby), the full store scan, and the
            # changelog tail [cursor, head) so the standby's engine drains
            # forward from the snapshot's cursor exactly as the owner would
            from ketotpu.engine import checkpoint as ckpt

            with flightrec.rpc_recording(
                r, "repl_bootstrap", traceparent=tp,
                detail="standby->owner bootstrap",
            ):
                eng = r._device_engine()
                (snap, cursor, fingerprint, rows, tail, head,
                 version) = eng.replication_snapshot()
                resp_arrays = ckpt.snapshot_to_arrays(
                    snap, extra={"fingerprint": fingerprint},
                    cursor=cursor, head=head, store_version=version,
                )
                wire.pack_tuplecols(resp_arrays, "st", rows)
                wire.pack_changes(resp_arrays, "tl", tail)
                return {
                    "cursor": int(cursor), "head": int(head),
                    "version": int(version),
                    "fingerprint": int(fingerprint),
                    "n_tuples": len(rows),
                }, resp_arrays
        if op == "repl_tail":
            # standby tail poll, doubling as the replication ack: the cursor
            # the standby sends IS its durable head, so acking it here is
            # what releases semi-sync writers waiting in wait_replicated.
            # resync=True mirrors the Watch API's overflow contract — the
            # cursor predates the bounded log and the standby must
            # re-bootstrap from a fresh snapshot.
            if faults.should("tail_drop"):
                raise OSError("fault-injected tail drop")
            cursor = int(meta["cursor"])
            st = r.store()
            if hasattr(st, "changes_since_versioned"):
                entries, head, version = st.changes_since_versioned(cursor)
            else:
                entries, head = st.changes_since(cursor)
                version = st.version
            gate = r.durability_gate()
            if gate is not None:
                gate.ack(cursor)
            resp_arrays = {}
            wire.pack_changes(resp_arrays, "tl", entries or [])
            return {
                "head": int(head), "version": int(version),
                "resync": entries is None,
            }, resp_arrays
        if op == "ping":
            return {"pong": True}, None
        if op == "health":
            # owner-side readiness for the workers' health surface: the
            # worker cannot see the device engine directly, so degraded
            # state (CPU fallback, respawning workers) flows over the wire
            fn = self.health_fn
            return {"health": dict(fn()) if fn is not None else {}}, None
        raise ValueError(f"unknown op {op!r}")

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


class ReplicationGate:
    """Write-path coupling to the warm-standby follower.

    ``durability.replication`` picks the mode:

    * ``async`` (default) — writes ack as soon as the store commits; the
      standby tails on its own schedule and a takeover may lose the last
      unreplicated entries (bounded by the poll interval);
    * ``semi-sync`` — a write's ack waits until the standby's tail cursor
      covers the committed head.  The standby's ``repl_tail`` poll carries
      its durable head as the cursor, and the owner's handler calls
      ``ack`` with it — that IS the replication acknowledgement.

    The gate only engages once a follower has ATTACHED (first tail poll
    seen): a semi-sync owner with no standby yet — boot order, standby
    restart — must not stall every write forever.  A wait that exceeds
    ``durability.ack_timeout_ms`` degrades that one write to async and
    counts it (``keto_replication_ack_timeouts_total``): availability
    over the durability upgrade, loudly.
    """

    def __init__(self, mode: str = "async", *,
                 ack_timeout_ms: float = 2000.0, metrics=None):
        self.mode = str(mode)
        self.ack_timeout = float(ack_timeout_ms) / 1000.0
        self._metrics = metrics
        self._cond = threading.Condition()
        self._acked = -1
        self._attached = False
        self.timeouts = 0
        self.waits = 0

    def ack(self, cursor: int) -> None:
        """Record the follower's durable head (its tail-poll cursor)."""
        with self._cond:
            self._attached = True
            if cursor > self._acked:
                self._acked = cursor
            self._cond.notify_all()

    def detach(self) -> None:
        """Forget the follower (owner noticed it gone); semi-sync writes
        stop waiting until a follower polls again."""
        with self._cond:
            self._attached = False
            self._cond.notify_all()

    def wait_replicated(self, head: Optional[int]) -> bool:
        """Block a committed write until the follower has acked ``head``.
        True = replicated (or gate not engaged); False = timed out and
        degraded to async for this write."""
        if self.mode != "semi-sync" or head is None:
            return True
        t0 = time.monotonic()
        deadline_at = t0 + self.ack_timeout
        with self._cond:
            if not self._attached:
                return True
            self.waits += 1
            while self._attached and self._acked < head:
                left = deadline_at - time.monotonic()
                if left <= 0:
                    self.timeouts += 1
                    if self._metrics is not None:
                        self._metrics.counter(
                            "keto_replication_ack_timeouts_total", 1,
                            help="semi-sync write acks degraded to async "
                                 "after waiting ack_timeout_ms",
                        )
                    return False
                self._cond.wait(timeout=left)
        if self._metrics is not None:
            self._metrics.observe(
                "keto_replication_wait_seconds",
                time.monotonic() - t0,
                help="time a semi-sync write ack waited for the standby's "
                     "tail cursor to cover it",
            )
        return True

    def stats(self) -> dict:
        with self._cond:
            return {
                "mode": self.mode,
                "attached": self._attached,
                "acked_cursor": self._acked,
                "semi_sync_waits": self.waits,
                "ack_timeouts": self.timeouts,
            }


class _Conn:
    def __init__(self, path, *, metrics=None, shm_threshold: int = 0,
                 connect_timeout: Optional[float] = None):
        # ``path`` is a unix-socket path (the same-host worker wire) or a
        # ``(host, port)`` tuple — the TCP form the cross-host PeerLink
        # lane (parallel/peerlink.py) reuses; the framing discipline
        # (strict one-response-per-request, discard on any transport
        # error) is identical on both transports
        if isinstance(path, tuple):
            self.sock = socket.create_connection(
                path, timeout=connect_timeout
            )
            self.sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
            self.sock.settimeout(None)
        else:
            self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            if connect_timeout is not None:
                self.sock.settimeout(connect_timeout)
            self.sock.connect(path)
            self.sock.settimeout(None)
        self.rfile = self.sock.makefile("rb")
        self.lock = threading.Lock()
        self.broken = False
        self._metrics = metrics
        # the shared-memory hop is a SAME-HOST optimization: on TCP the
        # peer is (potentially) another machine, so large payloads stay
        # on the socket and an inbound shm descriptor is a protocol
        # violation (recv_frame with no cache raises WireError)
        self._tcp = isinstance(path, tuple)
        self._shm_threshold = 0 if self._tcp else int(shm_threshold)
        self._ring = None if self._tcp else wire.ShmRing()
        self._shm_cache = None if self._tcp else wire.ShmCache()

    def close(self) -> None:
        self.broken = True
        try:
            self.sock.close()
        except OSError:
            pass
        if self._ring is not None:
            self._ring.close()
        if self._shm_cache is not None:
            self._shm_cache.close()

    def _count(self, direction: str, nbytes: int) -> None:
        if self._metrics is not None:
            self._metrics.counter(
                "keto_wire_bytes_total", float(nbytes),
                help="engine-wire socket bytes by direction",
                dir=direction,
            )

    def call(self, meta, arrays=None,
             timeout: Optional[float] = None) -> Tuple[dict, dict]:
        """One framed request/response on this connection.

        Any transport error — timeout, EOF, framing failure — marks the
        connection broken and closes it: the wire is strictly one
        response per request, so after a partial exchange the NEXT call
        on this socket would read THIS request's late response (the
        desync bug).  Only a decoded typed error keeps the connection —
        the exchange completed, the stream is still aligned.
        """
        if self.broken:
            raise ConnectionError("connection already discarded")
        try:
            with self.lock:
                self.sock.settimeout(timeout)
                sent = wire.send_frame(
                    self.sock, meta, arrays,
                    ring=self._ring, shm_threshold=self._shm_threshold,
                )
                got = wire.recv_frame(self.rfile, shm_cache=self._shm_cache)
            if got is None:
                raise ConnectionError("engine host closed the connection")
            resp, resp_arrays, nread = got
        except Exception:
            self.close()
            raise
        self._count("tx", sent)
        self._count("rx", nread)
        if "error" in resp:
            err = KetoAPIError(resp["error"]["msg"])
            err.status_code = resp["error"].get("status", 500)
            raise err
        return resp, resp_arrays


class RemoteCheckEngine:
    """check.Engine surface forwarding to the device owner's socket.

    A tiny per-thread connection pool: each serving thread keeps its own
    connection (requests on one connection are serialized), so worker
    concurrency maps 1:1 onto owner-side handler threads — which is
    exactly what feeds the owner's coalescer bigger waves.

    Tuples the worker has mirrored ids for ride the wire as packed int32
    rows; the rest go as strings and their ids come back in the response
    (``learn_pos``/``learn_ids``), so steady-state batches are nearly
    all binary.  The owner's vocab EPOCH rides every response; a bump
    (engine vocab swap) resets the mirror, and a ``stale_vocab`` reply
    makes the worker resend that batch as strings.

    Connection errors retry on a fresh connection with capped exponential
    backoff + jitter (the owner may be mid-respawn); a TIMEOUT does not
    retry — the budget is spent and the caller gets DEADLINE_EXCEEDED.
    A batch shares ONE deadline budget across all its items: the budget
    is read once per owner RPC, never re-armed per item."""

    #: reconnect schedule: base*2^n jittered, capped — tuned so a worker
    #: rides out an owner respawn without stampeding the fresh socket
    retry_attempts = 5
    backoff_base = 0.025
    backoff_cap = 0.25

    def __init__(self, path: str, *, rpc_timeout: float = 30.0,
                 cache=None, metrics=None, shm_threshold: int = 262144,
                 breaker_config: Optional[dict] = None,
                 retry_budget_ratio: float = 0.1, logger=None):
        from ketotpu.server.overload import CircuitBreaker, RetryBudget

        self.path = path
        # overload plane, worker-wire lane: the breaker fails calls fast
        # while the owner is down (callers surface the same typed
        # ConnectionError the retry loop would have, without the 5-attempt
        # backoff burn); the retry budget caps reconnect attempts to a
        # fraction of successes so a dead owner cannot multiply load
        self.breaker = CircuitBreaker(
            "worker_wire", metrics=metrics, logger=logger,
            **(breaker_config or {}),
        )
        self.retry_budget = RetryBudget(
            ratio=retry_budget_ratio, lane="worker_wire", metrics=metrics,
        )
        # budget for calls with no request deadline: a wedged owner must
        # surface as an error, not hang every worker thread (<=0 disables)
        self.rpc_timeout = rpc_timeout
        # hot-spot shield, worker side: this process's own ResultCache over
        # the shared store — a hot key answered here never crosses the
        # socket at all.  Verdicts coming back from the owner are stamped
        # with the owner's piggybacked changelog cursor, and that cursor
        # also advances the local staleness fence (the owner broadcasting
        # its drain position to every worker that talks to it).
        self.cache = cache
        self.metrics = metrics
        self.shm_threshold = int(shm_threshold)
        self._flight = SingleFlight(metrics=metrics)
        self.reconnects = 0  # observability: retried transport failures
        self._local = threading.local()
        # vocab mirror shared by every serving thread in this process
        self._mirror_lock = threading.Lock()
        self._mirror_epoch = 0
        self._mirror: dict = {}

    def _conn(self) -> _Conn:
        c = getattr(self._local, "conn", None)
        if c is None or c.broken:
            c = self._local.conn = _Conn(
                self.path, metrics=self.metrics,
                shm_threshold=self.shm_threshold,
            )
        return c

    def _discard(self) -> None:
        c = getattr(self._local, "conn", None)
        if c is not None:
            c.close()
        self._local.conn = None

    def _call(self, meta, arrays=None) -> Tuple[dict, dict]:
        tp = flightrec.current_traceparent()
        if tp:
            meta = dict(meta, traceparent=tp)
        budget = deadline.remaining()
        if budget is not None:
            if budget <= 0:
                raise DeadlineExceededError(
                    "deadline exceeded before owner RPC"
                )
            # forward the remaining budget so the owner bounds ITS waits
            meta = dict(meta, deadline_ms=deadline.deadline_ms())
        timeout = budget
        if timeout is None and self.rpc_timeout > 0:
            timeout = self.rpc_timeout
        if self.metrics is not None:
            self.metrics.counter(
                "keto_wire_calls_total", 1.0,
                help="owner RPC round trips", op=str(meta.get("op")),
            )
        t0 = time.perf_counter()
        try:
            if not self.breaker.allow():
                # lane is open: fail fast into the caller's degrade path
                # instead of burning the full reconnect schedule — the
                # half-open probe will test the owner on the cooldown
                raise ConnectionError(
                    "owner wire circuit breaker open; failing fast"
                )
            last: Optional[BaseException] = None
            for attempt in range(self.retry_attempts):
                try:
                    if faults.should("socket_drop"):
                        self._discard()
                        raise ConnectionError("injected owner-socket drop")
                    resp, resp_arrays = self._conn().call(
                        meta, arrays, timeout=timeout
                    )
                    if isinstance(resp, dict):
                        # owner-side span buffer piggybacks on the reply:
                        # fold it into THIS request's trace so one trace id
                        # covers both processes
                        spans = resp.pop("spans", None)
                        if spans:
                            flightrec.merge_spans(spans)
                    self.breaker.record_success()
                    self.retry_budget.record_success()
                    return resp, resp_arrays
                except KetoAPIError:
                    # a typed error is a COMPLETED exchange — the wire is
                    # healthy even though the verdict is an error
                    self.breaker.record_success()
                    raise
                except TimeoutError:
                    # budget spent waiting on the owner: retrying cannot
                    # beat the deadline, answer DEADLINE_EXCEEDED now
                    self._discard()
                    self.breaker.record_failure()
                    raise DeadlineExceededError(
                        f"owner RPC exceeded {timeout:.3f}s"
                    ) from None
                except (ConnectionError, OSError, ValueError) as e:
                    # ValueError covers a framing failure: the stream
                    # desynced, the connection is already discarded
                    last = e
                    self._discard()
                    self.breaker.record_failure()
                    if attempt + 1 >= self.retry_attempts:
                        break
                    if not self.retry_budget.allow_retry():
                        # retry budget dry: reconnecting now would just
                        # amplify the outage — fail fast instead
                        break
                    self.reconnects += 1
                    delay = min(
                        self.backoff_cap, self.backoff_base * (2 ** attempt)
                    )
                    delay *= 0.5 + random.random() * 0.5  # decorrelate
                    left = deadline.remaining()
                    if left is not None:
                        if left <= 0:
                            raise DeadlineExceededError(
                                "deadline exceeded during owner reconnect"
                            ) from e
                        delay = min(delay, left)
                    time.sleep(delay)
            raise ConnectionError(
                f"owner RPC failed after {attempt + 1} attempts: {last}"
            ) from last
        finally:
            flightrec.note_stage("worker_rpc", time.perf_counter() - t0)

    # -- vocab mirror --------------------------------------------------------

    def _mirror_encode(self, strs: List[str]):
        """Split a batch into mirrored id rows and string leftovers."""
        with self._mirror_lock:
            epoch = self._mirror_epoch
            if not epoch:
                return 0, [], None, list(range(len(strs))), strs
            pos_ids, rows, pos_str, leftovers = [], [], [], []
            for j, s in enumerate(strs):
                row = self._mirror.get(s)
                if row is not None:
                    pos_ids.append(j)
                    rows.append(row)
                else:
                    pos_str.append(j)
                    leftovers.append(s)
        ids = (
            np.asarray(rows, dtype=np.int32).reshape(len(rows), 4)
            if rows else None
        )
        return epoch, pos_ids, ids, pos_str, leftovers

    def _mirror_learn(self, resp, resp_arrays, sent_strs: List[str]) -> None:
        epoch = int(resp.get("vepoch", 0))
        if not epoch:
            return
        learn_pos = resp.get("learn_pos") or []
        learn_ids = (resp_arrays or {}).get("learn_ids")
        with self._mirror_lock:
            if epoch != self._mirror_epoch:
                self._mirror = {}
                self._mirror_epoch = epoch
            if learn_ids is None or not len(learn_pos):
                return
            if len(self._mirror) + len(learn_pos) > _MIRROR_CAP:
                self._mirror = {}
            # learn_pos indexes into the strings WE sent this call; map
            # each back to its canonical form and remember its id row
            pos_to_str = dict(enumerate(sent_strs))
            for row, pos in zip(learn_ids, learn_pos):
                s = pos_to_str.get(int(pos))
                if s is not None:
                    self._mirror[s] = tuple(int(v) for v in row)

    def _mirror_reset(self) -> None:
        with self._mirror_lock:
            self._mirror = {}
            self._mirror_epoch = 0

    # -- check surface -------------------------------------------------------

    def _wire_check(self, strs: List[str], rest_depth: int,
                    bypass: bool) -> Tuple[List[bool], Optional[int]]:
        """One owner round trip for the whole miss-list; id-encodes what
        the mirror knows, learns ids for the rest."""
        epoch, pos_ids, ids, pos_str, leftovers = self._mirror_encode(strs)
        meta = {
            "op": "check",
            "depth": rest_depth,
            "n": len(strs),
            "vepoch": epoch,
            "pos_ids": pos_ids,
            "pos_str": pos_str,
            "tuples": leftovers,
        }
        if bypass:
            meta["cache_bypass"] = True
        arrays = {"ids": ids} if ids is not None else None
        # the position lists index into THIS call's layout; remember the
        # string list actually sent for mirror learning
        resp, resp_arrays = self._call(meta, arrays)
        if resp.get("stale_vocab") is not None:
            # owner swapped vocabularies under our mirror: resend the
            # whole batch as strings (one extra round trip, rare) and
            # relearn from that response
            self._mirror_reset()
            meta = {
                "op": "check",
                "depth": rest_depth,
                "n": len(strs),
                "vepoch": 0,
                "pos_ids": [],
                "pos_str": list(range(len(strs))),
                "tuples": strs,
            }
            if bypass:
                meta["cache_bypass"] = True
            leftovers = strs
            resp, resp_arrays = self._call(meta)
        self._mirror_learn(resp, resp_arrays, leftovers)
        ok_arr = (resp_arrays or {}).get("ok")
        if ok_arr is None:
            ok = [bool(v) for v in resp.get("ok", [])]
        else:
            ok = [bool(v) for v in np.asarray(ok_arr).reshape(-1)]
        if len(ok) != len(strs):
            raise ValueError(
                f"owner answered {len(ok)} verdicts for {len(strs)} tuples"
            )
        cur = resp.get("cursor")
        return ok, (int(cur) if cur is not None else None)

    def batch_check(
        self, queries: Sequence[RelationTuple], rest_depth: int = 0
    ) -> List[bool]:
        if not queries:
            return []
        bypass = cache_context.bypassed()
        cache = None if bypass else self.cache
        results: List[Optional[bool]] = [None] * len(queries)
        miss = list(range(len(queries)))
        if cache is not None:
            hits = cache.lookup_many(
                [cache_check_key(q, rest_depth) for q in queries]
            )
            miss = [i for i, h in enumerate(hits) if h is None]
            for i, h in enumerate(hits):
                if h is not None:
                    results[i] = bool(h.value)
            if len(miss) < len(queries):
                flightrec.note_tier("cache", len(queries) - len(miss))
            if not miss:
                return [bool(v) for v in results]
        ok, cur = self._wire_check(
            [str(queries[i]) for i in miss], rest_depth, bypass,
        )
        if cache is not None and cur is not None:
            cache.advance_fence(int(cur))
            for i, v in zip(miss, ok):
                cache.insert(
                    cache_check_key(queries[i], rest_depth), bool(v), int(cur)
                )
        for i, v in zip(miss, ok):
            results[i] = bool(v)
        return [bool(v) for v in results]

    def batch_check_block(self, block, rest_depth: int = 0):
        """Columnar check surface over the owner wire: the block's string
        columns cross the socket as packed utf-8 blobs in ONE frame
        (wire.pack_strcol) and the verdicts come back as a uint8 array —
        no RelationTuple materialization on either side.

        Same contract as the device engine's ``batch_check_block``:
        ``(allowed bool array, {row: KetoAPIError})``, with the worker's
        local result cache probed first (block.cache_key rows answered
        here never cross the socket) and refilled from the owner's
        piggybacked changelog cursor."""
        n = len(block)
        errs: dict = {}
        allowed = np.zeros(n, dtype=bool)
        if n == 0:
            return allowed, errs
        bypass = cache_context.bypassed()
        cache = None if bypass else self.cache
        miss = list(range(n))
        if cache is not None:
            hits = cache.lookup_many(
                [block.cache_key(i, rest_depth) for i in range(n)]
            )
            miss = [i for i, h in enumerate(hits) if h is None]
            for i, h in enumerate(hits):
                if h is not None:
                    allowed[i] = bool(h.value)
            if len(miss) < n:
                flightrec.note_tier("cache", n - len(miss))
            if not miss:
                return allowed, errs
        sub = block if len(miss) == n else block.take(miss)
        meta = {"op": "check_cols", "depth": int(rest_depth), "n": len(sub)}
        if bypass:
            meta["cache_bypass"] = True
        arrays = {"skind": np.asarray(sub.skind, dtype=np.uint8)}
        for name, col in (("ns", sub.ns), ("obj", sub.obj),
                          ("rel", sub.rel), ("sa", sub.sa),
                          ("sb", sub.sb), ("sc", sub.sc)):
            wire.pack_strcol(arrays, name, col)
        try:
            resp, resp_arrays = self._call(meta, arrays)
        except DeadlineExceededError:
            raise
        except KetoAPIError as e:
            if int(getattr(e, "status_code", 0) or 0) == 504:
                # the owner's deadline expiry crossed the wire as a plain
                # typed error; re-raise it as the batch-wide expiry the
                # handler's per-item 504 fan-out expects
                raise DeadlineExceededError(str(e)) from e
            raise
        ok = (resp_arrays or {}).get("ok")
        if ok is None or len(np.asarray(ok).reshape(-1)) != len(sub):
            raise ValueError(
                f"owner answered {0 if ok is None else len(ok)} verdicts "
                f"for {len(sub)} tuples"
            )
        ok = np.asarray(ok).reshape(-1)
        sub_errs: dict = {}
        for row, msg, status in resp.get("errs") or []:
            e = KetoAPIError(str(msg))
            e.status_code = int(status)
            sub_errs[int(row)] = e
        cur = resp.get("cursor")
        if cache is not None and cur is not None:
            cache.advance_fence(int(cur))
        for j, i in enumerate(miss):
            e = sub_errs.get(j)
            if e is not None:
                errs[i] = e  # errored rows never reach the cache
                continue
            v = bool(ok[j])
            allowed[i] = v
            if cache is not None and cur is not None:
                cache.insert(block.cache_key(i, rest_depth), v, int(cur))
        return allowed, errs

    def check(self, r: RelationTuple, rest_depth: int = 0) -> bool:
        return self.batch_check([r], rest_depth)[0]

    def check_is_member(self, r: RelationTuple, rest_depth: int = 0) -> bool:
        if cache_context.bypassed():
            return self.check(r, rest_depth)
        # worker-side singleflight: a thundering herd on one hot key in
        # THIS process collapses to one owner RPC; followers park
        # deadline-aware and share the leader's verdict (the leader's
        # batch_check also lands it in the local cache for the next wave)
        value, _led = self._flight.do(
            (str(r), int(rest_depth)),
            lambda: self.check(r, rest_depth),
            default_timeout=self.rpc_timeout if self.rpc_timeout > 0 else None,
        )
        return bool(value)

    def consistency_barrier(
        self, snaptoken: Optional[str] = None, latest: bool = False,
        op: str = "check",
    ) -> None:
        """Run the freshness barrier on the device owner
        (ketotpu/consistency/barrier.py routes here when the engine is
        remote).  Raises the owner's typed refusal — StaleSnapshotError
        412 — through the wire-error path."""
        meta = {"op": "barrier", "rpc": op}
        if snaptoken:
            meta["snaptoken"] = snaptoken
        if latest:
            meta["latest"] = True
        self._call(meta)


class RemoteExpandEngine:
    """expand.Engine surface forwarding to the device owner."""

    def __init__(self, path: str, check: Optional[RemoteCheckEngine] = None):
        self._remote = check if check is not None else RemoteCheckEngine(path)

    def build_tree(self, subject: Subject, max_depth: int = 0) -> Optional[Tree]:
        resp, _ = self._remote._call({
            "op": "expand",
            "subject": _encode_subject(subject),
            "depth": max_depth,
        })
        if resp["tree"] is None:
            return None
        return Tree.from_json(resp["tree"])


class RemoteListEngine:
    """Listing-engine surface forwarding to the device owner (the Leopard
    closure index lives with the device; workers only relay)."""

    def __init__(self, path: str, check: Optional[RemoteCheckEngine] = None):
        self._remote = check if check is not None else RemoteCheckEngine(path)

    def list_objects(
        self, namespace: str, relation: str, subject: Subject,
        *, page_size: int = 0, page_token: str = "",
    ):
        resp, _ = self._remote._call({
            "op": "list_objects",
            "namespace": namespace,
            "relation": relation,
            "subject": _encode_subject(subject),
            "page_size": page_size,
            "page_token": page_token,
        })
        return list(resp["objects"]), resp.get("next_page_token", "")

    def list_subjects(
        self, namespace: str, object: str, relation: str,
        *, page_size: int = 0, page_token: str = "",
    ):
        resp, _ = self._remote._call({
            "op": "list_subjects",
            "namespace": namespace,
            "object": object,
            "relation": relation,
            "page_size": page_size,
            "page_token": page_token,
        })
        subs = [_decode_subject(u) for u in resp["subjects"]]
        return subs, resp.get("next_page_token", "")


def engine_host_readiness(path: str, timeout: float = 1.0):
    """Readiness-check factory for worker registries: probe the owner.

    Unreachable owner -> raise (the worker cannot serve checks at all);
    reachable owner with degraded health values -> return the degraded
    string so the worker's health surface mirrors the owner's.
    """

    def probe():
        conn = _Conn(path)
        try:
            resp, _ = conn.call({"op": "health"}, timeout=timeout)
        finally:
            conn.close()
        health = resp.get("health", {})
        bad = {k: v for k, v in health.items() if v != "ok"}
        if not bad:
            return "ok"
        if all(str(v).startswith("degraded") for v in bad.values()):
            return "degraded: owner " + "; ".join(
                f"{k}={v}" for k, v in sorted(bad.items())
            )
        raise ConnectionError(
            "owner unhealthy: " + "; ".join(
                f"{k}={v}" for k, v in sorted(bad.items())
            )
        )

    return probe


class WorkerSupervisor:
    """Respawn dead serve processes with capped backoff + jitter.

    ``serve --workers`` hands this every worker subprocess (and polls the
    owner's engine-host thread itself).  A dead worker is respawned after
    a jittered backoff that grows with its recent death count; while any
    respawn is pending the supervisor's ``state()`` reports ``degraded``
    (surfaced through health + ``status --block``).  A worker that keeps
    dying — ``max_rapid_deaths`` exits inside ``rapid_window`` seconds —
    makes the supervisor give up (``poll`` returns an exit code) instead
    of flapping forever: at that point the failure is systemic, not
    transient.
    """

    def __init__(
        self,
        spawn: Callable[[int], "subprocess.Popen"],
        count: int,
        *,
        max_rapid_deaths: int = 5,
        rapid_window: float = 30.0,
        backoff_base: float = 0.5,
        backoff_cap: float = 5.0,
        log: Optional[Callable[[str], None]] = None,
    ):
        self._spawn = spawn
        self.count = count
        self.max_rapid_deaths = max_rapid_deaths
        self.rapid_window = rapid_window
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self._log = log or (lambda msg: None)
        self.procs: List[Optional["subprocess.Popen"]] = [None] * count
        self.respawns = 0  # observability: successful respawn count
        self._deaths: List[float] = []  # monotonic stamps, pruned to window
        self._death_counts = [0] * count
        self._respawn_at: List[Optional[float]] = [None] * count

    def start(self) -> "WorkerSupervisor":
        for i in range(self.count):
            self.procs[i] = self._spawn(i)
        return self

    def _record_death(self, i: int, rc) -> Optional[int]:
        now = time.monotonic()
        self._deaths.append(now)
        self._deaths = [t for t in self._deaths if now - t < self.rapid_window]
        self._death_counts[i] += 1
        if len(self._deaths) >= self.max_rapid_deaths:
            self._log(
                f"worker {i} exited rc={rc}; {len(self._deaths)} deaths in "
                f"{self.rapid_window:.0f}s — giving up"
            )
            return 1
        delay = min(
            self.backoff_cap,
            self.backoff_base * (2 ** (self._death_counts[i] - 1)),
        )
        delay *= 0.5 + random.random() * 0.5
        self._respawn_at[i] = now + delay
        self._log(
            f"worker {i} exited rc={rc}; respawning in {delay:.1f}s"
        )
        return None

    def poll(self) -> Optional[int]:
        """One supervision step. Returns an exit code to give up with,
        or None to keep serving."""
        now = time.monotonic()
        for i, p in enumerate(self.procs):
            if p is not None and p.poll() is not None:
                rc = self._record_death(i, p.returncode)
                if rc is not None:
                    return rc
                self.procs[i] = None
            if self.procs[i] is None and self._respawn_at[i] is not None:
                if now >= self._respawn_at[i]:
                    self._respawn_at[i] = None
                    self.procs[i] = self._spawn(i)
                    self.respawns += 1
                    self._log(f"worker {i} respawned")
        return None

    def state(self) -> str:
        """Health-check value: 'ok', or 'degraded: ...' while respawning."""
        down = [
            i for i, p in enumerate(self.procs)
            if p is None or p.poll() is not None
        ]
        if not down:
            return "ok"
        return "degraded: respawning worker(s) " + ",".join(map(str, down))

    def terminate(self) -> None:
        for p in self.procs:
            if p is not None and p.poll() is None:
                p.terminate()
        for p in self.procs:
            if p is not None:
                try:
                    p.wait(timeout=5)
                except Exception:  # noqa: BLE001
                    p.kill()
