"""Multi-process serving: SO_REUSEPORT workers around one device owner.

The single-process daemon tops out on the Python wire stack (proto +
HTTP + GIL) long before the engine does — round 3 measured ~74 RPS
through the daemon against ~19k checks/s on-device.  The reference
scales by running on multi-core Go; the Python analog is processes:

* **one device owner** holds the real `DeviceCheckEngine` (a JAX device
  belongs to one process) and serves batched check/expand over a unix
  domain socket (`EngineHostServer`);
* **N workers** each run the full gRPC/REST daemon on the SAME public
  ports via ``SO_REUSEPORT`` (the kernel load-balances accepted
  connections) with a `RemoteCheckEngine` that forwards batches to the
  owner.  The owner's coalescer merges concurrent single checks from
  ALL workers into shared device waves, so cross-process fan-in feeds
  bigger (faster) batches, not contention.

Workers and owner share one durable store DSN (sqlite file / postgres);
writes land in the store from any worker and reach the device through
the owner's ordinary change-log drain.  A ``memory`` DSN cannot be
shared across processes and is refused.

Wire protocol: newline-delimited JSON over the unix socket — tuples in
their canonical string form (`RelationTuple.from_string` round-trips),
typed errors re-raised client-side by status code.  The socket is a
trusted same-host channel (mode 0700 directory recommended); no pickle.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
import time
from typing import List, Optional, Sequence

from ketotpu import flightrec
from ketotpu.api.types import (
    KetoAPIError,
    RelationTuple,
    Subject,
    SubjectID,
    SubjectSet,
    Tree,
)


def _encode_subject(s: Subject) -> str:
    return s.unique_id()


def _decode_subject(u: str) -> Subject:
    if u.startswith("set:"):
        return SubjectSet.from_string(u[4:])
    return SubjectID(u[3:] if u.startswith("id:") else u)


class EngineHostServer:
    """The device owner's unix-socket engine service."""

    def __init__(self, registry, path: str):
        self.registry = registry
        self.path = path
        if os.path.exists(path):
            os.unlink(path)

        host = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    try:
                        req = json.loads(line)
                        resp = host._serve_one(req)
                    except Exception as e:  # noqa: BLE001
                        resp = {"error": {
                            "msg": str(e),
                            "status": getattr(e, "status_code", 500),
                        }}
                    self.wfile.write(json.dumps(resp).encode() + b"\n")
                    self.wfile.flush()

        class Srv(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True
            allow_reuse_address = True

        self._srv = Srv(path, Handler)
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True,
            name="engine-host",
        )

    def start(self) -> "EngineHostServer":
        self._thread.start()
        return self

    def _serve_one(self, req):
        r = self.registry
        op = req.get("op")
        # workers forward their RPC's traceparent so the owner-side spans
        # (coalescer wave, device dispatch) stitch into the same trace
        tp = req.pop("traceparent", None)
        if op == "check":
            with flightrec.rpc_recording(
                r, "check", traceparent=tp, detail="worker->owner check"
            ):
                t0 = time.perf_counter()
                tuples = [RelationTuple.from_string(s) for s in req["tuples"]]
                flightrec.note_stage("parse", time.perf_counter() - t0)
                eng = r.check_engine()
                depth = int(req.get("depth", 0))
                if len(tuples) == 1:
                    # single-check RPCs from the workers MUST go through
                    # check_is_member: that is the coalescer's enqueue point,
                    # so concurrent singles from every worker merge into one
                    # shared device wave.  batch_check passes straight
                    # through the coalescer (it is already batched) — routing
                    # singles there made each RPC its own device dispatch.
                    ok = [bool(eng.check_is_member(tuples[0], depth))]
                    flightrec.note(verdict=ok[0])
                    return {"ok": ok}
                batch = getattr(eng, "batch_check", None)
                if batch is not None:
                    ok = batch(tuples, depth)
                else:  # oracle engine: sequential surface only
                    ok = [eng.check_is_member(t, depth) for t in tuples]
                return {"ok": [bool(v) for v in ok]}
        if op == "expand":
            with flightrec.rpc_recording(
                r, "expand", traceparent=tp, detail="worker->owner expand"
            ):
                subject = _decode_subject(req["subject"])
                tree = r.expand_engine().build_tree(
                    subject, int(req.get("depth", 0))
                )
                return {"tree": tree.to_json() if tree is not None else None}
        if op == "ping":
            return {"pong": True}
        raise ValueError(f"unknown op {op!r}")

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        try:
            os.unlink(self.path)
        except OSError:
            pass


class _Conn:
    def __init__(self, path: str):
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.connect(path)
        self.rfile = self.sock.makefile("rb")
        self.lock = threading.Lock()

    def call(self, req) -> dict:
        with self.lock:
            self.sock.sendall(json.dumps(req).encode() + b"\n")
            line = self.rfile.readline()
        if not line:
            raise ConnectionError("engine host closed the connection")
        resp = json.loads(line)
        if "error" in resp:
            err = KetoAPIError(resp["error"]["msg"])
            err.status_code = resp["error"].get("status", 500)
            raise err
        return resp


class RemoteCheckEngine:
    """check.Engine surface forwarding to the device owner's socket.

    A tiny per-thread connection pool: each serving thread keeps its own
    connection (requests on one connection are serialized), so worker
    concurrency maps 1:1 onto owner-side handler threads — which is
    exactly what feeds the owner's coalescer bigger waves."""

    def __init__(self, path: str):
        self.path = path
        self._local = threading.local()

    def _conn(self) -> _Conn:
        c = getattr(self._local, "conn", None)
        if c is None:
            c = self._local.conn = _Conn(self.path)
        return c

    def _call(self, req) -> dict:
        tp = flightrec.current_traceparent()
        if tp:
            req = dict(req, traceparent=tp)
        t0 = time.perf_counter()
        try:
            try:
                return self._conn().call(req)
            except (ConnectionError, OSError):
                # owner restarted: one reconnect attempt before failing
                self._local.conn = None
                return self._conn().call(req)
        finally:
            flightrec.note_stage("worker_rpc", time.perf_counter() - t0)

    def batch_check(
        self, queries: Sequence[RelationTuple], rest_depth: int = 0
    ) -> List[bool]:
        if not queries:
            return []
        resp = self._call({
            "op": "check",
            "tuples": [str(q) for q in queries],
            "depth": rest_depth,
        })
        return [bool(v) for v in resp["ok"]]

    def check(self, r: RelationTuple, rest_depth: int = 0) -> bool:
        return self.batch_check([r], rest_depth)[0]

    def check_is_member(self, r: RelationTuple, rest_depth: int = 0) -> bool:
        return self.check(r, rest_depth)


class RemoteExpandEngine:
    """expand.Engine surface forwarding to the device owner."""

    def __init__(self, path: str, check: Optional[RemoteCheckEngine] = None):
        self._remote = check if check is not None else RemoteCheckEngine(path)

    def build_tree(self, subject: Subject, max_depth: int = 0) -> Optional[Tree]:
        resp = self._remote._call({
            "op": "expand",
            "subject": _encode_subject(subject),
            "depth": max_depth,
        })
        if resp["tree"] is None:
            return None
        return Tree.from_json(resp["tree"])
