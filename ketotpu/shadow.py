"""Always-on shadow-verification plane (Zanzibar-style live verification).

With the cache, Leopard closure index, columnar fast path, and the mesh
all able to answer a Check, "the fast path still agrees with the
authoritative evaluator" must be *continuously measured*, not assumed.
This module samples ~1/``observability.shadow.sample_rate`` of live check
traffic at the serving edge, captures the inputs + the changelog cursor
they were answered against, and re-evaluates them asynchronously on the
host oracle:

* **same-snapshot guard** — the replay only scores a sample while the
  store's ``log_head`` still equals the cursor captured *before* the
  check ran; anything else (a write raced the sample) is skipped and
  counted, never misfiled as a divergence.  This is what keeps the plane
  at exactly zero false positives under write storms.
* **divergence ledger** — a mismatch files a bounded record carrying the
  answering tier (cache/leopard/fastpath/mesh-shard-N/oracle), wave id,
  trace id, projection generation, and routing decision, increments
  ``keto_shadow_divergence_total``, and force-promotes the request's
  trace in the trace store so the full anatomy of the lying request is
  preserved.  Served at ``GET /debug/divergence``.

The sampling fast path is one lock-guarded counter increment; unsampled
requests pay nothing else.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ketotpu import flightrec

CHECKS_METRIC = "keto_shadow_checks_total"
DIVERGENCE_METRIC = "keto_shadow_divergence_total"
SKIPPED_METRIC = "keto_shadow_skipped_total"


class ShadowVerifier:
    """Sampler + async oracle replayer + divergence ledger."""

    def __init__(
        self,
        registry,
        *,
        sample_rate: int = 1000,
        queue_cap: int = 1024,
        ledger_size: int = 256,
    ):
        self._r = registry
        self.sample_rate = max(1, int(sample_rate))
        self.queue_cap = int(queue_cap)
        self._count = 0
        self._clock = threading.Lock()
        self._cond = threading.Condition()
        self._q: deque = deque()
        self._inflight = 0
        self._ledger: deque = deque(maxlen=int(ledger_size))
        self._closed = False
        self.checks = 0
        self.divergences = 0
        self.skipped = 0
        metrics = registry.metrics()
        if metrics is not None:
            # pre-register the vocabulary so `== 0` is provable on any scrape
            metrics.counter(
                CHECKS_METRIC, 0, help="live checks replayed on the oracle",
            )
            metrics.counter(
                DIVERGENCE_METRIC, 0,
                help="fast-path verdicts that disagreed with the oracle",
            )
            metrics.counter(
                SKIPPED_METRIC, 0,
                help="shadow samples skipped (stale cursor / full queue)",
                reason="stale",
            )
        self._worker = threading.Thread(
            target=self._run, name="shadow-verifier", daemon=True
        )
        self._worker.start()

    # -- sampling fast path --------------------------------------------------

    def reserve(self) -> Optional[int]:
        """One-check sample roll: the captured ``log_head`` cursor when
        this check is sampled, else None.  Call BEFORE the check runs so
        the cursor brackets the verdict from the left."""
        idx = self._advance(1)
        if idx is None:
            return None
        return int(self._r.store().log_head)

    def reserve_block(self, n: int) -> Tuple[Optional[int], int]:
        """Block sample roll: (sampled row index or None, cursor)."""
        if n <= 0:
            return None, 0
        idx = self._advance(n)
        if idx is None:
            return None, 0
        return idx, int(self._r.store().log_head)

    def _advance(self, n: int) -> Optional[int]:
        with self._clock:
            c0 = self._count
            self._count += n
        first = self.sample_rate - 1 - (c0 % self.sample_rate)
        return first if first < n else None

    # -- capture -------------------------------------------------------------

    def submit(self, tuple_, rest_depth: int, verdict: bool, *,
               cursor: int) -> None:
        """Enqueue a sampled check for oracle replay.  Provenance (tier,
        wave, trace id) rides from the current request context; generation
        from the device engine.  Never blocks the serving thread."""
        ctx = flightrec.current()
        info = ctx.info if ctx is not None else {}
        dev = None
        try:
            dev = self._r._device_engine()
        except Exception:  # noqa: BLE001 - engine kinds without a device
            dev = None
        job = {
            "tuple": tuple_,
            "tuple_str": str(tuple_),
            "depth": int(rest_depth),
            "served": bool(verdict),
            "cursor": int(cursor),
            "tier": info.get("tier", "fastpath"),
            "tiers": dict(info.get("tiers") or {}),
            # fused-dispatch provenance (engine/fused.py): a divergence on
            # a fused wave indicts the one compiled program, not a tier
            "fused": bool(info.get("fused", False)),
            "wave": info.get("wave", -1),
            "trace_id": getattr(ctx, "trace_id", None) if ctx else None,
            "traceparent": info.get("traceparent"),
            "generation": int(getattr(dev, "generation", -1)),
            "op": getattr(ctx, "op", "check") if ctx else "check",
        }
        with self._cond:
            if self._closed or len(self._q) >= self.queue_cap:
                self._skip("queue_full")
                return
            self._q.append(job)
            self._cond.notify()

    # -- replay --------------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._closed:
                    self._cond.wait(0.5)
                if self._closed and not self._q:
                    return
                if not self._q:
                    continue
                job = self._q.popleft()
                self._inflight += 1
            try:
                self._replay(job)
            except Exception:  # noqa: BLE001 - the plane must never crash
                self._skip("replay_error")
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def _replay(self, job: Dict) -> None:
        head = int(self._r.store().log_head)
        if head != job["cursor"]:
            # a write raced the sample: the verdict was computed against a
            # state the live store no longer holds — not scoreable
            self._skip("stale")
            return
        oracle = self._r.oracle_engine()
        want = bool(oracle.check_is_member(job["tuple"], job["depth"]))
        if int(self._r.store().log_head) != job["cursor"]:
            # a write landed DURING the replay; same rule
            self._skip("stale")
            return
        metrics = self._r.metrics()
        self.checks += 1
        if metrics is not None:
            metrics.counter(CHECKS_METRIC, 1)
        if want == job["served"]:
            return
        record = {
            "ts": round(time.time(), 3),
            "tuple": job["tuple_str"],
            "depth": job["depth"],
            "served": job["served"],
            "oracle": want,
            "tier": job["tier"],
            "tiers": job["tiers"],
            "fused": job["fused"],
            "wave": job["wave"],
            "trace_id": job["trace_id"],
            "generation": job["generation"],
            "cursor": job["cursor"],
            "op": job["op"],
        }
        self.divergences += 1
        self._ledger.append(record)
        if metrics is not None:
            metrics.counter(DIVERGENCE_METRIC, 1)
        trace_store = None
        try:
            trace_store = self._r.trace_store()
        except Exception:  # noqa: BLE001
            trace_store = None
        if trace_store is not None and job["trace_id"]:
            trace_store.force_promote(job["trace_id"], "divergence")
        log = getattr(self._r, "logger", None)
        logger = log() if callable(log) else None
        if logger is not None:
            logger.error(
                "shadow divergence: %s served=%s oracle=%s tier=%s wave=%s "
                "generation=%s trace=%s",
                job["tuple_str"], job["served"], want, job["tier"],
                job["wave"], job["generation"], job["trace_id"],
            )

    def _skip(self, reason: str) -> None:
        self.skipped += 1
        metrics = self._r.metrics()
        if metrics is not None:
            metrics.counter(SKIPPED_METRIC, 1, reason=reason)

    # -- read side / lifecycle ----------------------------------------------

    def ledger(self) -> List[Dict]:
        return list(self._ledger)

    def stats(self) -> Dict:
        with self._cond:
            queued = len(self._q)
        return {
            "sample_rate": self.sample_rate,
            "checks": self.checks,
            "divergences": self.divergences,
            "skipped": self.skipped,
            "queued": queued,
        }

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until the replay queue is empty and idle (tests/benches).
        True when fully drained inside the timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._q or self._inflight:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(min(left, 0.25))
        return True

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._worker.join(timeout=5.0)
