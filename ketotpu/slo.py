"""Multi-window SLO burn-rate engine (Zanzibar-style serving objectives).

The ROADMAP north star is a latency objective (p99 ≤ 2 ms at ≥100k
check/s) — but until this module nothing in the stack computed whether
an objective was actually being *met over time*.  The engine turns the
cumulative per-request outcome histogram flightrec.py already emits
(``keto_request_outcome_seconds{op,outcome}``) into windowed SLI rates:

* **availability** — ok / (ok + shed + error) over the window; sheds and
  5xx both burn the availability budget (a 429 is the server refusing
  work it promised to absorb).
* **latency compliance** — among *ok* requests, the fraction whose
  end-to-end latency landed at or under ``observability.slo.
  latency_target_ms``.  The target is snapped to the nearest histogram
  bucket bound (observability.BUCKETS) so the fraction is exact, not
  interpolated; sheds/errors are excluded so a fast 429 cannot flatter
  the latency SLI.
* **burn rate** — the classic multi-window form: ``(1 - sli) /
  (1 - objective)`` for each SLI, and the per-op burn gauge is the worse
  of the two.  Burn 1.0 = consuming error budget exactly at the rate
  that exhausts it at the window's end; the watchdog alarms on the fast
  window crossing ``observability.watchdog.burn_threshold``.

Two windows ride one ring of delta buckets: a fast window (~5 min,
page-worthy burn) and a slow window (~1 h, budget trend).  ``sample()``
is called from the metrics scrape path (`Registry.sample_engine_metrics`)
and from every watchdog tick, so the ring advances whenever anyone is
watching; between samples the cumulative histogram holds the truth and
no request-path work is added.

Exposed as ``keto_slo_{availability,latency_compliance,burn_rate}
{op,window}`` gauges, ``GET /debug/slo``, and the compact fleet digest
(`Registry.health_digest`) that rides the DCN heartbeat.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, List, Optional, Tuple

from ketotpu import flightrec
from ketotpu.observability import BUCKETS

AVAILABILITY_GAUGE = "keto_slo_availability"
LATENCY_GAUGE = "keto_slo_latency_compliance"
BURN_GAUGE = "keto_slo_burn_rate"

#: ring granularity: the fast window is split into this many buckets, so
#: a 300 s fast window advances every 5 s — fine enough that the fast
#: burn alarm reacts within one watchdog tick of a storm starting
_FAST_BUCKETS = 60


def snap_target_bucket(latency_target_ms: float) -> Tuple[int, float]:
    """(bucket index, snapped target seconds): the smallest histogram
    bound >= the requested target; +Inf (index len(BUCKETS)) when the
    target exceeds every finite bound."""
    target_s = float(latency_target_ms) / 1000.0
    idx = bisect.bisect_left(BUCKETS, target_s)
    snapped = BUCKETS[idx] if idx < len(BUCKETS) else float("inf")
    return idx, snapped


class _OpTotals:
    """Cumulative (total, ok, under-target) read off the metrics registry."""

    __slots__ = ("total", "ok", "under")

    def __init__(self, total: int = 0, ok: int = 0, under: int = 0):
        self.total = total
        self.ok = ok
        self.under = under


class SLOEngine:
    """Windowed availability/latency SLIs + burn rates per op."""

    def __init__(
        self,
        metrics,
        *,
        latency_target_ms: float = 25.0,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        availability_objective: float = 0.999,
        latency_objective: float = 0.99,
        clock=time.monotonic,
    ):
        self._metrics = metrics
        self.latency_target_ms = float(latency_target_ms)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = max(float(slow_window_s), self.fast_window_s)
        self.availability_objective = float(availability_objective)
        self.latency_objective = float(latency_objective)
        self._clock = clock
        self._target_idx, self._target_s = snap_target_bucket(
            latency_target_ms
        )
        self._bucket_s = max(self.fast_window_s / _FAST_BUCKETS, 0.5)
        self._ring_len = int(self.slow_window_s / self._bucket_s) + 2
        self._lock = threading.Lock()
        # ring of {op: (d_total, d_ok, d_under)} deltas keyed by slot id
        self._ring: List[Optional[Tuple[int, Dict]]] = (
            [None] * self._ring_len
        )
        self._last: Dict[str, _OpTotals] = {}
        self._primed = False
        if metrics is not None:
            # pre-register the gauge vocabulary (healthy values) so a
            # fresh daemon's first scrape already carries the names
            for window in ("fast", "slow"):
                metrics.gauge(
                    AVAILABILITY_GAUGE, 1.0,
                    help="windowed availability SLI (1.0 = no errors/sheds)",
                    op="check", window=window,
                )
                metrics.gauge(
                    LATENCY_GAUGE, 1.0,
                    help="fraction of ok requests under the latency target",
                    op="check", window=window,
                )
                metrics.gauge(
                    BURN_GAUGE, 0.0,
                    help="error-budget burn rate (1.0 = budget gone at "
                         "window end)", op="check", window=window,
                )

    # -- sampling -------------------------------------------------------------

    def _read_cumulative(self) -> Dict[str, _OpTotals]:
        """Fold the outcome histogram's series into per-op totals."""
        out: Dict[str, _OpTotals] = {}
        if self._metrics is None:
            return out
        series = self._metrics.histogram_buckets(flightrec.OUTCOME_METRIC)
        for labels, (buckets, _sum, count) in series.items():
            lab = dict(labels)
            op = lab.get("op", "other")
            outcome = lab.get("outcome", "ok")
            t = out.setdefault(op, _OpTotals())
            t.total += count
            if outcome == "ok":
                t.ok += count
                # cumulative count at or under the snapped target bucket
                t.under += sum(buckets[: self._target_idx + 1])
        return out

    def sample(self, now: Optional[float] = None) -> None:
        """Advance the ring: cumulative deltas since the last sample land
        in the bucket of *now*.  Threadsafe; cheap enough for every
        scrape and watchdog tick."""
        t = self._clock() if now is None else float(now)
        slot = int(t // self._bucket_s)
        cum = self._read_cumulative()
        with self._lock:
            if not self._primed:
                # first sample: adopt the cumulative state as the floor so
                # pre-engine traffic does not land in one giant bucket
                self._last = cum
                self._primed = True
                return
            deltas: Dict[str, Tuple[int, int, int]] = {}
            for op, c in cum.items():
                p = self._last.get(op, _OpTotals())
                d = (c.total - p.total, c.ok - p.ok, c.under - p.under)
                if d[0] > 0 or d[1] > 0 or d[2] > 0:
                    deltas[op] = d
            self._last = cum
            idx = slot % self._ring_len
            held = self._ring[idx]
            if held is None or held[0] != slot:
                self._ring[idx] = (slot, dict(deltas))
            else:
                merged = held[1]
                for op, (dt, dok, du) in deltas.items():
                    pt, pok, pu = merged.get(op, (0, 0, 0))
                    merged[op] = (pt + dt, pok + dok, pu + du)

    # -- window math ----------------------------------------------------------

    def _window_totals(
        self, window_s: float, now: float
    ) -> Dict[str, Tuple[int, int, int]]:
        slot_now = int(now // self._bucket_s)
        first = slot_now - int(window_s / self._bucket_s)
        out: Dict[str, Tuple[int, int, int]] = {}
        for held in self._ring:
            if held is None:
                continue
            slot, deltas = held
            if slot < first or slot > slot_now:
                continue
            for op, (dt, dok, du) in deltas.items():
                pt, pok, pu = out.get(op, (0, 0, 0))
                out[op] = (pt + dt, pok + dok, pu + du)
        return out

    @staticmethod
    def _slis(total: int, ok: int, under: int) -> Tuple[float, float]:
        availability = (ok / total) if total > 0 else 1.0
        compliance = (under / ok) if ok > 0 else 1.0
        return availability, min(compliance, 1.0)

    def _burn(self, availability: float, compliance: float) -> float:
        a_budget = max(1.0 - self.availability_objective, 1e-9)
        l_budget = max(1.0 - self.latency_objective, 1e-9)
        return max(
            (1.0 - availability) / a_budget,
            (1.0 - compliance) / l_budget,
        )

    def window_report(
        self, window_s: float, now: Optional[float] = None
    ) -> Dict[str, Dict]:
        """{op: {total, availability, latency_compliance, burn_rate}}
        over the trailing ``window_s`` seconds."""
        t = self._clock() if now is None else float(now)
        with self._lock:
            totals = self._window_totals(window_s, t)
        report: Dict[str, Dict] = {}
        for op, (total, ok, under) in sorted(totals.items()):
            availability, compliance = self._slis(total, ok, under)
            report[op] = {
                "total": total,
                "availability": round(availability, 6),
                "latency_compliance": round(compliance, 6),
                "burn_rate": round(self._burn(availability, compliance), 4),
            }
        return report

    # -- read side ------------------------------------------------------------

    def snapshot(self, now: Optional[float] = None) -> Dict:
        """The ``GET /debug/slo`` body."""
        return {
            "objectives": {
                "availability": self.availability_objective,
                "latency": self.latency_objective,
                "latency_target_ms": self.latency_target_ms,
                "latency_target_bucket_s": (
                    None if self._target_s == float("inf")
                    else self._target_s
                ),
            },
            "windows": {
                "fast_s": self.fast_window_s,
                "slow_s": self.slow_window_s,
            },
            "fast": self.window_report(self.fast_window_s, now),
            "slow": self.window_report(self.slow_window_s, now),
        }

    def max_burn(
        self, window: str = "fast", now: Optional[float] = None
    ) -> float:
        """Worst per-op burn rate over one window — the watchdog's alarm
        input and the fleet digest's headline number."""
        window_s = (
            self.fast_window_s if window == "fast" else self.slow_window_s
        )
        report = self.window_report(window_s, now)
        return max(
            (r["burn_rate"] for r in report.values()), default=0.0
        )

    def digest(self, now: Optional[float] = None) -> Dict[str, float]:
        """Compact burn summary for the heartbeat health digest."""
        return {
            "fast": round(self.max_burn("fast", now), 4),
            "slow": round(self.max_burn("slow", now), 4),
        }

    def publish(self, now: Optional[float] = None) -> None:
        """Refresh the ``keto_slo_*`` gauges (scrape path)."""
        if self._metrics is None:
            return
        self.sample(now)
        for window, window_s in (
            ("fast", self.fast_window_s), ("slow", self.slow_window_s),
        ):
            for op, r in self.window_report(window_s, now).items():
                self._metrics.gauge(
                    AVAILABILITY_GAUGE, r["availability"],
                    op=op, window=window,
                )
                self._metrics.gauge(
                    LATENCY_GAUGE, r["latency_compliance"],
                    op=op, window=window,
                )
                self._metrics.gauge(
                    BURN_GAUGE, r["burn_rate"], op=op, window=window,
                )
