"""Anonymized usage telemetry (SQA) — the reference's metricsx seam.

The reference wires ory/x metricsx into the daemon
(`internal/driver/daemon.go:64-98`): an opt-out background reporter that
ships anonymized usage snapshots — service name, a HASH of the network
id as the deployment id, build version, and request counts restricted
to a whitelisted path set — to a vendor endpoint on a 6-hour interval.

This is the TPU-native analog with one deliberate parity delta: the
reference hard-codes its vendor's endpoint and write key; an
independent deployment has no vendor to report to, so ``sqa.server_url``
must be CONFIGURED for the reporter to start at all (``sqa.opt_out``
is still honored on top, preserving the reference's opt-out semantics
for distributions that do configure an endpoint).

Anonymization contract (metricsx parity):

* the deployment id is ``sha256(network_id)`` — never the raw id;
* only WHITELISTED metric names ship (request/check counters), never
  label values that could carry tenant data (namespace names, objects);
* payloads are fire-and-forget JSON POSTs; failures are dropped and
  never surface into serving.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import urllib.request
from typing import Optional

from ketotpu import __version__

#: metric names whose TOTALS (labels stripped) may ship — mirrors the
#: reference's WhitelistedPaths idea: aggregate usage, no tenant data
WHITELISTED_COUNTERS = (
    "keto_checks_total",
    "keto_expands_total",
    "keto_relation_tuple_writes_total",
    "keto_requests_total",
)

DEFAULT_INTERVAL_S = 6 * 3600.0  # daemon.go:95 (6h batches)


class SqaReporter:
    """Background usage reporter; ``close()`` stops it."""

    def __init__(
        self,
        endpoint: str,
        *,
        network_id: str,
        metrics=None,
        logger=None,
        dsn: str = "",
        interval: float = DEFAULT_INTERVAL_S,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.metrics = metrics
        self.logger = logger
        self.interval = interval
        self.deployment_id = hashlib.sha256(
            network_id.encode()
        ).hexdigest()
        # the reference flags sqlite-backed deployments as development
        # installs (daemon.go:74)
        self.is_development = dsn.startswith(("sqlite", "memory"))
        self.sent = 0
        self.errors = 0
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="keto-sqa", daemon=True
        )
        self._thread.start()

    def _snapshot(self) -> dict:
        counts = {}
        if self.metrics is not None:
            with self.metrics._lock:
                for (name, _labels), v in self.metrics._counters.items():
                    if name in WHITELISTED_COUNTERS:
                        counts[name] = counts.get(name, 0.0) + v
        return {
            "service": "keto-tpu",
            "deployment_id": self.deployment_id,
            "version": __version__,
            "is_development": self.is_development,
            "uptime_s": round(time.monotonic() - self._t0, 1),
            "counters": counts,
        }

    def _post(self) -> None:
        body = json.dumps(self._snapshot()).encode()
        req = urllib.request.Request(
            self.endpoint + "/v1/usage",
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=10.0) as resp:
                resp.read()
            self.sent += 1
        except Exception as e:  # noqa: BLE001 — telemetry never breaks serving
            self.errors += 1
            if self.logger is not None:
                self.logger.debug("sqa report dropped: %s", e)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self._post()

    def flush(self) -> None:
        """One immediate report (tests; shutdown best-effort)."""
        self._post()

    def close(self) -> None:
        self._stop.set()


def maybe_start(config, *, network_id: str, metrics=None, logger=None) -> Optional[SqaReporter]:
    """Build the reporter iff an endpoint is configured and the operator
    did not opt out (daemon.go:64 gate)."""
    endpoint = str(config.get("sqa.server_url", "") or "")
    if not endpoint or bool(config.get("sqa.opt_out", False)):
        return None
    interval = (
        float(config.get("sqa.interval_ms", DEFAULT_INTERVAL_S * 1000))
        / 1000.0
    )
    return SqaReporter(
        endpoint,
        network_id=network_id,
        metrics=metrics,
        logger=logger,
        dsn=str(config.get("dsn", "")),
        # floor: interval_ms: 0 is schema-valid but would busy-loop POSTs
        # at the endpoint for the daemon's lifetime
        interval=max(interval, 60.0),
    )
