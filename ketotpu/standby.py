"""Warm-standby follower: replicated changelog, snaptoken-exact takeover.

``keto-tpu serve --standby`` runs this process next to a live owner.  It
bootstraps over the owner's engine-host socket (``durability.socket``)
with ONE framed wire op — the checkpoint codec's flat array dict (the
projected CSR snapshot, so the standby never re-projects), the full
store scan, and the changelog tail — then anchors its local replica
store at the OWNER'S changelog coordinates (``adopt_replica``).  From
there it stays warm:

* the shipped snapshot is installed on the local device
  (``adopt_snapshot``) and the jit programs are pre-compiled against the
  owner's shapes by probe checks, so the first post-takeover verdict
  costs a dispatch, not a cold projection build or an XLA compile;
* a tail loop polls ``repl_tail`` every ``durability.poll_ms``, applying
  the owner's changelog entries position-exactly (``apply_replicated``)
  and draining them into the device overlay — the poll's cursor IS the
  standby's durable head, which the owner's :class:`ReplicationGate`
  treats as the semi-sync replication ack;
* a tail cursor that fell off the owner's bounded log comes back as
  ``resync`` (the Watch API's overflow contract) and the standby
  re-bootstraps from a fresh snapshot instead of serving a gap.

Takeover is snaptoken-exact: because the replica lives at the owner's
(version, cursor) coordinates, every token the old owner ever minted is
satisfiable here and at-least-as-fresh reads never regress.  Promotion
fires on ``durability.heartbeat_misses`` consecutive failed polls (owner
death) or a deliberate ``POST /debug/handoff`` on the standby's metrics
port (rolling restart); the caller then binds the SO_REUSEPORT front
door via ``daemon.serve_all(reg, reuse_port=True)``.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ketotpu import compilewatch
from ketotpu.engine import checkpoint as ckpt
from ketotpu.server import wire

#: numeric encoding of the follower state for the keto_standby_state gauge
STATES = {
    "bootstrapping": 0,
    "tailing": 1,
    "resyncing": 2,
    "promoting": 3,
    "serving": 4,
}

#: probe rounds the warm-up loop may spend chasing compile quiescence
_WARM_MAX_ROUNDS = 16
#: consecutive compile-free probe dispatches before declaring warm
_WARM_CLEAN_TARGET = 2


class StandbyError(RuntimeError):
    """The follower cannot proceed (misconfiguration, dead owner at
    bootstrap); the CLI surfaces it and exits non-zero."""


class StandbyFollower:
    """The follower state machine: bootstrap → tail → (resync) → promote."""

    def __init__(
        self,
        registry,
        socket_path: str,
        *,
        poll_s: Optional[float] = None,
        heartbeat_s: Optional[float] = None,
        heartbeat_misses: Optional[int] = None,
    ):
        cfg = registry.config
        self.registry = registry
        self.path = socket_path
        self.poll_s = poll_s if poll_s is not None else float(
            cfg.get("durability.poll_ms", 50) or 50
        ) / 1000.0
        self.heartbeat_s = heartbeat_s if heartbeat_s is not None else float(
            cfg.get("durability.heartbeat_ms", 500) or 500
        ) / 1000.0
        self.miss_budget = int(
            heartbeat_misses if heartbeat_misses is not None
            else cfg.get("durability.heartbeat_misses", 3) or 3
        )
        self._conn = None
        self._lock = threading.Lock()
        self.state = "bootstrapping"
        self.misses = 0
        self.resyncs = 0
        self.bootstraps = 0
        self.applied_entries = 0
        self.owner_head = -1
        self.owner_version = -1
        self.warm_probe_rounds = 0
        self._last_ok = time.monotonic()
        self._promote_evt = threading.Event()
        self._promote_reason: Optional[str] = None
        # surface this follower on the registry's debug plane: standby
        # rows in /debug/projection + status --debug, and POST
        # /debug/handoff on the standby's own metrics port
        registry.standby_state_fn = self.state_snapshot
        registry.handoff_fn = self.request_promote

    # -- wire ----------------------------------------------------------------

    def _call(self, meta, timeout: Optional[float]):
        from ketotpu.server.workers import _Conn

        if self._conn is None or self._conn.broken:
            self._conn = _Conn(
                self.path,
                metrics=self.registry.metrics(),
                shm_threshold=int(
                    self.registry.config.get(
                        "engine.wire_shm_threshold", 262144
                    ) or 262144
                ),
            )
        return self._conn.call(meta, timeout=timeout)

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    # -- bootstrap -----------------------------------------------------------

    def bootstrap(self, *, timeout: float = 300.0) -> None:
        """Stream the owner's snapshot + scan + tail and install all three
        at the owner's coordinates; then drain and pre-compile."""
        from ketotpu.engine.tpu import config_fingerprint

        self._set_state("bootstrapping")
        resp, arrays = self._call({"op": "repl_bootstrap"}, timeout)
        eng = self.registry._device_engine()
        if eng is None:
            raise StandbyError(
                "standby needs a device engine (engine.kind=tpu)"
            )
        # the shipped fingerprint must match OUR namespace config: adopting
        # a projection built under different namespaces would serve wrong
        # verdicts silently — refuse loudly instead (SnapshotFormatError)
        want = config_fingerprint(self.registry.namespace_manager())
        snap = ckpt.snapshot_from_arrays(arrays, {"fingerprint": want})
        rows = wire.unpack_tuplecols(arrays, "st")
        tail = wire.unpack_changes(arrays, "tl")
        cursor = int(resp["cursor"])
        head = int(resp["head"])
        version = int(resp["version"])
        store = self.registry.store()
        if not hasattr(store, "adopt_replica"):
            raise StandbyError(
                f"store {type(store).__name__} cannot host a replica; "
                "run the standby with dsn=memory"
            )
        store.adopt_replica(rows, head, version, log=tail, log_start=cursor)
        eng.adopt_snapshot(snap, cursor=cursor, fingerprint=want)
        eng.snapshot()  # drain the shipped tail into the overlay
        self.owner_head = head
        self.owner_version = version
        self.bootstraps += 1
        self._last_ok = time.monotonic()
        self._warm(eng)
        self._set_state("tailing")

    def _warm(self, eng) -> None:
        """Probe-dispatch until the compile observatory goes quiet, then
        declare warm: from here every XLA compile is an after-warm alarm,
        which is exactly the takeover guarantee — the first post-promotion
        verdict must not pay a compile."""
        rows, _ = self.registry.store().get_relation_tuples(page_size=4)
        if not rows:
            return  # empty graph: nothing to shape the programs against
        watch = compilewatch.get()
        clean = 0
        for _ in range(_WARM_MAX_ROUNDS):
            before = watch.compiles_total
            eng.batch_check(list(rows), 0)
            self.warm_probe_rounds += 1
            if watch.compiles_total == before:
                clean += 1
                if clean >= _WARM_CLEAN_TARGET:
                    break
            else:
                clean = 0
        watch.declare_warm()

    # -- tail loop -----------------------------------------------------------

    def poll_once(self) -> bool:
        """One tail poll; True on success.  A failure of ANY kind — socket
        drop, owner error, timeout — is one heartbeat miss; the owner is
        only as alive as its ability to answer the tail."""
        store = self.registry.store()
        cursor = store.log_head
        try:
            resp, arrays = self._call(
                {"op": "repl_tail", "cursor": int(cursor)},
                max(self.heartbeat_s, 0.1),
            )
        except Exception:  # noqa: BLE001 - every failure is one miss
            self.misses += 1
            self._set_gauges()
            return False
        self.misses = 0
        self._last_ok = time.monotonic()
        self.owner_head = int(resp["head"])
        self.owner_version = int(resp["version"])
        if resp.get("resync"):
            # our cursor fell off the owner's bounded log: the gap is
            # unrecoverable from the tail — re-bootstrap from a fresh
            # snapshot (mirrors the Watch API's resync_required marker)
            self.resyncs += 1
            self.registry.metrics().counter(
                "keto_standby_resyncs_total", 1,
                help="standby re-bootstraps after changelog overflow",
            )
            self._set_state("resyncing")
            self.bootstrap()
            return True
        entries = wire.unpack_changes(arrays, "tl")
        if entries:
            store.apply_replicated(
                entries, self.owner_head, self.owner_version
            )
            self.applied_entries += len(entries)
            eng = self.registry._device_engine()
            if eng is not None:
                eng.snapshot()  # drain into the device overlay, stay warm
        self._set_gauges()
        return True

    def run(self) -> str:
        """Bootstrap, then tail until promotion triggers; returns the
        promotion reason (``owner_death`` | a /debug/handoff reason)."""
        self.bootstrap()
        while not self._promote_evt.is_set():
            ok = self.poll_once()
            if not ok and self.misses >= self.miss_budget:
                self.request_promote("owner_death")
                break
            # failed polls back off to the heartbeat cadence; healthy
            # ones run at the (faster) replication poll interval
            self._promote_evt.wait(
                self.poll_s if ok else self.heartbeat_s
            )
        return self.promote(self._promote_reason or "handoff")

    # -- promotion -----------------------------------------------------------

    def request_promote(self, reason: str = "handoff") -> dict:
        """Ask the tail loop to promote (the /debug/handoff seam); safe
        from any thread, idempotent."""
        with self._lock:
            if self._promote_reason is None:
                self._promote_reason = str(reason or "handoff")
        self._promote_evt.set()
        return {"status": "promoting", "state": self.state}

    def promote(self, reason: str) -> str:
        """Finalize takeover: one last drain so the served snapshot covers
        every replicated entry, then hand the front door to the caller.
        The projection was shipped and the programs pre-compiled, so this
        is O(tail), never a cold build."""
        self._set_state("promoting")
        self.close()
        # this process is the owner now: /debug/handoff must 409 again
        # (the state_snapshot seam stays — its serving row is useful)
        self.registry.handoff_fn = None
        eng = self.registry._device_engine()
        if eng is not None:
            eng.snapshot()
        self.registry.metrics().counter(
            "keto_handoff_total", 1,
            help="standby promotions by trigger", reason=reason,
        )
        self._set_state("serving")
        return reason

    # -- observability -------------------------------------------------------

    def _set_state(self, state: str) -> None:
        self.state = state
        self._set_gauges()

    def _set_gauges(self) -> None:
        m = self.registry.metrics()
        lag = max(0, self.owner_head - self.registry.store().log_head) \
            if self.owner_head >= 0 else 0
        m.gauge("keto_standby_lag_entries", float(lag),
                help="changelog entries the standby has not yet applied")
        m.gauge("keto_standby_lag_seconds",
                time.monotonic() - self._last_ok,
                help="seconds since the standby last heard the owner")
        m.gauge("keto_standby_state", STATES.get(self.state, -1),
                help="follower state (0=bootstrapping 1=tailing "
                     "2=resyncing 3=promoting 4=serving)")

    def state_snapshot(self) -> dict:
        """The standby row for /debug/projection and status --debug."""
        store = self.registry.store()
        return {
            "state": self.state,
            "cursor": store.log_head,
            "owner_head": self.owner_head,
            "owner_version": self.owner_version,
            "lag_entries": max(0, self.owner_head - store.log_head)
            if self.owner_head >= 0 else 0,
            "misses": self.misses,
            "miss_budget": self.miss_budget,
            "resyncs": self.resyncs,
            "bootstraps": self.bootstraps,
            "applied_entries": self.applied_entries,
            "warm_probe_rounds": self.warm_probe_rounds,
        }
