"""Relation-tuple storage: in-memory manager, traverser, namespace managers.

The store is the system of record for writes (the Manager seam,
`internal/relationtuple/definitions.go:27-33`); the TPU engine reads
projected CSR snapshots of it, not the store directly.
"""

from ketotpu.storage.memory import ErrMalformedPageToken, InMemoryTupleStore
from ketotpu.storage.sqlite import MIGRATIONS, SQLiteTupleStore
from ketotpu.storage.namespaces import (
    DirectoryNamespaceManager,
    OPLFileNamespaceManager,
    StaticNamespaceManager,
    ast_relation_for,
)
from ketotpu.storage.traverser import (
    TraversalDirection,
    TraversalResult,
    Traverser,
)

__all__ = [
    "DirectoryNamespaceManager",
    "ErrMalformedPageToken",
    "InMemoryTupleStore",
    "MIGRATIONS",
    "SQLiteTupleStore",
    "OPLFileNamespaceManager",
    "StaticNamespaceManager",
    "TraversalDirection",
    "TraversalResult",
    "Traverser",
    "ast_relation_for",
]
