"""Columnar tuple store: the 10M-tuple-scale Manager implementation.

The reference loads bulk data through row-at-a-time SQL inserts
(`internal/persistence/sql/relationtuples.go:263-287`); at the BASELINE
scale (10M tuples) a Python-object row store costs gigabytes and minutes
of per-tuple work just to *hold* the data.  This store keeps a bulk-loaded
**base segment** as numpy id columns over a shared `Vocab` — the exact
layout the device projection consumes (`engine/delta.TupleColumns`), so
the engine adopts it zero-copy via ``export_columns`` instead of
materializing ten million `RelationTuple` objects.

Everything written *after* the bulk load flows through the inherited
`InMemoryTupleStore` machinery (rows, indexes, change log), so the write
path, pagination contract, and change-log semantics are identical to the
in-memory store; reads stitch the base segment and the tail together.
Base-segment queries run as vectorized column scans behind a lazily built
sorted index (the (ns, obj, rel) forward index — the same shape as the
reference's ``idx_relation_tuples_full`` partial index).

Wire parity note: base sequence numbers are 0..n_base-1 in load order and
tail rows continue after them, so page tokens behave exactly like the
in-memory store's.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from ketotpu.api.types import (
    RelationQuery,
    RelationTuple,
    SubjectID,
    SubjectSet,
)
from ketotpu.engine.vocab import Vocab
from ketotpu.storage.memory import (
    DEFAULT_PAGE_SIZE,
    InMemoryTupleStore,
    _matches,
)


class ColumnarTupleStore(InMemoryTupleStore):
    """Manager over a columnar base segment + an in-memory tail."""

    #: column names of the base segment (TupleColumns layout)
    COLS = ("ns", "obj", "rel", "subj", "is_set", "s_ns", "s_obj", "s_rel")

    def __init__(self, vocab: Optional[Vocab] = None):
        super().__init__()
        self.vocab = vocab if vocab is not None else Vocab()
        self._b: Dict[str, np.ndarray] = {
            c: np.zeros(0, np.int32) for c in self.COLS
        }
        self._b_alive = np.zeros(0, bool)
        self._b_n = 0
        # id -> string decode tables, refreshed lazily from the vocab
        self._dec: Dict[str, List[str]] = {}
        # lazy (hi=ns*STRIDE... ) sorted forward index over base rows
        self._fwd_order: Optional[np.ndarray] = None
        self._fwd_keys: Optional[np.ndarray] = None
        self._sub_order: Optional[np.ndarray] = None  # reverse-subject index

    # -- bulk load -----------------------------------------------------------

    def bulk_load_ids(self, cols: Dict[str, np.ndarray]) -> None:
        """Adopt pre-interned id columns as the base segment (append).

        ``cols`` maps every name in ``COLS`` to an int32 array of equal
        length; ids MUST come from this store's ``vocab``.  One version
        bump for the whole load; the change log is reset (readers holding
        an older cursor get the None sentinel and full-rescan, which for
        engines lands on the ``export_columns`` fast path).
        """
        n = len(cols["ns"])
        with self._lock:
            if self._rows:
                raise ValueError(
                    "bulk_load_ids must precede row-wise writes"
                )
            base = {
                c: np.ascontiguousarray(cols[c], np.int32)
                for c in self.COLS
            }
            if self._b_n:
                base = {
                    c: np.concatenate([self._b[c], base[c]])
                    for c in self.COLS
                }
            self._b = base
            self._b_n = len(base["ns"])
            self._b_alive = np.ones(self._b_n, bool)
            self._next_seq = self._b_n
            self._fwd_order = self._fwd_keys = self._sub_order = None
            # advance past every pre-load log entry AND the loaded base
            # rows, so every cursor issued before this load falls behind
            # _log_start and forces the full-rescan/export_columns path
            # (advancing by n alone would let a cursor taken after
            # write-then-delete churn read an empty delta and miss the
            # whole bulk-loaded segment)
            self._log_start += len(self._log) + n
            self._log.clear()
            self._bump()

    def export_columns(self):
        """(columns dict, alive bool[n], tail tuples, head) for zero-copy
        engine adoption (engine/delta.TupleColumns.from_arrays).  All four
        read under ONE lock so a concurrent write cannot slip between the
        column view and the change-log cursor (it would double-apply when
        the engine later drains ``changes_since(head)``)."""
        with self._lock:
            return (
                {c: self._b[c] for c in self.COLS},
                self._b_alive,
                list(self._rows.values()),
                self._log_start + len(self._log),
            )

    # -- decode --------------------------------------------------------------

    def _strings(self, space: str) -> List[str]:
        tab = self._dec.get(space)
        interner = getattr(self.vocab, space)
        if tab is None or len(tab) != len(interner):
            tab = interner.strings()
            self._dec[space] = tab
        return tab

    def _materialize(self, i: int) -> RelationTuple:
        b = self._b
        nss = self._strings("namespaces")
        objs = self._strings("objects")
        rels = self._strings("relations")
        if b["is_set"][i]:
            subject = SubjectSet(
                namespace=nss[b["s_ns"][i]],
                object=objs[b["s_obj"][i]],
                relation=rels[b["s_rel"][i]],
            )
        else:
            uid = self._strings("subjects")[b["subj"][i]]
            subject = SubjectID(id=uid[3:])  # strip "id:" (unique_id form)
        return RelationTuple(
            namespace=nss[b["ns"][i]],
            object=objs[b["obj"][i]],
            relation=rels[b["rel"][i]],
            subject=subject,
        )

    # -- base-segment query machinery ---------------------------------------

    def _fwd(self) -> Tuple[np.ndarray, np.ndarray]:
        """Sorted (ns, obj, rel) forward index over base rows: one int64
        key per row, argsorted — range lookup by searchsorted."""
        if self._fwd_keys is None:
            b = self._b
            key = (
                (b["ns"].astype(np.int64) << 42)
                | (b["obj"].astype(np.int64) << 14)
                | b["rel"].astype(np.int64)
            )
            self._fwd_order = np.argsort(key, kind="stable")
            self._fwd_keys = key[self._fwd_order]
        return self._fwd_keys, self._fwd_order

    def _base_candidates(self, query: Optional[RelationQuery]) -> np.ndarray:
        """Base row indices possibly matching ``query``, ascending."""
        if self._b_n == 0:
            return np.zeros(0, np.int64)
        b = self._b
        if query is None:
            return np.flatnonzero(self._b_alive)
        v = self.vocab
        full = (
            query.namespace is not None
            and query.object is not None
            and query.relation is not None
        )
        if full:
            ns = v.namespaces.lookup(query.namespace)
            obj = v.objects.lookup(query.object)
            rel = v.relations.lookup(query.relation)
            if -1 in (ns, obj, rel):
                return np.zeros(0, np.int64)
            keys, order = self._fwd()
            want = (int(ns) << 42) | (int(obj) << 14) | int(rel)
            lo = np.searchsorted(keys, want, side="left")
            hi = np.searchsorted(keys, want, side="right")
            rows = np.sort(order[lo:hi])
        else:
            mask = self._b_alive.copy()
            if query.namespace is not None:
                i = v.namespaces.lookup(query.namespace)
                mask &= b["ns"] == i
            if query.object is not None:
                i = v.objects.lookup(query.object)
                mask &= b["obj"] == i
            if query.relation is not None:
                i = v.relations.lookup(query.relation)
                mask &= b["rel"] == i
            subject = query.subject()
            if subject is not None:
                i = v.subjects.lookup(subject.unique_id())
                mask &= b["subj"] == i
            return np.flatnonzero(mask)
        subject = query.subject()
        out = rows[self._b_alive[rows]]
        if subject is not None:
            i = v.subjects.lookup(subject.unique_id())
            if i < 0:
                return np.zeros(0, np.int64)
            out = out[b["subj"][out] == i]
        return out

    # -- Manager surface (base + inherited tail) ----------------------------

    def get_relation_tuples(
        self,
        query: Optional[RelationQuery] = None,
        *,
        page_token: str = "",
        page_size: int = 0,
    ) -> Tuple[List[RelationTuple], str]:
        if page_size <= 0:
            page_size = DEFAULT_PAGE_SIZE
        after = -1
        if page_token:
            try:
                after = int(page_token)
            except ValueError:
                from ketotpu.storage.memory import ErrMalformedPageToken

                raise ErrMalformedPageToken() from None
        with self._lock:
            out: List[Tuple[int, RelationTuple]] = []
            rows = self._base_candidates(query)
            if after >= 0:
                rows = rows[rows > after]
            rows = rows[: page_size + 1]  # never materialize a full scan
            for i in rows.tolist():
                out.append((i, self._materialize(i)))
                if len(out) > page_size:
                    break
            if len(out) <= page_size:
                for seq in self._candidates(query):
                    if seq <= after:
                        continue
                    t = self._rows.get(seq)
                    if t is not None and _matches(t, query):
                        out.append((seq, t))
                        if len(out) > page_size:
                            break
            if len(out) > page_size:
                page = out[:page_size]
                return [t for _, t in page], str(page[-1][0])
            return [t for _, t in out], ""

    def exists_relation_tuples(
        self, query: Optional[RelationQuery] = None
    ) -> bool:
        with self._lock:
            if len(self._base_candidates(query)):
                return True
        return super().exists_relation_tuples(query)

    def __len__(self) -> int:
        return int(self._b_alive.sum()) + len(self._rows)

    def all_tuples(self) -> List[RelationTuple]:
        with self._lock:
            base = [
                self._materialize(i)
                for i in np.flatnonzero(self._b_alive).tolist()
            ]
            return base + list(self._rows.values())

    def tuples_and_head(self) -> Tuple[List[RelationTuple], int]:
        with self._lock:
            return self.all_tuples(), self._log_start + len(self._log)

    # -- writes --------------------------------------------------------------

    def transact_relation_tuples(
        self,
        insert: Iterable[RelationTuple] = (),
        delete: Iterable[RelationTuple] = (),
    ) -> None:
        insert, delete = list(insert), list(delete)
        for t in insert:
            if t.subject is not None:  # nil subject: typed error below
                self.vocab.intern_tuple(t)  # keep ids available for encode
        with self._lock:
            # deletes may target base rows: handle those here, the rest
            # (incl. inserts) via the inherited row machinery
            base_deletes = []
            for t in delete:
                base_deletes.extend(self._base_rows_of(t))
            super().transact_relation_tuples(insert=insert, delete=delete)
            killed = False
            for i in base_deletes:
                if self._b_alive[i]:
                    self._b_alive[i] = False
                    self._log_locked(-1, self._materialize(i))
                    killed = True
            if killed and not insert:
                self._bump()

    def _base_rows_of(self, t: RelationTuple) -> List[int]:
        v = self.vocab
        ids = (
            v.namespaces.lookup(t.namespace),
            v.objects.lookup(t.object),
            v.relations.lookup(t.relation),
        )
        if -1 in ids:
            return []
        keys, order = self._fwd()
        want = (int(ids[0]) << 42) | (int(ids[1]) << 14) | int(ids[2])
        lo = np.searchsorted(keys, want, side="left")
        hi = np.searchsorted(keys, want, side="right")
        rows = np.sort(order[lo:hi])
        sid = v.subjects.lookup(t.subject.unique_id())
        if sid < 0:
            return []
        rows = rows[
            self._b_alive[rows] & (self._b["subj"][rows] == sid)
        ]
        return rows.tolist()

    def delete_all_relation_tuples(
        self, query: Optional[RelationQuery] = None
    ) -> int:
        with self._lock:
            rows = self._base_candidates(query)
            for i in rows.tolist():
                self._b_alive[i] = False
                self._log_locked(-1, self._materialize(i))
            n_tail = super().delete_all_relation_tuples(query)
            if len(rows) and not n_tail:
                self._bump()
            return int(len(rows)) + n_tail
