"""In-memory relation-tuple store.

Implements the Manager contract of the reference persister
(`internal/persistence/sql/relationtuples.go:207-287`): filtered reads with
opaque-token pagination, existence probes, transactional insert+delete, and
delete-by-query — over an ordered in-memory map with secondary indexes instead
of SQL.  Duplicate tuples are allowed, as in the reference (every insert is a
fresh row keyed by a new id, relationtuples.go:112-115).

The store versions itself: every committed write bumps ``version`` and fires
registered change listeners.  Snapshot projection (CSR for the TPU engine)
keys off that version.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ketotpu.api.types import (
    BadRequestError,
    RelationQuery,
    RelationTuple,
)

DEFAULT_PAGE_SIZE = 100


def ErrMalformedPageToken() -> BadRequestError:
    return BadRequestError("malformed page token")


class InMemoryTupleStore:
    """Ordered tuple store with by-userset and by-subject indexes."""

    def __init__(self):
        self._lock = threading.RLock()
        self._rows: Dict[int, RelationTuple] = {}  # seq -> tuple, insertion order
        self._next_seq = 0
        # (namespace, object, relation) -> [seq]; the forward index backing
        # expand / subject-set traversal (the reference's
        # idx_relation_tuples_full partial indexes).
        self._by_userset: Dict[Tuple[str, str, str], List[int]] = {}
        # subject unique_id -> [seq]; the reverse-subject index.
        self._by_subject: Dict[str, List[int]] = {}
        self.version = 0
        self._listeners: List[Callable[[int], None]] = []
        # append-only change log for incremental snapshot projection
        # (SURVEY §7 step 8): entries are (+1|-1, tuple) effective mutations.
        # Bounded: readers that fall behind log_start must full-rebuild.
        self._log: List[Tuple[int, RelationTuple]] = []
        self._log_start = 0  # index of _log[0] in the all-time sequence
        self._log_cap = 65536
        # overflow surfacing (keto_changelog_overflow_total): the registry
        # installs a hook(n_evicted, first_of_episode); an "episode" runs
        # from the first eviction until a lagging reader actually observes
        # the gap (changes_since -> None) and rebuilds.
        self.overflow_hook: Optional[Callable[[int, bool], None]] = None
        self.overflow_evictions = 0
        self._overflow_episode = False

    def with_network(self, nid: str):
        """A network-scoped handle over THIS store — the in-memory analog
        of opening a second :class:`SQLiteTupleStore` with a different
        ``network_id`` over the same database file.  Rows are scoped by a
        tenant prefix on the namespace column, the changelog stays global
        (nid-filtered slices, global head), and the view keeps its own
        per-nid version counter — the same contract the SQL stores'
        ``nid`` column provides (tests/test_tenancy.py gates the parity).
        """
        from ketotpu.tenancy.store import TenantStoreView

        return TenantStoreView(self, nid)

    # -- change notification -------------------------------------------------

    def on_change(self, fn: Callable[[int], None]) -> None:
        self._listeners.append(fn)

    def _bump(self) -> None:
        self.version += 1
        for fn in self._listeners:
            fn(self.version)

    # -- reads ---------------------------------------------------------------

    def get_relation_tuples(
        self,
        query: Optional[RelationQuery] = None,
        *,
        page_token: str = "",
        page_size: int = 0,
    ) -> Tuple[List[RelationTuple], str]:
        """Return (tuples, next_page_token); empty token means last page."""
        if page_size <= 0:
            page_size = DEFAULT_PAGE_SIZE
        after = -1
        if page_token:
            try:
                after = int(page_token)
            except ValueError:
                raise ErrMalformedPageToken() from None

        with self._lock:
            out: List[Tuple[int, RelationTuple]] = []
            for seq in self._candidates(query):
                if seq <= after:
                    continue
                t = self._rows.get(seq)
                if t is not None and _matches(t, query):
                    out.append((seq, t))
                    if len(out) > page_size:
                        # one overflow row fetched: a next page exists
                        page = out[:page_size]
                        return [t for _, t in page], str(page[-1][0])
        return [t for _, t in out], ""

    def _candidates(self, query: Optional[RelationQuery]) -> Iterable[int]:
        """Pick the most selective index for the query; always sorted by seq."""
        if query is not None and query.namespace is not None and query.object is not None \
                and query.relation is not None:
            return list(self._by_userset.get(
                (query.namespace, query.object, query.relation), ()))
        if query is not None and query.subject() is not None:
            return list(self._by_subject.get(query.subject().unique_id(), ()))
        return list(self._rows.keys())

    def exists_relation_tuples(self, query: Optional[RelationQuery] = None) -> bool:
        with self._lock:
            return any(_matches(self._rows[s], query) for s in self._candidates(query))

    def __len__(self) -> int:
        return len(self._rows)

    def all_tuples(self) -> List[RelationTuple]:
        with self._lock:
            return list(self._rows.values())

    def tuples_and_head(self) -> Tuple[List[RelationTuple], int]:
        """All tuples plus the log head, read atomically — a snapshot
        builder that seeds from the scan and later drains `changes_since`
        from the returned head cannot miss a concurrent write."""
        with self._lock:
            return list(self._rows.values()), self._log_start + len(self._log)

    def version_and_head(self) -> Tuple[int, int]:
        """(version, log head) in one lock window.  Snaptoken minting needs
        the pair atomic: a write landing between two separate reads would
        mint a token whose cursor includes entries of a version the token
        does not claim — harmless for freshness, wrong for the exactness a
        replicated follower must preserve across a takeover."""
        with self._lock:
            return self.version, self._log_start + len(self._log)

    def replica_scan(self) -> Tuple[List[RelationTuple], int, int]:
        """(tuples, head, version) in one lock window: the bootstrap scan a
        warm-standby follower seeds its replica from."""
        with self._lock:
            head = self._log_start + len(self._log)
            return list(self._rows.values()), head, self.version

    # -- writes --------------------------------------------------------------

    def write_relation_tuples(self, *tuples: RelationTuple) -> None:
        self.transact_relation_tuples(insert=tuples, delete=())

    def delete_relation_tuples(self, *tuples: RelationTuple) -> None:
        self.transact_relation_tuples(insert=(), delete=tuples)

    def transact_relation_tuples(
        self,
        insert: Iterable[RelationTuple] = (),
        delete: Iterable[RelationTuple] = (),
    ) -> None:
        """Apply inserts then deletes atomically (transact_server semantics:
        sql/relationtuples.go:277-287)."""
        insert, delete = list(insert), list(delete)
        for t in insert:
            if t.subject is None:
                raise BadRequestError("subject is not allowed to be nil")
        with self._lock:
            for t in insert:
                self._insert_locked(t)
            n_deleted = 0
            for t in delete:
                n_deleted += self._delete_exact_locked(t)
            if insert or n_deleted:
                self._bump()

    # -- replication (warm-standby follower) ---------------------------------

    def adopt_replica(
        self,
        tuples: Iterable[RelationTuple],
        head: int,
        version: int,
        log: Iterable[Tuple[int, RelationTuple]] = (),
        log_start: Optional[int] = None,
    ) -> None:
        """Install a leader's full row scan as this store's state, anchored
        at the LEADER'S changelog coordinates.  ``log`` is the leader's tail
        ``[log_start, head)`` so an engine whose base snapshot sits at
        ``log_start`` can drain forward through ``changes_since`` exactly as
        it would on the leader.  From here on, ``apply_replicated`` batches
        keep positions and versions identical to the leader's — which is
        what makes every leader-minted snaptoken satisfiable on this
        replica after a takeover."""
        tuples = list(tuples)
        log = list(log)
        if log_start is None:
            log_start = head - len(log)
        if log_start + len(log) != head:
            raise ValueError(
                f"replica log [{log_start}, {log_start + len(log)}) does "
                f"not end at the declared head {head}"
            )
        with self._lock:
            self._rows.clear()
            self._by_userset.clear()
            self._by_subject.clear()
            self._next_seq = 0
            for t in tuples:
                seq = self._next_seq
                self._next_seq += 1
                self._rows[seq] = t
                self._by_userset.setdefault(
                    (t.namespace, t.object, t.relation), []
                ).append(seq)
                self._by_subject.setdefault(
                    t.subject.unique_id(), []
                ).append(seq)
            self._log = log
            self._log_start = log_start
            self._overflow_episode = False
            self.version = version

    def apply_replicated(
        self,
        entries: Iterable[Tuple[int, RelationTuple]],
        head: int,
        version: int,
    ) -> None:
        """Apply a tailed changelog batch from the leader.  Each entry is
        one EFFECTIVE row mutation (exactly what ``_log_locked`` recorded on
        the leader), so a ``-1`` removes exactly one matching row; applying
        the batch grows this log by ``len(entries)``, landing the head at
        the leader's — asserted, because silent coordinate drift would
        desync every snaptoken cursor minted afterward."""
        entries = list(entries)
        with self._lock:
            for op, t in entries:
                if op > 0:
                    self._insert_locked(t)
                else:
                    key = (t.namespace, t.object, t.relation)
                    for seq in list(self._by_userset.get(key, ())):
                        if self._rows[seq] == t:
                            self._remove_row_locked(seq)
                            break
            my_head = self._log_start + len(self._log)
            if my_head != head:
                raise ValueError(
                    f"replica head {my_head} diverged from leader head "
                    f"{head} after applying {len(entries)} entries"
                )
            self.version = version
            for fn in self._listeners:
                fn(self.version)

    def delete_all_relation_tuples(self, query: Optional[RelationQuery] = None) -> int:
        with self._lock:
            doomed = [s for s in self._candidates(query) if _matches(self._rows[s], query)]
            for seq in doomed:
                self._remove_row_locked(seq)
            if doomed:
                self._bump()
            return len(doomed)

    # -- internals -----------------------------------------------------------

    def _insert_locked(self, t: RelationTuple) -> None:
        seq = self._next_seq
        self._next_seq += 1
        self._rows[seq] = t
        self._by_userset.setdefault((t.namespace, t.object, t.relation), []).append(seq)
        self._by_subject.setdefault(t.subject.unique_id(), []).append(seq)
        self._log_locked(1, t)

    def _delete_exact_locked(self, t: RelationTuple) -> int:
        key = (t.namespace, t.object, t.relation)
        n = 0
        for seq in list(self._by_userset.get(key, ())):
            if self._rows[seq] == t:
                self._remove_row_locked(seq)
                n += 1
        return n

    def _remove_row_locked(self, seq: int) -> None:
        t = self._rows.pop(seq)
        key = (t.namespace, t.object, t.relation)
        self._by_userset[key].remove(seq)
        if not self._by_userset[key]:
            del self._by_userset[key]
        sid = t.subject.unique_id()
        self._by_subject[sid].remove(seq)
        if not self._by_subject[sid]:
            del self._by_subject[sid]
        self._log_locked(-1, t)

    # -- change log ----------------------------------------------------------

    def _log_locked(self, op: int, t: RelationTuple) -> None:
        self._log.append((op, t))
        if len(self._log) > self._log_cap:
            drop = len(self._log) - self._log_cap
            del self._log[:drop]
            self._log_start += drop
            first = not self._overflow_episode
            self._overflow_episode = True
            self.overflow_evictions += drop
            if self.overflow_hook is not None:
                self.overflow_hook(drop, first)

    @property
    def log_head(self) -> int:
        """All-time index just past the newest change-log entry."""
        with self._lock:
            return self._log_start + len(self._log)

    def changes_since(self, cursor: int):
        """Effective mutations [(op, tuple)] since ``cursor`` (a previous
        ``log_head`` value), plus the new cursor.  Returns ``None`` for the
        entries when the cursor predates the bounded log (reader must
        rebuild from a full scan)."""
        with self._lock:
            head = self._log_start + len(self._log)
            if cursor < self._log_start:
                # the lagging reader has seen the gap and will rebuild:
                # the overflow episode is over (the next eviction logs anew)
                self._overflow_episode = False
                return None, head
            return list(self._log[cursor - self._log_start:]), head

    def changes_since_versioned(self, cursor: int):
        """``changes_since`` plus the store version, all in one lock window
        (the replication tail op ships the triple so the follower's replica
        lands on exactly the leader's (head, version) pair)."""
        with self._lock:
            entries, head = self.changes_since(cursor)
            return entries, head, self.version


def _matches(t: RelationTuple, q: Optional[RelationQuery]) -> bool:
    if q is None:
        return True
    if q.namespace is not None and t.namespace != q.namespace:
        return False
    if q.object is not None and t.object != q.object:
        return False
    if q.relation is not None and t.relation != q.relation:
        return False
    subject = q.subject()
    if subject is not None and t.subject != subject:
        return False
    return True
