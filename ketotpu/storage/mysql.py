"""Durable relation-tuple store on MySQL.

The third dialect of the SQL persister matrix (the reference runs ONE
persister over sqlite / postgres / mysql / cockroach selected by DSN,
`internal/persistence/sql/full_test.go:32`,
`internal/x/dbx/dsn_testutils.go:106-160`, with per-dialect migration
variants under `internal/persistence/sql/migrations/`).  Like
`storage/postgres.py`, this subclasses `SQLiteTupleStore` and inherits
every query, pagination rule, change-log and nid-isolation behavior
verbatim — only the connection adapter (`_open`) and the dialect DDL
(`BASE_MIGRATIONS`) differ.

MySQL-specific translation, all at execute time in `_MyConn`:

* ``?`` placeholders → ``%s``;
* ``BEGIN DEFERRED/IMMEDIATE`` → ``BEGIN`` (server-side transactions on
  an autocommit connection, as the store body already issues);
* ``INSERT OR IGNORE`` → ``INSERT IGNORE``;
* sqlite/postgres upsert (``ON CONFLICT (..) DO UPDATE SET value =
  excluded.value``) → ``ON DUPLICATE KEY UPDATE value = VALUES(value)``;
* the lowercase ``key`` column of ``keto_meta`` is a reserved word in
  MySQL → backtick-quoted (case-sensitive token replace; the uppercase
  ``KEY`` in PRIMARY KEY / DUPLICATE KEY is untouched);
* ``PRAGMA`` → no-op.

DDL differences: AUTO_INCREMENT keys, VARCHAR(255) for indexed columns
(MySQL cannot index unbounded TEXT), no partial indexes (plain indexes
instead — correctness is unaffected, they just include the NULL rows).

Drivers: `pymysql`, `MySQLdb` (mysqlclient) or `mysql.connector`,
imported lazily — none ships in this image, so construction raises a
clear error without one and the conformance leg in tests/test_storage.py
is DSN-gated via ``KETO_TEST_MYSQL_DSN`` (the CI workflow provides a
mysql service container), exactly like the Postgres leg.
"""

from __future__ import annotations

import re
from typing import Iterable, List, Tuple

from ketotpu.storage.sqlite import DEFAULT_NID, SQLiteTupleStore

MY_MIGRATIONS: List[Tuple[str, List[str], List[str]]] = [
    # MySQL DDL implicitly commits (no transactional migrations), so every
    # statement must be IDEMPOTENT: a crash between a CREATE and the
    # keto_migrations bookkeeping row must not brick the next migrate_up.
    # Indexes are declared inline (CREATE INDEX has no IF NOT EXISTS in
    # MySQL; inline declarations ride the table's IF NOT EXISTS).
    (
        "20240101000001_relation_tuples",
        [
            """CREATE TABLE IF NOT EXISTS keto_relation_tuples (
                seq BIGINT PRIMARY KEY AUTO_INCREMENT,
                nid VARCHAR(255) NOT NULL,
                namespace VARCHAR(255) NOT NULL,
                object VARCHAR(255) NOT NULL,
                relation VARCHAR(255) NOT NULL,
                subject_id VARCHAR(255),
                subject_set_namespace VARCHAR(255),
                subject_set_object VARCHAR(255),
                subject_set_relation VARCHAR(255),
                commit_time DOUBLE NOT NULL,
                INDEX keto_rt_userset (nid, namespace, object, relation),
                INDEX keto_rt_subject_id (nid, subject_id),
                INDEX keto_rt_subject_set (nid, subject_set_namespace,
                    subject_set_object, subject_set_relation)
            )""",
        ],
        ["DROP TABLE IF EXISTS keto_relation_tuples"],
    ),
    (
        "20240101000002_change_log",
        [
            """CREATE TABLE IF NOT EXISTS keto_change_log (
                id BIGINT PRIMARY KEY AUTO_INCREMENT,
                nid VARCHAR(255) NOT NULL,
                op INTEGER NOT NULL,
                namespace VARCHAR(255) NOT NULL,
                object VARCHAR(255) NOT NULL,
                relation VARCHAR(255) NOT NULL,
                subject_id VARCHAR(255),
                subject_set_namespace VARCHAR(255),
                subject_set_object VARCHAR(255),
                subject_set_relation VARCHAR(255),
                INDEX keto_cl_nid (nid, id)
            )""",
        ],
        ["DROP TABLE IF EXISTS keto_change_log"],
    ),
    (
        "20240101000003_meta",
        [
            """CREATE TABLE IF NOT EXISTS keto_meta (
                nid VARCHAR(255) NOT NULL,
                `key` VARCHAR(255) NOT NULL,
                value TEXT NOT NULL,
                PRIMARY KEY (nid, `key`)
            )""",
        ],
        ["DROP TABLE IF EXISTS keto_meta"],
    ),
    (
        "20240101000004_uuid_mappings",
        [
            """CREATE TABLE IF NOT EXISTS keto_uuid_mappings (
                id VARCHAR(255) PRIMARY KEY,
                string_representation TEXT NOT NULL
            )""",
        ],
        ["DROP TABLE IF EXISTS keto_uuid_mappings"],
    ),
]

# sqlite/postgres upsert tail the shared body emits (sqlite.py
# _bump_locked / log-floor trim) → MySQL's form.  The conflict target is
# always the PK, so ON DUPLICATE KEY is the exact equivalent.
_UPSERT = re.compile(
    r"ON CONFLICT \([^)]*\)\s*DO UPDATE SET value = excluded\.value"
)
# the keto_meta `key` column: lowercase token only (PRIMARY KEY /
# DUPLICATE KEY are uppercase in every emitted statement)
_KEY = re.compile(r"(?<![A-Za-z_`])key(?![A-Za-z_`])")


class _EmptyCursor:
    def fetchall(self):
        return []

    def fetchone(self):
        return None


class _MyConn:
    """DBAPI adapter exposing sqlite3's ``conn.execute(sql, params)``
    shape over a MySQL driver connection (see module docstring)."""

    def __init__(self, conn):
        self._c = conn
        # pymysql: autocommit(bool) method; mysql.connector / MySQLdb:
        # autocommit attribute or method — normalize to ON
        try:
            conn.autocommit(True)
        except TypeError:
            conn.autocommit = True

    def execute(self, sql: str, params=()):
        s = sql.lstrip()
        if s.startswith("PRAGMA"):
            return _EmptyCursor()
        if s.startswith("BEGIN"):
            s = "BEGIN"
        elif s.startswith("INSERT OR IGNORE"):
            s = s.replace("INSERT OR IGNORE", "INSERT IGNORE", 1)
        elif "version TEXT PRIMARY KEY" in s:
            # the store's shared keto_migrations DDL: MySQL cannot key an
            # unbounded TEXT column
            s = s.replace(
                "version TEXT PRIMARY KEY", "version VARCHAR(255) PRIMARY KEY"
            )
        s = _UPSERT.sub("ON DUPLICATE KEY UPDATE value = VALUES(value)", s)
        s = _KEY.sub("`key`", s)
        cur = self._c.cursor()
        cur.execute(s.replace("?", "%s"), tuple(params))
        return cur

    def close(self):
        self._c.close()


def _connect_my(dsn: str):
    from urllib.parse import unquote, urlparse

    u = urlparse(dsn)
    kw = dict(
        user=unquote(u.username or "root"),
        password=unquote(u.password or ""),
        host=u.hostname or "localhost",
        port=u.port or 3306,
        database=(u.path or "/mysql").lstrip("/"),
    )
    try:
        import pymysql

        return pymysql.connect(**kw)
    except ImportError:
        pass
    try:
        import MySQLdb

        kw["passwd"] = kw.pop("password")
        kw["db"] = kw.pop("database")
        return MySQLdb.connect(**kw)
    except ImportError:
        pass
    try:
        import mysql.connector

        return mysql.connector.connect(**kw)
    except ImportError:
        raise RuntimeError(
            "MySQLTupleStore needs pymysql, mysqlclient or mysql-connector;"
            " none is installed (set a sqlite:// or memory dsn, or install"
            " a driver)"
        )


class MySQLTupleStore(SQLiteTupleStore):
    """Manager-contract store on MySQL; one network id per handle.

    Same conformance surface as the in-memory / SQLite / Postgres /
    columnar backends (tests/test_storage.py); schema migrations are the
    MySQL dialect of the same versioned set.
    """

    BASE_MIGRATIONS = MY_MIGRATIONS

    def __init__(
        self,
        dsn: str,
        *,
        network_id: str = DEFAULT_NID,
        auto_migrate: bool = None,
        log_cap: int = 65536,
        extra_migrations: Iterable[Tuple[str, List[str], List[str]]] = (),
        tracer=None,
    ):
        super().__init__(
            dsn,
            network_id=network_id,
            auto_migrate=auto_migrate,
            log_cap=log_cap,
            extra_migrations=extra_migrations,
            tracer=tracer,
        )

    def _open(self, path: str):
        return _MyConn(_connect_my(path))

    @staticmethod
    def _default_auto_migrate(path: str) -> bool:
        # a real server is never ephemeral: migrate explicitly
        return False
