"""Namespace managers: static (in-config) and OPL-file backed.

Parity with the reference's three manager flavors
(`internal/driver/config/provider.go:315-342`): a literal namespace list, an
OPL file (re-parsed on change, keeping the previous value on parse errors,
`namespace_watcher.go:71-89`), and the lookup special cases of
`internal/namespace/definitions.go:37-62`.
"""

from __future__ import annotations

import os
import threading
from typing import Iterable, List, Optional, Protocol

from ketotpu.api.types import BadRequestError, NotFoundError
from ketotpu.opl.ast import Namespace, Relation
from ketotpu.opl.parser import ParseError, parse


class NamespaceManager(Protocol):
    def get_namespace(self, name: str) -> Namespace: ...

    def namespaces(self) -> List[Namespace]: ...


class StaticNamespaceManager:
    """Fixed namespace list (config-literal flavor).  Entries without
    relations model legacy name-only namespaces."""

    def __init__(self, namespaces: Iterable[Namespace]):
        self._namespaces = list(namespaces)

    def get_namespace(self, name: str) -> Namespace:
        for n in self._namespaces:
            if n.name == name:
                return n
        raise NotFoundError(f"namespace {name!r} was not found")

    def namespaces(self) -> List[Namespace]:
        return list(self._namespaces)


class OPLFileNamespaceManager:
    """OPL-file-backed manager with mtime-based hot reload.

    On a failed re-parse the previous namespaces stay active (rollback
    semantics of the reference's OPL config watcher).
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._namespaces: List[Namespace] = []
        self._mtime: Optional[float] = None
        self._last_errors: List[ParseError] = []
        try:
            self._mtime = os.stat(path).st_mtime
        except OSError:
            pass
        self._load(initial=True)

    def _load(self, *, initial: bool = False) -> None:
        with open(self.path, "r") as f:
            source = f.read()
        namespaces, errors = parse(source)
        if errors:
            self._last_errors = errors
            if initial:
                raise BadRequestError(
                    "parsing OPL file failed: "
                    + "; ".join(e.msg for e in errors)
                )
            return  # rollback: keep previous namespaces
        self._namespaces = namespaces
        self._last_errors = []

    def _maybe_reload(self) -> None:
        try:
            mtime = os.stat(self.path).st_mtime
        except OSError:
            return
        with self._lock:
            if self._mtime is None or mtime != self._mtime:
                try:
                    self._load()
                except OSError:
                    # Transient read failure (e.g. write-temp-then-rename
                    # window): keep previous namespaces, retry on next call.
                    return
                self._mtime = mtime

    def get_namespace(self, name: str) -> Namespace:
        self._maybe_reload()
        for n in self._namespaces:
            if n.name == name:
                return n
        raise NotFoundError(f"namespace {name!r} was not found")

    def namespaces(self) -> List[Namespace]:
        self._maybe_reload()
        return list(self._namespaces)


class DirectoryNamespaceManager:
    """Legacy namespace-directory watcher (`namespace_watcher.go:54`):
    one yaml/json/toml file per namespace (the pre-OPL config format,
    e.g. ``{"id": 0, "name": "videos"}`` — cat-videos-example shape),
    re-scanned on directory or file mtime change.  Files that fail to
    parse are skipped with rollback-to-previous semantics per file, like
    the reference's per-file watcher events; a failed parse still records
    the file's mtime so the broken content is not re-parsed until it
    changes (namespaces()/get_namespace() sit on the check hot path via
    the engine's config fingerprint)."""

    _EXTS = (".yml", ".yaml", ".json", ".toml")

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._namespaces: dict = {}  # filename -> Namespace
        self._mtimes: dict = {}
        self._scan(initial=True)

    @staticmethod
    def _parse_file(fname: str):
        with open(fname, "rb") as f:
            raw = f.read()
        if fname.endswith(".json"):
            import json

            data = json.loads(raw)
        elif fname.endswith(".toml"):
            import tomllib

            data = tomllib.loads(raw.decode("utf-8"))
        else:
            import yaml

            data = yaml.safe_load(raw)
        if not isinstance(data, dict) or not data.get("name"):
            raise BadRequestError("namespace file must define 'name'")
        return Namespace(str(data["name"]))

    def _scan(self, *, initial: bool = False) -> None:
        try:
            entries = sorted(
                e for e in os.listdir(self.path)
                if e.endswith(self._EXTS)
            )
        except OSError as e:
            if initial:
                raise BadRequestError(
                    f"cannot read namespace directory {self.path!r}: {e}"
                ) from None
            return
        seen = set()
        for name in entries:
            fname = os.path.join(self.path, name)
            try:
                mtime = os.stat(fname).st_mtime
            except OSError:
                continue
            seen.add(name)
            if self._mtimes.get(name) == mtime:
                continue
            try:
                self._namespaces[name] = self._parse_file(fname)
            except Exception:  # noqa: BLE001 - per-file rollback
                pass  # keep the previous parse of this file, if any
            self._mtimes[name] = mtime
        for gone in set(self._mtimes) - seen:
            self._namespaces.pop(gone, None)
            del self._mtimes[gone]

    def get_namespace(self, name: str) -> Namespace:
        with self._lock:
            self._scan()
            for n in self._namespaces.values():
                if n.name == name:
                    return n
        raise NotFoundError(f"namespace {name!r} was not found")

    def namespaces(self) -> List[Namespace]:
        with self._lock:
            self._scan()
            return list(self._namespaces.values())


def ast_relation_for(
    manager: NamespaceManager, namespace: str, relation: str
) -> Optional[Relation]:
    """Look up the rewrite AST for (namespace, relation).

    Behavioral special cases (namespace/definitions.go:37-62):
    * empty relation -> None (not an error),
    * unknown namespace -> None ("not allowed", never "not found"),
    * namespace without relation config -> None,
    * known namespace that doesn't declare the relation -> BadRequest.
    """
    if relation == "":
        return None
    try:
        ns = manager.get_namespace(namespace)
    except Exception:
        return None
    if not ns.relations:
        return None
    rel = ns.relation(relation)
    if rel is not None:
        return rel
    raise BadRequestError(f"relation {relation!r} does not exist")
