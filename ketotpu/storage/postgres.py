"""Durable relation-tuple store on PostgreSQL.

The second dialect of the SQL persister matrix: the reference runs ONE
persister implementation over sqlite / postgres / mysql / cockroach,
selected by DSN, with a per-dialect migration set
(`internal/persistence/sql/full_test.go:32`,
`internal/x/dbx/dsn_testutils.go:106-160`,
`internal/persistence/sql/migrations/`).  This module does the same the
Python way: `PostgresTupleStore` subclasses `SQLiteTupleStore` and
inherits every query, pagination rule, change-log and nid-isolation
behavior verbatim — only the connection (`_open`) and the dialect DDL
(`BASE_MIGRATIONS`) differ.  A thin DBAPI adapter translates the two
placeholder styles (`?` → `%s`) and the few SQLite-only statement forms
(`BEGIN IMMEDIATE`, `INSERT OR IGNORE`, `PRAGMA`) at execute time, so
the shared store body stays single-sourced.

Drivers: `psycopg2` or `pg8000`, imported lazily — neither ships in
this image, so construction raises a clear error without one and the
conformance suite (tests/test_storage.py) is DSN-gated exactly like the
reference's: set ``KETO_TEST_PG_DSN`` to run it against a live server
(the CI workflow provides a postgres service container).
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

from ketotpu.storage.sqlite import DEFAULT_NID, SQLiteTupleStore

#: dialect DDL: identical schema to sqlite.MIGRATIONS with Postgres
#: auto-increment forms (the reference keeps per-dialect variants of
#: each migration the same way, e.g.
#: 20210623162417000001_relationtuple.postgres.up.sql)
PG_MIGRATIONS: List[Tuple[str, List[str], List[str]]] = [
    (
        "20240101000001_relation_tuples",
        [
            """CREATE TABLE keto_relation_tuples (
                seq BIGSERIAL PRIMARY KEY,
                nid TEXT NOT NULL,
                namespace TEXT NOT NULL,
                object TEXT NOT NULL,
                relation TEXT NOT NULL,
                subject_id TEXT,
                subject_set_namespace TEXT,
                subject_set_object TEXT,
                subject_set_relation TEXT,
                commit_time REAL NOT NULL
            )""",
            """CREATE INDEX keto_rt_userset
               ON keto_relation_tuples (nid, namespace, object, relation)""",
            """CREATE INDEX keto_rt_subject_id
               ON keto_relation_tuples (nid, subject_id)
               WHERE subject_id IS NOT NULL""",
            """CREATE INDEX keto_rt_subject_set
               ON keto_relation_tuples (nid, subject_set_namespace,
                   subject_set_object, subject_set_relation)
               WHERE subject_set_namespace IS NOT NULL""",
        ],
        ["DROP TABLE keto_relation_tuples"],
    ),
    (
        "20240101000002_change_log",
        [
            """CREATE TABLE keto_change_log (
                id BIGSERIAL PRIMARY KEY,
                nid TEXT NOT NULL,
                op INTEGER NOT NULL,
                namespace TEXT NOT NULL,
                object TEXT NOT NULL,
                relation TEXT NOT NULL,
                subject_id TEXT,
                subject_set_namespace TEXT,
                subject_set_object TEXT,
                subject_set_relation TEXT
            )""",
            """CREATE INDEX keto_cl_nid ON keto_change_log (nid, id)""",
        ],
        ["DROP TABLE keto_change_log"],
    ),
    (
        "20240101000003_meta",
        [
            """CREATE TABLE keto_meta (
                nid TEXT NOT NULL,
                key TEXT NOT NULL,
                value TEXT NOT NULL,
                PRIMARY KEY (nid, key)
            )""",
        ],
        ["DROP TABLE keto_meta"],
    ),
    (
        "20240101000004_uuid_mappings",
        [
            """CREATE TABLE keto_uuid_mappings (
                id TEXT PRIMARY KEY,
                string_representation TEXT NOT NULL
            )""",
        ],
        ["DROP TABLE keto_uuid_mappings"],
    ),
]


class _PgConn:
    """DBAPI adapter exposing sqlite3's ``conn.execute(sql, params)``
    shape over a Postgres driver connection, translating the store
    body's SQLite idioms:

    * ``?`` placeholders → ``%s`` (both supported drivers use format
      style);
    * ``BEGIN IMMEDIATE`` / ``BEGIN DEFERRED`` → plain ``BEGIN`` (the
      connection runs autocommit; transactions are the explicit
      server-side BEGIN/COMMIT the store already issues);
    * ``INSERT OR IGNORE`` → ``INSERT ... ON CONFLICT DO NOTHING``;
    * ``PRAGMA`` → no-op.
    """

    def __init__(self, conn):
        self._c = conn
        conn.autocommit = True

    def execute(self, sql: str, params=()):
        s = sql.lstrip()
        if s.startswith("PRAGMA"):
            return _EmptyCursor()
        if s.startswith("BEGIN"):
            s = "BEGIN"
        elif s.startswith("INSERT OR IGNORE"):
            s = s.replace("INSERT OR IGNORE", "INSERT", 1)
            s += " ON CONFLICT DO NOTHING"
        cur = self._c.cursor()
        cur.execute(s.replace("?", "%s"), tuple(params))
        return cur

    def close(self):
        self._c.close()


class _EmptyCursor:
    def fetchall(self):
        return []

    def fetchone(self):
        return None


def _connect_pg(dsn: str):
    try:
        import psycopg2

        return psycopg2.connect(dsn)
    except ImportError:
        pass
    try:
        import pg8000.dbapi

        # pg8000 takes keyword args; parse the URL form
        from urllib.parse import urlparse

        u = urlparse(dsn)
        conn = pg8000.dbapi.Connection(
            user=u.username or "postgres",
            password=u.password,
            host=u.hostname or "localhost",
            port=u.port or 5432,
            database=(u.path or "/postgres").lstrip("/"),
        )
        return conn
    except ImportError:
        raise RuntimeError(
            "PostgresTupleStore needs psycopg2 or pg8000; neither is "
            "installed (set a sqlite:// or memory dsn, or install a driver)"
        )


class PostgresTupleStore(SQLiteTupleStore):
    """Manager-contract store on PostgreSQL; one network id per handle.

    Same conformance surface as the in-memory / SQLite / columnar
    backends (tests/test_storage.py); schema migrations are the
    Postgres dialect of the same versioned set.
    """

    BASE_MIGRATIONS = PG_MIGRATIONS

    def __init__(
        self,
        dsn: str,
        *,
        network_id: str = DEFAULT_NID,
        auto_migrate: bool = None,
        log_cap: int = 65536,
        extra_migrations: Iterable[Tuple[str, List[str], List[str]]] = (),
        tracer=None,
    ):
        super().__init__(
            dsn,
            network_id=network_id,
            auto_migrate=auto_migrate,
            log_cap=log_cap,
            extra_migrations=extra_migrations,
            tracer=tracer,
        )

    def _open(self, path: str):
        return _PgConn(_connect_pg(path))

    @staticmethod
    def _default_auto_migrate(path: str) -> bool:
        # a real server is never ephemeral: migrate explicitly
        # (`keto-tpu migrate up`), like the reference's file-backed rule
        return False
