"""Durable relation-tuple store on SQLite (stdlib ``sqlite3``).

The durable analog of the reference's SQL persister
(`internal/persistence/sql/persister.go:54`, `relationtuples.go:207-287`):

* same row shape as `keto_relation_tuples` (migration
  `20210623162417000001_relationtuple.postgres.up.sql`): nullable
  ``subject_id`` XOR subject-set triple, forward userset index and a
  reverse-subject index;
* ``nid`` multi-tenancy on every row and every statement
  (`persister.go:91-101`) — stores opened on the same file with different
  network ids are fully isolated;
* opaque-token pagination by row sequence (`relationtuples.go:216-219`);
* versioned schema **migrations** with up/down/status
  (`internal/persistence/sql/migrations/`, `popx` MigrationBox) — the CLI
  exposes them as ``keto-tpu migrate {up,down,status}``;
* a bounded change log so the TPU engine's incremental projection
  (engine/delta.py) can drain effective mutations without rescanning —
  this is the durable replacement for Keto's read-committed visibility:
  cross-process writes surface at the next ``changes_since`` drain.

Duck-type compatible with `storage.memory.InMemoryTupleStore`; the shared
conformance suite in tests/test_storage.py runs over both backends (the
reference exports its persister suite the same way,
`manager_requirements.go:25`).
"""

from __future__ import annotations

import sqlite3
import threading
import time
from contextlib import contextmanager
from typing import Callable, Iterable, List, Optional, Tuple

from ketotpu.api.types import (
    BadRequestError,
    RelationQuery,
    RelationTuple,
    SubjectID,
    SubjectSet,
)
from ketotpu.storage.memory import DEFAULT_PAGE_SIZE, ErrMalformedPageToken

DEFAULT_NID = "default"

# -- migrations --------------------------------------------------------------
# Ordered (version, up_sql[], down_sql[]).  Mirrors the reference's
# versioned-migration discipline; new schema changes append a new entry.

MIGRATIONS: List[Tuple[str, List[str], List[str]]] = [
    (
        "20240101000001_relation_tuples",
        [
            """CREATE TABLE keto_relation_tuples (
                seq INTEGER PRIMARY KEY AUTOINCREMENT,
                nid TEXT NOT NULL,
                namespace TEXT NOT NULL,
                object TEXT NOT NULL,
                relation TEXT NOT NULL,
                subject_id TEXT,
                subject_set_namespace TEXT,
                subject_set_object TEXT,
                subject_set_relation TEXT,
                commit_time REAL NOT NULL
            )""",
            """CREATE INDEX keto_rt_userset
               ON keto_relation_tuples (nid, namespace, object, relation)""",
            """CREATE INDEX keto_rt_subject_id
               ON keto_relation_tuples (nid, subject_id)
               WHERE subject_id IS NOT NULL""",
            """CREATE INDEX keto_rt_subject_set
               ON keto_relation_tuples (nid, subject_set_namespace,
                   subject_set_object, subject_set_relation)
               WHERE subject_set_namespace IS NOT NULL""",
        ],
        ["DROP TABLE keto_relation_tuples"],
    ),
    (
        "20240101000002_change_log",
        [
            """CREATE TABLE keto_change_log (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                nid TEXT NOT NULL,
                op INTEGER NOT NULL,
                namespace TEXT NOT NULL,
                object TEXT NOT NULL,
                relation TEXT NOT NULL,
                subject_id TEXT,
                subject_set_namespace TEXT,
                subject_set_object TEXT,
                subject_set_relation TEXT
            )""",
            """CREATE INDEX keto_cl_nid ON keto_change_log (nid, id)""",
        ],
        ["DROP TABLE keto_change_log"],
    ),
    (
        "20240101000003_meta",
        [
            """CREATE TABLE keto_meta (
                nid TEXT NOT NULL,
                key TEXT NOT NULL,
                value TEXT NOT NULL,
                PRIMARY KEY (nid, key)
            )""",
        ],
        ["DROP TABLE keto_meta"],
    ),
    (
        # the reference persists every string->UUIDv5 mapping so UUID-keyed
        # reverse lookups survive restart (persistence/sql/uuid_mapping.go:
        # 35-74, migration 20220513200300000001); same two columns here.
        # No nid column: the UUIDv5 is already namespaced by network id.
        "20240101000004_uuid_mappings",
        [
            """CREATE TABLE keto_uuid_mappings (
                id TEXT PRIMARY KEY,
                string_representation TEXT NOT NULL
            )""",
        ],
        ["DROP TABLE keto_uuid_mappings"],
    ),
]


class SQLiteReverseStore:
    """Durable ReverseStore (api/uuid_map.py surface) over the store's
    keto_uuid_mappings table, with a bounded write-through cache so the
    hot mapping path rarely touches SQL."""

    CACHE_CAP = 65536

    def __init__(self, store: "SQLiteTupleStore"):
        self._s = store
        self._cache: dict = {}
        self._cache_lock = threading.Lock()

    def put(self, u, value: str) -> None:
        with self._cache_lock:
            if u in self._cache:
                return  # already persisted by us
            if len(self._cache) >= self.CACHE_CAP:
                self._cache.clear()  # reads fall back to the table
            self._cache[u] = value
        with self._s._lock:
            self._s._db.execute(
                "INSERT OR IGNORE INTO keto_uuid_mappings VALUES (?, ?)",
                (str(u), value),
            )

    def get(self, u):
        with self._cache_lock:
            v = self._cache.get(u)
        if v is not None:
            return v
        with self._s._lock:
            row = self._s._db.execute(
                "SELECT string_representation FROM keto_uuid_mappings"
                " WHERE id = ?",
                (str(u),),
            ).fetchone()
        return row[0] if row else None


class _TracedConn:
    """Connection proxy opening a ``sql-conn-query`` span per statement —
    the reference instruments at the same seam (instrumentedsql wired
    into the pop connection, `internal/driver/pop_connection.go:26-31`),
    and its queries-per-check KPI counts exactly these spans
    (`internal/check/bench_test.go:171-183`).  Dialect-independent: it
    wraps whatever `_open` returned (sqlite3 or the Postgres adapter)."""

    def __init__(self, conn, tracer):
        self._conn = conn
        self._tracer = tracer

    def execute(self, sql: str, params=()):
        with self._tracer.span("sql-conn-query", query=sql, args=params):
            return self._conn.execute(sql, params)

    def __getattr__(self, name):
        return getattr(self._conn, name)


class SQLiteTupleStore:
    """Durable Manager-contract store; one network id per handle."""

    #: the dialect's migration set (subclasses substitute their DDL)
    BASE_MIGRATIONS = MIGRATIONS

    def __init__(
        self,
        path: str = ":memory:",
        *,
        network_id: str = DEFAULT_NID,
        auto_migrate: Optional[bool] = None,
        log_cap: int = 65536,
        extra_migrations: Iterable[Tuple[str, List[str], List[str]]] = (),
        tracer=None,
    ):
        self._lock = threading.RLock()
        self.path = path
        self.nid = network_id
        # embedder migrations append after the built-ins (the reference's
        # MigrationBox merges keto + embedder migrations,
        # registry_default.go:247-273 / ketoctx WithExtraMigrations)
        self.migrations = type(self).BASE_MIGRATIONS + list(extra_migrations)
        self._log_cap = log_cap
        # trim probes walk O(log_cap) index entries; amortize them
        self._trim_interval = max(1, min(1024, log_cap // 4))
        self._writes_since_trim = 0
        # overflow surfacing, same contract as the in-memory store: the
        # registry installs hook(n_evicted, first_of_episode); an episode
        # ends when a lagging reader sees the gap (changes_since -> None)
        self.overflow_hook: Optional[Callable[[int, bool], None]] = None
        self.overflow_evictions = 0
        self._overflow_episode = False
        self._listeners: List[Callable[[int], None]] = []
        # autocommit connection; transactions are explicit (_tx) so that
        # (a) DDL participates in migration transactions and (b) multi-
        # statement reads see one WAL snapshot even across handles.
        # _open is the dialect seam: the Postgres persister overrides it
        # (and BASE_MIGRATIONS) while inheriting every query verbatim —
        # the reference runs one persister over a DSN-selected dialect
        # matrix the same way (internal/persistence/sql/full_test.go:32).
        self._db = self._open(path)
        if tracer is not None:
            self._db = _TracedConn(self._db, tracer)
        self._db.execute(
            """CREATE TABLE IF NOT EXISTS keto_migrations (
                version TEXT PRIMARY KEY, applied_at REAL NOT NULL)"""
        )
        # the reference auto-migrates only ephemeral stores
        # (registry_default.go:316-327); file-backed stores migrate
        # explicitly via `keto-tpu migrate up` unless told otherwise
        if auto_migrate is None:
            auto_migrate = self._default_auto_migrate(path)
        if auto_migrate:
            self.migrate_up()

    def _open(self, path: str):
        db = sqlite3.connect(path, check_same_thread=False, isolation_level=None)
        db.execute("PRAGMA foreign_keys=ON")
        if path != ":memory:":
            db.execute("PRAGMA journal_mode=WAL")
            db.execute("PRAGMA synchronous=NORMAL")
        return db

    @staticmethod
    def _default_auto_migrate(path: str) -> bool:
        return path == ":memory:"

    def with_network(self, nid: str):
        """A sibling handle over the SAME database scoped to ``nid``:
        rows and the version counter are per-nid (the ``nid`` column /
        per-nid keto_meta row), while the change-log id space stays
        global.  Shares the connection and lock, so it works for
        ``:memory:`` stores too; listeners are per-handle, exactly as
        with two independently opened handles over one file."""
        sib = object.__new__(type(self))
        sib.__dict__.update(self.__dict__)
        sib.nid = nid
        sib._listeners = []
        return sib

    @contextmanager
    def _tx(self, mode: str = "DEFERRED"):
        """Explicit transaction: IMMEDIATE for writes (takes the write lock
        up front), DEFERRED for consistent multi-statement reads."""
        self._db.execute(f"BEGIN {mode}")
        try:
            yield
        except BaseException:
            self._db.execute("ROLLBACK")
            raise
        else:
            self._db.execute("COMMIT")

    # -- migrations ----------------------------------------------------------

    def _applied(self) -> List[str]:
        rows = self._db.execute(
            "SELECT version FROM keto_migrations ORDER BY version"
        ).fetchall()
        return [r[0] for r in rows]

    def migration_status(self) -> List[Tuple[str, str]]:
        """[(version, 'applied'|'pending')] in order."""
        applied = set(self._applied())
        return [
            (v, "applied" if v in applied else "pending")
            for v, _, _ in self.migrations
        ]

    def migrate_up(self) -> int:
        """Apply all pending migrations; returns how many ran.  Each
        migration's DDL + bookkeeping commit atomically (SQLite DDL is
        transactional), so a crash leaves whole migrations, never halves."""
        with self._lock:
            applied = set(self._applied())
            n = 0
            for version, ups, _ in self.migrations:
                if version in applied:
                    continue
                with self._tx("IMMEDIATE"):
                    for stmt in ups:
                        self._db.execute(stmt)
                    self._db.execute(
                        "INSERT INTO keto_migrations VALUES (?, ?)",
                        (version, time.time()),
                    )
                n += 1
            return n

    def migrate_down(self, steps: int = 1) -> int:
        """Roll back the newest ``steps`` applied migrations atomically."""
        with self._lock:
            applied = self._applied()
            n = 0
            for version in reversed(applied):
                if n >= steps:
                    break
                downs = next(d for v, _, d in self.migrations if v == version)
                with self._tx("IMMEDIATE"):
                    for stmt in downs:
                        self._db.execute(stmt)
                    self._db.execute(
                        "DELETE FROM keto_migrations WHERE version = ?",
                        (version,),
                    )
                n += 1
            return n

    def _assert_migrated(self) -> None:
        if len(self._applied()) < len(self.migrations):
            raise BadRequestError(
                "database schema is not up to date: run `keto-tpu migrate up`"
            )

    # -- row codecs ----------------------------------------------------------

    @staticmethod
    def _subject_cols(t: RelationTuple) -> Tuple:
        s = t.subject
        if isinstance(s, SubjectSet):
            return (None, s.namespace, s.object, s.relation)
        return (s.id, None, None, None)

    @staticmethod
    def _decode(row) -> RelationTuple:
        ns, obj, rel, sid, ssn, sso, ssr = row
        subject = SubjectID(sid) if sid is not None else SubjectSet(ssn, sso, ssr)
        return RelationTuple(ns, obj, rel, subject)

    _COLS = (
        "namespace, object, relation, subject_id, "
        "subject_set_namespace, subject_set_object, subject_set_relation"
    )

    def _where(self, query: Optional[RelationQuery]) -> Tuple[str, List]:
        clauses, args = ["nid = ?"], [self.nid]
        if query is not None:
            if query.namespace is not None:
                clauses.append("namespace = ?")
                args.append(query.namespace)
            if query.object is not None:
                clauses.append("object = ?")
                args.append(query.object)
            if query.relation is not None:
                clauses.append("relation = ?")
                args.append(query.relation)
            subject = query.subject()
            if subject is not None:
                if isinstance(subject, SubjectSet):
                    clauses.append(
                        "subject_set_namespace = ? AND subject_set_object = ?"
                        " AND subject_set_relation = ?"
                    )
                    args.extend([subject.namespace, subject.object, subject.relation])
                else:
                    clauses.append("subject_id = ?")
                    args.append(subject.id)
        return " AND ".join(clauses), args

    # -- reads ---------------------------------------------------------------

    def get_relation_tuples(
        self,
        query: Optional[RelationQuery] = None,
        *,
        page_token: str = "",
        page_size: int = 0,
    ) -> Tuple[List[RelationTuple], str]:
        if page_size <= 0:
            page_size = DEFAULT_PAGE_SIZE
        after = -1
        if page_token:
            try:
                after = int(page_token)
            except ValueError:
                raise ErrMalformedPageToken() from None
        where, args = self._where(query)
        with self._lock:
            self._assert_migrated()
            rows = self._db.execute(
                f"SELECT seq, {self._COLS} FROM keto_relation_tuples"
                f" WHERE {where} AND seq > ? ORDER BY seq LIMIT ?",
                (*args, after, page_size + 1),
            ).fetchall()
        if len(rows) > page_size:
            rows = rows[:page_size]
            return [self._decode(r[1:]) for r in rows], str(rows[-1][0])
        return [self._decode(r[1:]) for r in rows], ""

    def exists_relation_tuples(self, query: Optional[RelationQuery] = None) -> bool:
        where, args = self._where(query)
        with self._lock:
            self._assert_migrated()
            row = self._db.execute(
                f"SELECT 1 FROM keto_relation_tuples WHERE {where} LIMIT 1",
                args,
            ).fetchone()
        return row is not None

    def __len__(self) -> int:
        with self._lock:
            self._assert_migrated()
            return self._db.execute(
                "SELECT COUNT(*) FROM keto_relation_tuples WHERE nid = ?",
                (self.nid,),
            ).fetchone()[0]

    def _all_tuples_locked(self) -> List[RelationTuple]:
        rows = self._db.execute(
            f"SELECT {self._COLS} FROM keto_relation_tuples"
            " WHERE nid = ? ORDER BY seq",
            (self.nid,),
        ).fetchall()
        return [self._decode(r) for r in rows]

    def all_tuples(self) -> List[RelationTuple]:
        with self._lock:
            self._assert_migrated()
            return self._all_tuples_locked()

    def tuples_and_head(self) -> Tuple[List[RelationTuple], int]:
        """Scan + log head in ONE read transaction: a write committed by
        any other handle/process either lands in the scan or in a later
        ``changes_since(head)`` drain — never in neither."""
        with self._lock:
            self._assert_migrated()
            with self._tx():
                return self._all_tuples_locked(), self._log_head_locked()

    # -- change notification / version ---------------------------------------

    def on_change(self, fn: Callable[[int], None]) -> None:
        self._listeners.append(fn)

    @property
    def version(self) -> int:
        with self._lock:
            self._assert_migrated()
            row = self._db.execute(
                "SELECT value FROM keto_meta WHERE nid = ? AND key = 'version'",
                (self.nid,),
            ).fetchone()
        return int(row[0]) if row else 0

    def _bump_locked(self) -> int:
        v = self.version + 1
        self._db.execute(
            "INSERT INTO keto_meta (nid, key, value) VALUES (?, 'version', ?)"
            " ON CONFLICT (nid, key) DO UPDATE SET value = excluded.value",
            (self.nid, str(v)),
        )
        return v

    # -- writes --------------------------------------------------------------

    def write_relation_tuples(self, *tuples: RelationTuple) -> None:
        self.transact_relation_tuples(insert=tuples, delete=())

    def delete_relation_tuples(self, *tuples: RelationTuple) -> None:
        self.transact_relation_tuples(insert=(), delete=tuples)

    def transact_relation_tuples(
        self,
        insert: Iterable[RelationTuple] = (),
        delete: Iterable[RelationTuple] = (),
    ) -> None:
        """Inserts then deletes in one transaction
        (sql/relationtuples.go:277-287)."""
        insert, delete = list(insert), list(delete)
        for t in insert:
            if t.subject is None:
                raise BadRequestError("subject is not allowed to be nil")
        with self._lock:
            self._assert_migrated()
            with self._tx("IMMEDIATE"):
                now = time.time()
                for t in insert:
                    self._db.execute(
                        "INSERT INTO keto_relation_tuples"
                        f" (nid, {self._COLS}, commit_time)"
                        " VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)",
                        (self.nid, t.namespace, t.object, t.relation,
                         *self._subject_cols(t), now),
                    )
                    self._log_locked(1, t)
                n_deleted = 0
                for t in delete:
                    n_deleted += self._delete_exact_locked(t)
                if insert or n_deleted:
                    v = self._bump_locked()
                else:
                    v = None
        if v is not None:
            for fn in self._listeners:
                fn(v)

    def delete_all_relation_tuples(self, query: Optional[RelationQuery] = None) -> int:
        where, args = self._where(query)
        with self._lock:
            self._assert_migrated()
            with self._tx("IMMEDIATE"):
                rows = self._db.execute(
                    f"SELECT seq, {self._COLS} FROM keto_relation_tuples"
                    f" WHERE {where} ORDER BY seq",
                    args,
                ).fetchall()
                for r in rows:
                    self._db.execute(
                        "DELETE FROM keto_relation_tuples WHERE seq = ?", (r[0],)
                    )
                    self._log_locked(-1, self._decode(r[1:]))
                v = self._bump_locked() if rows else None
        if v is not None:
            for fn in self._listeners:
                fn(v)
        return len(rows)

    def _delete_exact_locked(self, t: RelationTuple) -> int:
        sid, ssn, sso, ssr = self._subject_cols(t)
        subj_clause = (
            "subject_id = ?" if sid is not None
            else "subject_set_namespace = ? AND subject_set_object = ?"
                 " AND subject_set_relation = ?"
        )
        subj_args = [sid] if sid is not None else [ssn, sso, ssr]
        rows = self._db.execute(
            "SELECT seq FROM keto_relation_tuples"
            " WHERE nid = ? AND namespace = ? AND object = ? AND relation = ?"
            f" AND {subj_clause}",
            (self.nid, t.namespace, t.object, t.relation, *subj_args),
        ).fetchall()
        for (seq,) in rows:
            self._db.execute(
                "DELETE FROM keto_relation_tuples WHERE seq = ?", (seq,)
            )
            self._log_locked(-1, t)
        return len(rows)

    # -- change log ----------------------------------------------------------

    def _log_locked(self, op: int, t: RelationTuple) -> None:
        self._db.execute(
            "INSERT INTO keto_change_log"
            f" (nid, op, {self._COLS}) VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)",
            (self.nid, op, t.namespace, t.object, t.relation,
             *self._subject_cols(t)),
        )
        # bounded retention: drop entries beyond the cap for this nid and
        # record the trim floor so stale cursors are detectable.  The
        # boundary probe walks O(log_cap) index entries, so it runs every
        # _trim_interval writes (the log may overshoot the cap by that
        # interval — readers only need the floor to be accurate, which the
        # meta update below keeps)
        self._writes_since_trim += 1
        if self._writes_since_trim < self._trim_interval:
            return
        self._writes_since_trim = 0
        row = self._db.execute(
            "SELECT id FROM keto_change_log WHERE nid = ?"
            " ORDER BY id DESC LIMIT 1 OFFSET ?",
            (self.nid, self._log_cap),
        ).fetchone()
        if row is not None:
            cur = self._db.execute(
                "DELETE FROM keto_change_log WHERE nid = ? AND id <= ?",
                (self.nid, row[0]),
            )
            self._db.execute(
                "INSERT INTO keto_meta (nid, key, value)"
                " VALUES (?, 'log_floor', ?) ON CONFLICT (nid, key)"
                " DO UPDATE SET value = excluded.value",
                (self.nid, str(row[0] + 1)),
            )
            dropped = max(int(cur.rowcount or 0), 0)
            if dropped:
                first = not self._overflow_episode
                self._overflow_episode = True
                self.overflow_evictions += dropped
                if self.overflow_hook is not None:
                    self.overflow_hook(dropped, first)

    def _log_head_locked(self) -> int:
        row = self._db.execute(
            "SELECT MAX(id) FROM keto_change_log"
        ).fetchone()
        return (row[0] or 0) + 1

    @property
    def log_head(self) -> int:
        with self._lock:
            self._assert_migrated()
            return self._log_head_locked()

    def changes_since(self, cursor: int):
        """([(op, tuple)], head) for this nid since ``cursor``; (None, head)
        when the bounded log no longer covers the cursor.  One read
        transaction, rows bounded by the head read inside it, so repeated
        drains never miss or double-deliver a cross-handle write."""
        with self._lock:
            self._assert_migrated()
            with self._tx():
                head = self._log_head_locked()
                row = self._db.execute(
                    "SELECT value FROM keto_meta"
                    " WHERE nid = ? AND key = 'log_floor'",
                    (self.nid,),
                ).fetchone()
                if row is not None and cursor < int(row[0]):
                    # the lagging reader has seen the gap and will
                    # rebuild: the overflow episode is over
                    self._overflow_episode = False
                    return None, head  # trimmed past the cursor
                rows = self._db.execute(
                    f"SELECT op, {self._COLS} FROM keto_change_log"
                    " WHERE nid = ? AND id >= ? AND id < ? ORDER BY id",
                    (self.nid, cursor, head),
                ).fetchall()
        return [(r[0], self._decode(r[1:])) for r in rows], head

    def version_and_head(self) -> Tuple[int, int]:
        """(version, log head) in one read transaction — the atomic pair
        snaptoken minting and checkpoint stamping key off (the in-memory
        store exposes the same contract)."""
        with self._lock:
            self._assert_migrated()
            with self._tx():
                return self.version, self._log_head_locked()

    def replica_scan(self) -> Tuple[List[RelationTuple], int, int]:
        """(tuples, head, version) in one read transaction: the bootstrap
        scan a warm-standby follower seeds its replica from."""
        with self._lock:
            self._assert_migrated()
            with self._tx():
                return (
                    self._all_tuples_locked(),
                    self._log_head_locked(),
                    self.version,
                )

    def changes_since_versioned(self, cursor: int):
        """``changes_since`` plus the store version under one lock (the
        replication tail op ships the triple so the follower's replica
        lands on exactly the leader's (head, version) pair)."""
        with self._lock:
            entries, head = self.changes_since(cursor)
            return entries, head, self.version

    def uuid_reverse_store(self) -> SQLiteReverseStore:
        """Durable reverse UUID mappings sharing this store's connection —
        the registry hands this to UUIDMapper so reverse lookups survive
        restart (the in-memory store has no such factory and mappers fall
        back to the process-memory ReverseStore)."""
        return SQLiteReverseStore(self)

    def close(self) -> None:
        with self._lock:
            self._db.close()
