"""Batched one-hop traversal primitives.

These are the check engine's hot-path queries, with the same contract as the
reference's SQL traverser (`internal/persistence/sql/traverser.go:51-191`):

* ``traverse_subject_set_expansion``: all subject-set children of
  ``obj#relation``, each annotated with a *found* bit — whether the target
  subject is a direct member of that child — short-circuiting after the first
  found child.
* ``traverse_subject_set_rewrite``: the OR-of-computed-subject-sets shortcut —
  one probe across ``relation IN (...)``; on miss, returns the rewritten
  candidate tuples for another hop.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import List, Optional

from ketotpu.api.types import RelationQuery, RelationTuple, SubjectSet
from ketotpu.storage.memory import InMemoryTupleStore
from ketotpu.storage.namespaces import NamespaceManager, ast_relation_for


class TraversalDirection(enum.Enum):
    # reference: internal/relationtuple/definitions.go:66-72
    SUBJECT_SET_EXPAND = "subject set expand"
    COMPUTED_USERSET = "computed userset"
    TUPLE_TO_USERSET = "tuple to userset"


@dataclass
class TraversalResult:
    from_: RelationTuple
    to: RelationTuple
    via: TraversalDirection
    found: bool


class Traverser:
    def __init__(
        self,
        store: InMemoryTupleStore,
        namespace_manager: Optional[NamespaceManager] = None,
        *,
        strict_mode: bool = False,
    ):
        self.store = store
        self.namespace_manager = namespace_manager
        self.strict_mode = strict_mode

    def traverse_subject_set_expansion(
        self, start: RelationTuple
    ) -> List[TraversalResult]:
        """traverser.go:53-121.  The *to* tuples carry the start subject so
        the engine can recurse on them directly."""
        res: List[TraversalResult] = []
        page_token = ""
        while True:
            rows, page_token = self.store.get_relation_tuples(
                RelationQuery(
                    namespace=start.namespace,
                    object=start.object,
                    relation=start.relation,
                ),
                page_token=page_token,
                page_size=1000,
            )
            for row in rows:
                if not isinstance(row.subject, SubjectSet):
                    continue
                child = row.subject
                found = self.store.exists_relation_tuples(
                    RelationQuery(
                        namespace=child.namespace,
                        object=child.object,
                        relation=child.relation,
                    ).with_subject(start.subject)
                )
                res.append(
                    TraversalResult(
                        from_=start,
                        to=RelationTuple(
                            namespace=child.namespace,
                            object=child.object,
                            relation=child.relation,
                            subject=start.subject,
                        ),
                        via=TraversalDirection.SUBJECT_SET_EXPAND,
                        found=found,
                    )
                )
                if found:
                    return res
            if not page_token:
                return res

    def traverse_subject_set_rewrite(
        self, start: RelationTuple, computed_subject_set_relations: List[str]
    ) -> List[TraversalResult]:
        """traverser.go:123-191."""
        relations = []
        for relation in computed_subject_set_relations:
            ast_rel = None
            if self.namespace_manager is not None:
                try:
                    ast_rel = ast_relation_for(
                        self.namespace_manager, start.namespace, relation
                    )
                except Exception:
                    ast_rel = None
            # In strict mode, skip relations that have their own rewrites --
            # those rewrites are applied in memory instead (traverser.go:135-140).
            if self.strict_mode and ast_rel is not None \
                    and ast_rel.subject_set_rewrite is not None:
                continue
            relations.append(relation)

        if relations:
            for relation in relations:
                hit, _ = self.store.get_relation_tuples(
                    RelationQuery(
                        namespace=start.namespace,
                        object=start.object,
                        relation=relation,
                    ).with_subject(start.subject),
                    page_size=1,
                )
                if hit:
                    return [
                        TraversalResult(
                            from_=start,
                            to=hit[0],
                            via=TraversalDirection.COMPUTED_USERSET,
                            found=True,
                        )
                    ]

        # Otherwise the next candidates are ALL rewritten relations -- the
        # unfiltered input list, as in traverser.go:176-188.
        return [
            TraversalResult(
                from_=start,
                to=RelationTuple(
                    namespace=start.namespace,
                    object=start.object,
                    relation=relation,
                    subject=start.subject,
                ),
                via=TraversalDirection.COMPUTED_USERSET,
                found=False,
            )
            for relation in computed_subject_set_relations
        ]
