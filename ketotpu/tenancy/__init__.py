"""Tenant plane: thousands of isolated stores on one device engine.

Ory Network runs Keto multi-tenant with a per-request ``Contextualizer``
resolving ``X-Keto-Network`` into a network id and ``nid``-scoped rows
(SURVEY §5.6); Zanzibar itself is one shared service for every client
namespace.  This package makes that model first-class on the packed
device path: ONE compiled program serves every tenant.

The core trick is namespace qualification.  A tenant's tuples live in a
single shared ("fused") store under the namespace ``f"{nid}\\x1f{ns}"``
— the unit separator can never appear in a client namespace, so the
qualified name space is collision-free.  Because node identity in the
device projection is (namespace, object, relation), qualifying the
namespace qualifies every vocab id, CSR row, leopard closure pair,
cache key, singleflight key, and mesh routing hash at once: cross-tenant
leakage is impossible by construction rather than filtered after the
fact.  Tenant create/reload/delete only changes the namespace-config
fingerprint, so it rides the existing PR-8 generation swap — padded
array shapes are unchanged and warmed programs stay warm.

Per-tenant surfaces are facades over the shared machinery:

* :class:`~ketotpu.tenancy.store.TenantStoreView` — the storage contract
  (rows/changelog/log_head in GLOBAL changelog coordinates, filtered per
  tenant — the same contract the SQL stores' ``nid`` column implements);
* ``TenantCheckEngine`` — qualifies checks/blocks before the shared
  coalescer, so waves mix tenants while identical keys from different
  tenants never singleflight-collapse;
* :class:`~ketotpu.tenancy.quota.TenantQuotas` — token buckets for
  inflight check units, write rate, and tuple count; a tenant's batch
  flood sheds inside its own budget (429) under the PR 16 ladder.
"""

from ketotpu.tenancy.plane import (  # noqa: F401
    SEP,
    TenantCheckEngine,
    TenantPlane,
    qualify_ns,
    split_ns,
)
from ketotpu.tenancy.quota import TenantQuotas, TokenBucket  # noqa: F401
from ketotpu.tenancy.store import TenantStoreView  # noqa: F401
