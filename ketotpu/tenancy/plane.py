"""The tenant plane: one shared device engine, thousands of isolated stores.

Architecture (see the package docstring): tenant tuples live in one fused
store under qualified namespaces (``nid + "\\x1f" + ns``).  This module
holds everything above the store view:

* :class:`PlaneNamespaceManager` — the namespace config the SHARED device
  engine sees: every tenant's effective namespaces under their qualified
  names.  Tenant create/delete/OPL-reload changes this manager's output,
  which changes ``config_fingerprint`` — the engine's next snapshot sync
  runs a full PR-8 generation swap.  Padded device shapes come from
  pow2/1.5-pow2 buckets, so the swap re-runs warmed programs: no new XLA
  compiles unless the fleet actually outgrows its buckets.
* :class:`TenantNamespaceManager` — one tenant's UNqualified view for its
  derived registry (handlers validate raw client namespace names).
* :class:`TenantCheckEngine` — the per-tenant check facade ABOVE the
  shared coalescer: it qualifies scalar tuples and ColumnBlocks, then
  delegates, so waves mix tenants while flight/cache keys stay
  tenant-distinct by construction (two tenants' identical checks can
  never singleflight-collapse).  Inflight-unit quota gates admission.
* :class:`TenantListEngine` — qualifying facade over the shared device
  list engine (leopard closure answers stay per-tenant because node
  identity embeds the qualified namespace).
* :class:`TenantPlane` — lifecycle (create/list/delete/OPL hot reload),
  per-tenant quotas and counters, and bounded-cardinality metrics
  (top-K tenants by traffic, remainder folded into ``other``).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ketotpu.api.types import (
    BadRequestError,
    NotFoundError,
    TooManyRequestsError,
)
from ketotpu.opl.ast import Namespace
from ketotpu.tenancy.quota import TenantQuotas
from ketotpu.tenancy.store import (  # noqa: F401  (re-exported package API)
    SEP,
    TenantStoreView,
    qualify_ns,
    qualify_subject,
    qualify_tuple,
    split_ns,
    unqualify_subject,
)


class PlaneNamespaceManager:
    """Namespace config for the shared engine: the union of every
    tenant's effective namespaces under qualified names.

    ``namespaces()`` sits on the snapshot-sync hot path (the engine
    fingerprints it before every dispatch), so the qualified list is
    cached and keyed on (plane config version, base manager output
    identity) — the base identity keeps file-backed managers' hot
    reload windows working without re-quoting every call.
    """

    def __init__(self, plane: "TenantPlane", base):
        self._plane = plane
        self._base = base
        self._cache_key = None
        self._cache: List[Namespace] = []
        self._lock = threading.Lock()

    def namespaces(self) -> List[Namespace]:
        base = self._base.namespaces()  # reload window for file managers
        key = (self._plane.ns_version, tuple(id(n) for n in base))
        with self._lock:
            if key != self._cache_key:
                out: List[Namespace] = []
                for nid in self._plane.tenant_ids():
                    override = self._plane.override_namespaces(nid)
                    for ns in (override if override is not None else base):
                        # rewrites reference relation names only, so a
                        # renamed shallow copy shares the relation ASTs
                        out.append(Namespace(
                            name=qualify_ns(nid, ns.name),
                            relations=ns.relations,
                        ))
                self._cache_key = key
                self._cache = out
            return list(self._cache)

    def get_namespace(self, name: str) -> Namespace:
        nid, base_name = split_ns(name)
        if nid is None or not self._plane.has_tenant(nid):
            raise NotFoundError(f"namespace {name!r} was not found")
        override = self._plane.override_namespaces(nid)
        if override is not None:
            for ns in override:
                if ns.name == base_name:
                    return Namespace(name=name, relations=ns.relations)
            raise NotFoundError(f"namespace {name!r} was not found")
        ns = self._base.get_namespace(base_name)
        return Namespace(name=name, relations=ns.relations)


class TenantNamespaceManager:
    """One tenant's unqualified namespace view (override-or-shared),
    resolved dynamically so an OPL hot reload is visible immediately."""

    def __init__(self, plane: "TenantPlane", nid: str):
        self._plane = plane
        self.nid = nid

    def namespaces(self) -> List[Namespace]:
        override = self._plane.override_namespaces(self.nid)
        if override is not None:
            return list(override)
        return self._plane.base_manager.namespaces()

    def get_namespace(self, name: str) -> Namespace:
        override = self._plane.override_namespaces(self.nid)
        if override is not None:
            for ns in override:
                if ns.name == name:
                    return ns
            raise NotFoundError(f"namespace {name!r} was not found")
        return self._plane.base_manager.get_namespace(name)


class TenantCheckEngine:
    """Per-tenant check facade over the shared (coalescing) engine.

    Every query is namespace-qualified BEFORE it reaches the shared
    machinery, so the coalescer's flight keys (``str(tuple)``), the
    result-cache keys, and the device vocab ids are tenant-distinct by
    construction.  The inflight-unit token bucket sheds a flooding
    tenant with 429 before its work occupies a wave slot.
    """

    # the handler's columnar pre-encode probes engine._vocab; the block
    # must be qualified first, so hide the shared vocab behind None (the
    # coalescer/device encodes after qualification)
    _vocab = None

    def __init__(self, plane: "TenantPlane", nid: str, parent):
        self._plane = plane
        self.nid = nid
        self._prefix = nid + SEP
        self._parent = parent
        self._quotas = plane.quotas_for(nid)

    @property
    def inner(self):
        # debug surfaces (_device_engine -> projection_stats) unwrap to
        # the SHARED device engine; mutating paths never travel this way
        return getattr(self._parent, "inner", self._parent)

    def close(self) -> None:
        """Tenant eviction must NOT close the shared engine underneath
        every other tenant — the facade owns nothing to close."""

    def _acquire(self, n: int) -> None:
        if not self._quotas.inflight.try_acquire(n):
            self._plane.note_shed(self.nid, n)
            raise TooManyRequestsError(
                f"tenant {self.nid!r} inflight quota exceeded "
                f"({self._quotas.inflight.cap} units)"
            )

    def check(self, r, rest_depth: int = 0) -> bool:
        return self.check_is_member(r, rest_depth)

    def check_is_member(self, r, rest_depth: int = 0) -> bool:
        self._acquire(1)
        try:
            verdict = self._parent.check_is_member(
                qualify_tuple(self.nid, r), rest_depth
            )
        finally:
            self._quotas.inflight.release(1)
        self._plane.note_checks(self.nid, 1)
        return verdict

    def batch_check(self, queries, rest_depth: int = 0):
        n = len(queries)
        if n == 0:
            return []
        self._acquire(n)
        try:
            verdicts = self._parent.batch_check(
                [qualify_tuple(self.nid, q) for q in queries], rest_depth
            )
        finally:
            self._quotas.inflight.release(n)
        self._plane.note_checks(self.nid, n)
        return verdicts

    def _qualify_block(self, block):
        from ketotpu.engine import columns

        ns = [self._prefix + s for s in block.ns]
        sa = [
            self._prefix + s if block.skind[i] == columns.SUBJ_SET else s
            for i, s in enumerate(block.sa)
        ]
        # suid recomputes from the qualified sa column, so cache keys and
        # vocab subject ids are tenant-distinct too
        return columns.ColumnBlock(
            ns, list(block.obj), list(block.rel), list(block.skind),
            sa, list(block.sb), list(block.sc),
        )

    def check_block(self, block, rest_depth: int = 0):
        n = len(block)
        if n == 0:
            import numpy as np

            return np.zeros(0, bool), {}
        self._acquire(n)
        try:
            qb = self._qualify_block(block)
            cb = (getattr(self._parent, "check_block", None)
                  or getattr(self._parent, "batch_check_block", None))
            if cb is not None:
                verdicts, row_errs = cb(qb, rest_depth)
            else:
                from ketotpu.engine import columns

                verdicts, row_errs = columns.block_check_via_tuples(
                    self._parent, qb, rest_depth
                )
        finally:
            self._quotas.inflight.release(n)
        self._plane.note_checks(self.nid, n)
        return verdicts, row_errs

    # the worker wire and direct block callers probe this name
    batch_check_block = check_block

    def __getattr__(self, name):
        # read-only forwarding (rebuilds, consistency_cursors, snapshot,
        # refresh, projection_stats, ...) to the shared engine
        if name.startswith("__"):
            raise AttributeError(name)
        return getattr(object.__getattribute__(self, "_parent"), name)


class TenantListEngine:
    """Qualifying facade over the shared device list engine."""

    def __init__(self, nid: str, parent):
        self.nid = nid
        self._parent = parent

    def list_objects(self, namespace: str, relation: str, subject, *,
                     page_size: int = 0, page_token: str = ""):
        return self._parent.list_objects(
            qualify_ns(self.nid, namespace), relation,
            qualify_subject(self.nid, subject),
            page_size=page_size, page_token=page_token,
        )

    def list_subjects(self, namespace: str, object: str, relation: str, *,
                      page_size: int = 0, page_token: str = ""):
        subs, token = self._parent.list_subjects(
            qualify_ns(self.nid, namespace), object, relation,
            page_size=page_size, page_token=page_token,
        )
        return [unqualify_subject(s) for s in subs], token


class _Tenant:
    __slots__ = ("nid", "quotas", "checks", "writes", "shed",
                 "created_at", "override", "opl_source")

    def __init__(self, nid: str, quotas: TenantQuotas):
        self.nid = nid
        self.quotas = quotas
        self.checks = 0
        self.writes = 0
        self.shed = 0
        self.created_at = time.time()
        self.override: Optional[List[Namespace]] = None
        self.opl_source: Optional[str] = None


class TenantPlane:
    """Tenant catalog + quotas + metrics over one fused store.

    ``ns_version`` bumps on every lifecycle event (create / delete / OPL
    reload); :class:`PlaneNamespaceManager` folds it into the namespace
    config the shared engine fingerprints, so each event is exactly one
    generation swap on the warmed engine.
    """

    def __init__(self, fused_store, base_manager, *,
                 default_network: str = "default",
                 max_tenants: int = 1024,
                 quota_inflight: int = 0,
                 quota_write_rate: float = 0.0,
                 quota_max_tuples: int = 0,
                 metrics_top_k: int = 8,
                 logger=None):
        self.fused_store = fused_store
        self.base_manager = base_manager
        self.default_network = default_network
        self.max_tenants = int(max_tenants)
        self.metrics_top_k = int(metrics_top_k)
        self._quota_defaults = dict(
            inflight=int(quota_inflight),
            write_rate=float(quota_write_rate),
            max_tuples=int(quota_max_tuples),
        )
        self._logger = logger
        self._lock = threading.RLock()
        self._tenants: Dict[str, _Tenant] = {}
        self.ns_version = 0
        self._published: Dict[tuple, float] = {}  # counter emit deltas
        self.manager = PlaneNamespaceManager(self, base_manager)
        # the default network always exists — single-tenant requests land
        # there without an admin step
        self._create_locked(default_network)

    # -- catalog -------------------------------------------------------------

    @staticmethod
    def _validate_nid(nid: str) -> str:
        if not nid or SEP in nid:
            raise BadRequestError(f"invalid tenant id {nid!r}")
        return nid

    def tenant_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    def has_tenant(self, nid: str) -> bool:
        with self._lock:
            return nid in self._tenants

    def _create_locked(self, nid: str) -> _Tenant:
        t = _Tenant(nid, TenantQuotas(**self._quota_defaults))
        self._tenants[nid] = t
        self.ns_version += 1
        return t

    def create(self, nid: str) -> dict:
        """Explicit create (admin surface); idempotent."""
        self._validate_nid(nid)
        with self._lock:
            if nid in self._tenants:
                return {"id": nid, "created": False}
            if len(self._tenants) >= self.max_tenants:
                raise TooManyRequestsError(
                    f"tenant capacity reached ({self.max_tenants})"
                )
            self._create_locked(nid)
        if self._logger is not None:
            self._logger.info("tenant %r created", nid)
        return {"id": nid, "created": True}

    def ensure(self, nid: str) -> _Tenant:
        """Implicit create on first request — the Ory Network pattern
        where the auth proxy's header IS the provisioning event."""
        self._validate_nid(nid)
        with self._lock:
            t = self._tenants.get(nid)
            if t is None:
                if len(self._tenants) >= self.max_tenants:
                    raise TooManyRequestsError(
                        f"tenant capacity reached ({self.max_tenants})"
                    )
                t = self._create_locked(nid)
            return t

    def delete(self, nid: str) -> dict:
        """Drop a tenant: its tuples leave through the ordinary changelog
        (so caches/projections invalidate), then its namespaces leave the
        fingerprint (one generation swap)."""
        with self._lock:
            if nid not in self._tenants:
                raise NotFoundError(f"tenant {nid!r} was not found")
            if nid == self.default_network:
                raise BadRequestError("cannot delete the default network")
        prefix = nid + SEP
        doomed = [
            t for t in self.fused_store.all_tuples()
            if t.namespace.startswith(prefix)
        ]
        if doomed:
            self.fused_store.transact_relation_tuples(delete=doomed)
        with self._lock:
            self._tenants.pop(nid, None)
            self.ns_version += 1
        if self._logger is not None:
            self._logger.info("tenant %r deleted (%d tuples)", nid, len(doomed))
        return {"id": nid, "deleted": True, "tuples_removed": len(doomed)}

    # -- per-tenant config ---------------------------------------------------

    def set_opl(self, nid: str, source: str) -> dict:
        """Install (or clear, with empty source) a tenant's own OPL
        namespace config — hot: the next snapshot sync sees the new
        fingerprint and swaps generations."""
        from ketotpu.opl.parser import parse

        t = self.ensure(nid)
        if not source.strip():
            with self._lock:
                t.override = None
                t.opl_source = None
                self.ns_version += 1
            return {"id": nid, "namespaces": None}
        namespaces, errors = parse(source)
        if errors:
            raise BadRequestError(
                "parsing OPL failed: " + "; ".join(e.msg for e in errors)
            )
        with self._lock:
            t.override = namespaces
            t.opl_source = source
            self.ns_version += 1
        return {"id": nid, "namespaces": [n.name for n in namespaces]}

    def override_namespaces(self, nid: str) -> Optional[List[Namespace]]:
        with self._lock:
            t = self._tenants.get(nid)
            return t.override if t is not None else None

    def quotas_for(self, nid: str) -> TenantQuotas:
        return self.ensure(nid).quotas

    # -- per-tenant assembly (used by Registry.for_network) ------------------

    def view_for(self, nid: str, quotas: Optional[TenantQuotas] = None
                 ) -> TenantStoreView:
        t = self.ensure(nid)
        return TenantStoreView(
            self.fused_store, nid,
            quotas=quotas if quotas is not None else t.quotas,
            on_write=lambda n, _nid=nid: self.note_writes(_nid, n),
        )

    def manager_for(self, nid: str) -> TenantNamespaceManager:
        self.ensure(nid)
        return TenantNamespaceManager(self, nid)

    def engine_for(self, nid: str, parent) -> TenantCheckEngine:
        return TenantCheckEngine(self, nid, parent)

    def list_engine_for(self, nid: str, parent) -> TenantListEngine:
        return TenantListEngine(nid, parent)

    # -- accounting ----------------------------------------------------------

    def note_checks(self, nid: str, n: int) -> None:
        with self._lock:
            t = self._tenants.get(nid)
            if t is not None:
                t.checks += n

    def note_writes(self, nid: str, n: int) -> None:
        with self._lock:
            t = self._tenants.get(nid)
            if t is not None:
                t.writes += n

    def note_shed(self, nid: str, n: int) -> None:
        with self._lock:
            t = self._tenants.get(nid)
            if t is not None:
                t.shed += n

    def tuple_counts(self) -> Dict[str, int]:
        """One pass over the fused store: nid -> live tuple count."""
        counts = {nid: 0 for nid in self.tenant_ids()}
        for t in self.fused_store.all_tuples():
            nid, _ = split_ns(t.namespace)
            if nid in counts:
                counts[nid] += 1
        return counts

    def catalog(self) -> List[dict]:
        """Per-tenant rows for GET /debug/tenants and the CLI."""
        counts = self.tuple_counts()
        out = []
        with self._lock:
            for nid in sorted(self._tenants):
                t = self._tenants[nid]
                out.append({
                    "id": nid,
                    "default": nid == self.default_network,
                    "tuples": counts.get(nid, 0),
                    "checks": t.checks,
                    "writes": t.writes,
                    "shed": t.shed,
                    "opl_override": t.override is not None,
                    "quotas": t.quotas.stats(),
                    "created_at": t.created_at,
                })
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "tenants": len(self._tenants),
                "max_tenants": self.max_tenants,
                "ns_version": self.ns_version,
                "default_network": self.default_network,
            }

    # -- metrics (bounded cardinality) ---------------------------------------

    def publish(self, metrics) -> None:
        """Emit per-tenant series for the top-K tenants by lifetime check
        traffic; every other tenant folds into ``tenant="other"`` so the
        scrape cardinality is bounded by K+1 regardless of fleet size."""
        counts = self.tuple_counts()
        with self._lock:
            tenants = list(self._tenants.values())
        tenants.sort(key=lambda t: t.checks, reverse=True)
        top = tenants[:max(1, self.metrics_top_k)]
        rest = tenants[len(top):]
        metrics.gauge(
            "keto_tenant_count", float(len(tenants)),
            help="live tenants on the plane",
        )
        rows = [(t.nid, t.checks, t.writes, t.shed,
                 counts.get(t.nid, 0)) for t in top]
        if rest:
            rows.append((
                "other",
                sum(t.checks for t in rest),
                sum(t.writes for t in rest),
                sum(t.shed for t in rest),
                sum(counts.get(t.nid, 0) for t in rest),
            ))
        for nid, checks, writes, shed, tuples in rows:
            metrics.gauge(
                "keto_tenant_tuples", float(tuples),
                help="live relation tuples per tenant (top-K + other)",
                tenant=nid,
            )
            for name, total, hlp in (
                ("keto_tenant_checks_total", checks,
                 "checks served per tenant (top-K + other)"),
                ("keto_tenant_writes_total", writes,
                 "tuple mutations per tenant (top-K + other)"),
                ("keto_tenant_shed_total", shed,
                 "requests shed by per-tenant quotas (top-K + other)"),
            ):
                prev = self._published.get((name, nid), 0.0)
                if total > prev:
                    metrics.counter(name, float(total - prev),
                                    help=hlp, tenant=nid)
                    self._published[(name, nid)] = float(total)
