"""Per-tenant quotas: token buckets for inflight units, write rate, and
tuple count.

The buckets sit UNDER the PR 16 admission/brownout plane: the global
AIMD limit and priority ladder decide how much work the process accepts
at all; these buckets decide how much of that budget one tenant may
occupy.  A tenant that floods batches exhausts its own inflight bucket
and sheds with 429 (TooManyRequestsError, which the transport layers
already map to Retry-After semantics) while every other tenant's budget
is untouched — the noisy-neighbor isolation the serve_tenants bench leg
gates on.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/second up to ``burst``.

    ``rate <= 0`` disables the bucket (every take succeeds).  Thread-safe;
    ``try_take`` never blocks — quota overflow must shed, not queue, or a
    noisy tenant's backlog would still occupy serving threads.
    """

    def __init__(self, rate: float, burst: Optional[float] = None):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else max(rate, 1.0))
        self._tokens = self.burst
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0) -> bool:
        if self.rate <= 0:
            return True
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._t) * self.rate
            )
            self._t = now
            if self._tokens < n:
                return False
            self._tokens -= n
            return True

    def level(self) -> float:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._t) * self.rate
            )
            self._t = now
            return self._tokens


class InflightGauge:
    """Counting cap on concurrently in-flight check units for one tenant.

    Non-blocking by design (see TokenBucket): a tenant over its cap is
    shed immediately, so its flood queues nowhere.  ``cap <= 0``
    disables.
    """

    def __init__(self, cap: int):
        self.cap = int(cap)
        self._inflight = 0
        self._lock = threading.Lock()

    def try_acquire(self, n: int = 1) -> bool:
        if self.cap <= 0:
            return True
        with self._lock:
            if self._inflight + n > self.cap:
                return False
            self._inflight += n
            return True

    def release(self, n: int = 1) -> None:
        if self.cap <= 0:
            return
        with self._lock:
            self._inflight = max(0, self._inflight - n)

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight


class TenantQuotas:
    """One tenant's quota state: inflight units, write rate, tuple cap."""

    def __init__(self, *, inflight: int = 0, write_rate: float = 0.0,
                 max_tuples: int = 0):
        self.inflight = InflightGauge(inflight)
        self.writes = TokenBucket(write_rate)
        self.max_tuples = int(max_tuples)

    def stats(self) -> dict:
        return {
            "inflight": self.inflight.inflight,
            "inflight_cap": self.inflight.cap,
            "write_tokens": round(self.writes.level(), 1),
            "write_rate": self.writes.rate,
            "max_tuples": self.max_tuples,
        }
