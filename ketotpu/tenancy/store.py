"""Tenant store view: the nid-scoped storage contract over the fused store.

One shared ("fused") in-memory store holds every tenant's tuples under
qualified namespaces (``f"{nid}\\x1f{ns}"``).  Each tenant gets a
:class:`TenantStoreView` presenting the ordinary single-tenant storage
surface — unqualified rows, filtered changelog — in GLOBAL changelog
coordinates, exactly the contract the SQL stores implement with their
``nid`` column over a global AUTOINCREMENT id (``keto_change_log``):

* ``log_head`` is the fused head (sqlite's ``MAX(id)+1`` has no nid
  filter either), so snaptokens minted by any tenant compare directly
  against the shared engine's drain cursors — no translation layer;
* ``changes_since(cursor)`` returns only this tenant's entries but
  advances to the global head, so repeated drains never re-deliver;
* writes are quota-gated (write-rate bucket + tuple cap) and fire the
  view's own listeners — a tenant WatchHub or expand-cache follows only
  its own writes.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, List, Optional, Tuple

from ketotpu.api.types import (
    RelationQuery,
    RelationTuple,
    SubjectSet,
    TooManyRequestsError,
)
from ketotpu.storage.memory import DEFAULT_PAGE_SIZE, ErrMalformedPageToken, _matches

#: unit separator — cannot appear in a client namespace, so qualified
#: names are collision-free and the tenant prefix splits unambiguously
SEP = "\x1f"


def qualify_ns(nid: str, ns: str) -> str:
    return nid + SEP + ns


def split_ns(qns: str) -> Tuple[Optional[str], str]:
    """(nid, ns) for a qualified name; (None, name) when unqualified."""
    i = qns.find(SEP)
    if i < 0:
        return None, qns
    return qns[:i], qns[i + 1:]


def qualify_subject(nid: str, s):
    if isinstance(s, SubjectSet):
        return SubjectSet(
            namespace=qualify_ns(nid, s.namespace),
            object=s.object,
            relation=s.relation,
        )
    return s


def unqualify_subject(s):
    if isinstance(s, SubjectSet):
        _, ns = split_ns(s.namespace)
        return SubjectSet(namespace=ns, object=s.object, relation=s.relation)
    return s


def qualify_tuple(nid: str, t: RelationTuple) -> RelationTuple:
    return RelationTuple(
        namespace=qualify_ns(nid, t.namespace),
        object=t.object,
        relation=t.relation,
        subject=qualify_subject(nid, t.subject),
    )


def unqualify_tuple(t: RelationTuple) -> RelationTuple:
    _, ns = split_ns(t.namespace)
    return RelationTuple(
        namespace=ns,
        object=t.object,
        relation=t.relation,
        subject=unqualify_subject(t.subject),
    )


def qualify_query(nid: str, q: Optional[RelationQuery]) -> Optional[RelationQuery]:
    if q is None:
        return None
    return RelationQuery(
        namespace=qualify_ns(nid, q.namespace) if q.namespace is not None else None,
        object=q.object,
        relation=q.relation,
        subject_id=q.subject_id,
        subject_set=qualify_subject(nid, q.subject_set)
        if q.subject_set is not None else None,
    )


class TenantStoreView:
    """Single-tenant storage surface over the shared fused store."""

    # the registry's overflow hook targets the fused store, not the view;
    # expose the seam so _wire_overflow no-ops cleanly
    def __init__(self, fused, nid: str, quotas=None, on_write=None):
        self._fused = fused
        self.nid = nid
        self._prefix = nid + SEP
        self._quotas = quotas
        self._on_write = on_write  # plane accounting hook(n_ops)
        self._listeners: List[Callable[[int], None]] = []
        self.overflow_hook: Optional[Callable[[int, bool], None]] = None
        self._lock = threading.Lock()
        # per-nid version, mirroring sqlite's per-nid keto_meta row: bumps
        # only on THIS tenant's effective writes
        self._version = 0
        # follow the fused changelog so view listeners fire for THIS
        # tenant's writes however they arrive (own view, admin surface,
        # or another view handle of the same nid)
        self._follow_cursor = fused.log_head
        fused.on_change(self._fused_changed)

    # -- change notification -------------------------------------------------

    def on_change(self, fn: Callable[[int], None]) -> None:
        self._listeners.append(fn)

    def _fused_changed(self, _version: int) -> None:
        """Fused-store listener: bump the per-nid version and fire view
        listeners only when the new changelog entries touch this tenant.
        Always invoked on the writer's thread while it holds the fused
        store's (re-entrant) lock, so the drain below is race-free and
        lock order is strictly fused -> view."""
        with self._lock:
            entries, head = self._fused.changes_since(self._follow_cursor)
            self._follow_cursor = head
            mine = entries is None or any(
                t.namespace.startswith(self._prefix) for _op, t in entries
            )
            if mine:
                self._version += 1
                v = self._version
        if mine:
            for fn in self._listeners:
                fn(v)

    # -- reads ---------------------------------------------------------------

    def _mine(self) -> List[RelationTuple]:
        return [
            unqualify_tuple(t) for t in self._fused.all_tuples()
            if t.namespace.startswith(self._prefix)
        ]

    def get_relation_tuples(
        self,
        query: Optional[RelationQuery] = None,
        *,
        page_token: str = "",
        page_size: int = 0,
    ) -> Tuple[List[RelationTuple], str]:
        if page_size <= 0:
            page_size = DEFAULT_PAGE_SIZE
        after = -1
        if page_token:
            try:
                after = int(page_token)
            except ValueError:
                raise ErrMalformedPageToken() from None
        out: List[Tuple[int, RelationTuple]] = []
        for i, t in enumerate(self._mine()):
            if i <= after or not _matches(t, query):
                continue
            out.append((i, t))
            if len(out) > page_size:
                page = out[:page_size]
                return [t for _, t in page], str(page[-1][0])
        return [t for _, t in out], ""

    def exists_relation_tuples(self, query: Optional[RelationQuery] = None) -> bool:
        if query is not None and query.namespace is not None:
            return self._fused.exists_relation_tuples(qualify_query(self.nid, query))
        return any(_matches(t, query) for t in self._mine())

    def __len__(self) -> int:
        return sum(
            1 for t in self._fused.all_tuples()
            if t.namespace.startswith(self._prefix)
        )

    def all_tuples(self) -> List[RelationTuple]:
        return self._mine()

    def tuples_and_head(self) -> Tuple[List[RelationTuple], int]:
        tuples, head = self._fused.tuples_and_head()
        return [
            unqualify_tuple(t) for t in tuples
            if t.namespace.startswith(self._prefix)
        ], head

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def version_and_head(self) -> Tuple[int, int]:
        # per-nid version, GLOBAL head — exactly sqlite's pair (per-nid
        # keto_meta version, global MAX(id)+1 head).  Fused head is read
        # first: lock order is strictly fused -> view everywhere (the
        # fused-change listener holds the fused lock when it takes ours).
        head = self._fused.log_head
        with self._lock:
            return self._version, head

    @property
    def log_head(self) -> int:
        return self._fused.log_head

    def changes_since(self, cursor: int):
        entries, head = self._fused.changes_since(cursor)
        if entries is None:
            return None, head
        return [
            (op, unqualify_tuple(t)) for op, t in entries
            if t.namespace.startswith(self._prefix)
        ], head

    def changes_since_versioned(self, cursor: int):
        entries, head = self.changes_since(cursor)
        return entries, head, self._fused.version

    # -- writes --------------------------------------------------------------

    def write_relation_tuples(self, *tuples: RelationTuple) -> None:
        self.transact_relation_tuples(insert=tuples, delete=())

    def delete_relation_tuples(self, *tuples: RelationTuple) -> None:
        self.transact_relation_tuples(insert=(), delete=tuples)

    def transact_relation_tuples(
        self,
        insert: Iterable[RelationTuple] = (),
        delete: Iterable[RelationTuple] = (),
    ) -> None:
        insert, delete = list(insert), list(delete)
        q = self._quotas
        if q is not None and (insert or delete):
            n = len(insert) + len(delete)
            if not q.writes.try_take(n):
                raise TooManyRequestsError(
                    f"tenant {self.nid!r} write rate exceeded"
                )
            if q.max_tuples > 0 and insert \
                    and len(self) + len(insert) > q.max_tuples:
                raise TooManyRequestsError(
                    f"tenant {self.nid!r} tuple quota exceeded "
                    f"({q.max_tuples})"
                )
        self._fused.transact_relation_tuples(
            insert=[qualify_tuple(self.nid, t) for t in insert],
            delete=[qualify_tuple(self.nid, t) for t in delete],
        )
        if self._on_write is not None and (insert or delete):
            self._on_write(len(insert) + len(delete))

    def delete_all_relation_tuples(self, query: Optional[RelationQuery] = None) -> int:
        doomed = [t for t in self._mine() if _matches(t, query)]
        if not doomed:
            return 0
        # through transact so quota accounting and the changelog see the
        # deletes as ordinary effective mutations (exact-match semantics
        # delete duplicates too, matching the fused store's behavior)
        self._fused.transact_relation_tuples(
            insert=(),
            delete=[qualify_tuple(self.nid, t) for t in doomed],
        )
        if self._on_write is not None:
            self._on_write(len(doomed))
        return len(doomed)
