"""Tail-sampled trace store: the request-anatomy observatory's archive.

Every request opens a cheap span buffer (flightrec.py); at completion the
buffer reaches :meth:`TraceStore.complete`, which drops the overwhelming
majority on the floor and keeps only the anatomy worth reading:

* **promoted** — slow (past ``observability.trace.slow_ms``), errored,
  shed, deadline-exceeded, or force-promoted (shadow divergence) traces,
  in a bounded newest-wins store served at ``GET /debug/trace``;
* **recent** — a short ring of completed-but-unpromoted traces, kept only
  so the asynchronous shadow plane can still :meth:`force_promote` a
  trace whose divergence is discovered after the response went out.

Promoted traces also flow out through the OTLP exporter when one is
configured (``tracing.provider: otlp``) — the same ``/v1/traces`` flush
path the live spans use, so a collector sees the full stitched timeline.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional


class TraceStore:
    """Bounded promoted-trace store + recent ring; all methods threadsafe."""

    def __init__(
        self,
        *,
        slow_ms: float = 25.0,
        store_size: int = 64,
        recent_size: int = 512,
        metrics=None,
        tracer=None,
    ):
        self.slow_ms = float(slow_ms)
        self.store_size = int(store_size)
        self.recent_size = int(recent_size)
        self._metrics = metrics
        self._tracer = tracer
        self._lock = threading.Lock()
        self._promoted: "OrderedDict[str, Dict]" = OrderedDict()
        self._recent: "OrderedDict[str, Dict]" = OrderedDict()
        self.completions = 0
        self.promotions = 0
        self.force_promotions = 0
        if metrics is not None:
            # pre-register so the vocabulary is on the first scrape
            metrics.counter(
                "keto_trace_completed_total", 0,
                help="requests that closed a span buffer",
            )
            metrics.counter(
                "keto_trace_promoted_total", 0,
                help="traces promoted into the trace store", reason="slow",
            )

    # -- completion / promotion ---------------------------------------------

    def complete(self, entry: Dict, reasons: Optional[List[str]]) -> None:
        """File one finished request.  ``reasons`` non-empty promotes;
        empty parks it in the recent ring (droppable, force-promotable)."""
        tid = entry.get("trace_id")
        if not tid:
            return
        with self._lock:
            self.completions += 1
            if reasons:
                self._promote_locked(tid, entry, list(reasons))
            else:
                self._recent[tid] = entry
                while len(self._recent) > self.recent_size:
                    self._recent.popitem(last=False)
        if self._metrics is not None:
            self._metrics.counter("keto_trace_completed_total", 1)

    def _promote_locked(self, tid: str, entry: Dict, reasons: List[str]):
        prior = self._promoted.pop(tid, None)
        if prior is not None:
            # same trace id promoted twice (owner + worker legs in one
            # process, or a re-promotion): merge reasons, keep newest body
            reasons = sorted(set(prior.get("promoted", [])) | set(reasons))
        entry["promoted"] = reasons
        self._promoted[tid] = entry
        while len(self._promoted) > self.store_size:
            self._promoted.popitem(last=False)
        self.promotions += 1
        if self._metrics is not None:
            for r in reasons:
                self._metrics.counter(
                    "keto_trace_promoted_total", 1,
                    help="traces promoted into the trace store", reason=r,
                )
        if self._tracer is not None:
            export = getattr(self._tracer, "export_trace", None)
            if export is not None:
                export(entry)

    def force_promote(self, trace_id: str, reason: str) -> bool:
        """Promote a trace after the fact (shadow divergence found
        asynchronously).  True when the trace was still findable."""
        with self._lock:
            if trace_id in self._promoted:
                ent = self._promoted[trace_id]
                if reason not in ent.get("promoted", []):
                    ent.setdefault("promoted", []).append(reason)
                self.force_promotions += 1
                return True
            ent = self._recent.pop(trace_id, None)
            if ent is None:
                return False
            self._promote_locked(trace_id, ent, [reason])
            self.force_promotions += 1
            return True

    # -- read side -----------------------------------------------------------

    def promoted(self, n: int = 0) -> List[Dict]:
        """Newest-first promoted traces (summaries include full spans)."""
        with self._lock:
            out = [dict(e) for e in reversed(self._promoted.values())]
        return out[:n] if n > 0 else out

    def get(self, trace_id: str) -> Optional[Dict]:
        with self._lock:
            e = self._promoted.get(trace_id) or self._recent.get(trace_id)
            return dict(e) if e is not None else None

    def stats(self) -> Dict:
        with self._lock:
            return {
                "completions": self.completions,
                "promotions": self.promotions,
                "force_promotions": self.force_promotions,
                "promoted_held": len(self._promoted),
                "recent_held": len(self._recent),
                "slow_ms": self.slow_ms,
            }
