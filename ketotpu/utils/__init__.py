"""Shared utilities: synthetic graph generation, timing helpers."""
