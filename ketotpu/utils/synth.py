"""Synthetic Drive-style permission graphs for benchmarks and dry runs.

Models the BASELINE benchmark shapes: a folder tree with viewer/owner
assignments (some through group subject-sets), documents under folders, and
`view` permissions that chain computed-userset + tuple-to-userset rewrites up
the tree (the "5-hop rewrites" workload).  Mirrors the reference's deep/wide
benchmark generators (internal/check/bench_test.go:56-133) in spirit, at
configurable scale.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

import numpy as np

from ketotpu.api.types import RelationTuple, SubjectID, SubjectSet
from ketotpu.opl.parser import parse
from ketotpu.storage.memory import InMemoryTupleStore
from ketotpu.storage.namespaces import StaticNamespaceManager

SYNTH_OPL = """
import { Namespace, SubjectSet, Context } from "@ory/keto-namespace-types"

class User implements Namespace {}

class Group implements Namespace {
  related: {
    members: (User | Group)[]
  }
}

class Folder implements Namespace {
  related: {
    parents: Folder[]
    viewers: (User | SubjectSet<Group, "members">)[]
    owners: (User | SubjectSet<Group, "members">)[]
  }
  permits = {
    own: (ctx: Context): boolean =>
      this.related.owners.includes(ctx.subject) ||
      this.related.parents.traverse((p) => p.permits.own(ctx)),
    view: (ctx: Context): boolean =>
      this.related.viewers.includes(ctx.subject) ||
      this.permits.own(ctx) ||
      this.related.parents.traverse((p) => p.permits.view(ctx)),
  }
}

class Doc implements Namespace {
  related: {
    parents: Folder[]
    viewers: (User | SubjectSet<Group, "members">)[]
    owners: (User | SubjectSet<Group, "members">)[]
    banned: User[]
  }
  permits = {
    view: (ctx: Context): boolean =>
      this.related.viewers.includes(ctx.subject) ||
      this.related.owners.includes(ctx.subject) ||
      this.related.parents.traverse((p) => p.permits.view(ctx)),
    edit: (ctx: Context): boolean =>
      !this.related.banned.includes(ctx.subject) &&
      this.permits.view(ctx),
  }
}
"""


@dataclass
class SynthGraph:
    store: InMemoryTupleStore
    manager: StaticNamespaceManager
    users: List[str]
    docs: List[str]
    folders: List[str]
    groups: List[str] = None


def build_synth(
    *,
    n_users: int = 100,
    n_groups: int = 10,
    n_folders: int = 50,
    n_docs: int = 200,
    fanout: int = 4,
    seed: int = 0,
) -> SynthGraph:
    """Folder tree of degree ``fanout``; docs attach to random folders;
    viewers/owners assigned directly and through groups."""
    rng = np.random.default_rng(seed)
    namespaces, errors = parse(SYNTH_OPL)
    assert not errors, errors
    manager = StaticNamespaceManager(namespaces)
    store = InMemoryTupleStore()

    users = [f"u{i}" for i in range(n_users)]
    groups = [f"g{i}" for i in range(n_groups)]
    folders = [f"f{i}" for i in range(n_folders)]
    docs = [f"d{i}" for i in range(n_docs)]
    tuples: List[RelationTuple] = []

    def t(ns, obj, rel, subj):
        tuples.append(RelationTuple(ns, obj, rel, subj))

    # group membership: users spread over groups; a few nested groups
    for i, u in enumerate(users):
        t("Group", groups[i % n_groups], "members", SubjectID(u))
    for i in range(1, n_groups, 3):
        t("Group", groups[i - 1], "members", SubjectSet("Group", groups[i], "members"))

    # folder tree rooted at f0
    for i in range(1, n_folders):
        t("Folder", folders[i], "parents", SubjectSet("Folder", folders[(i - 1) // fanout]))
    # scatter viewers/owners on folders: direct users and group sets
    for i, f in enumerate(folders):
        if i % 3 == 0:
            t("Folder", f, "viewers", SubjectID(users[int(rng.integers(n_users))]))
        if i % 5 == 0:
            t("Folder", f, "owners", SubjectID(users[int(rng.integers(n_users))]))
        if i % 4 == 0:
            t("Folder", f, "viewers",
              SubjectSet("Group", groups[int(rng.integers(n_groups))], "members"))

    # docs under folders with occasional direct grants
    for i, d in enumerate(docs):
        t("Doc", d, "parents", SubjectSet("Folder", folders[int(rng.integers(n_folders))]))
        if i % 7 == 0:
            t("Doc", d, "viewers", SubjectID(users[int(rng.integers(n_users))]))
        if i % 11 == 0:
            t("Doc", d, "owners", SubjectID(users[int(rng.integers(n_users))]))
        if i % 13 == 0:
            # exclusion targets for the AND/NOT `edit` permit
            t("Doc", d, "banned", SubjectID(users[int(rng.integers(n_users))]))

    store.write_relation_tuples(*tuples)
    return SynthGraph(
        store=store, manager=manager, users=users, docs=docs,
        folders=folders, groups=groups,
    )


def synth_queries(
    graph: SynthGraph, n: int, *, seed: int = 1
) -> List[RelationTuple]:
    """Mixed doc-view checks: random (doc, user) pairs."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        d = graph.docs[int(rng.integers(len(graph.docs)))]
        u = graph.users[int(rng.integers(len(graph.users)))]
        out.append(RelationTuple("Doc", d, "view", SubjectID(u)))
    return out


def synth_queries_mixed(
    graph: SynthGraph,
    n: int,
    *,
    seed: int = 1,
    general_frac: float = 0.3,
    subject_set_frac: float = 0.15,
) -> List[RelationTuple]:
    """BASELINE config #4's query shape: mixed (subject_id, subject_set)
    queries with a slice hitting the intersection/exclusion `edit` permit
    (the AND/NOT general path)."""
    rng = np.random.default_rng(seed)
    out = []
    groups = graph.groups or []
    for _ in range(n):
        d = graph.docs[int(rng.integers(len(graph.docs)))]
        rel = "edit" if rng.random() < general_frac else "view"
        if groups and rng.random() < subject_set_frac:
            subject = SubjectSet(
                "Group", groups[int(rng.integers(len(groups)))], "members"
            )
        else:
            subject = SubjectID(
                graph.users[int(rng.integers(len(graph.users)))]
            )
        out.append(RelationTuple("Doc", d, rel, subject))
    return out


def build_deep_groups(
    *,
    depth: int = 12,
    n_chains: int = 8,
    n_users: int = 64,
    seed: int = 0,
) -> SynthGraph:
    """Deep nested-group chains for the Leopard deep-check workload.

    ``n_chains`` independent chains of ``depth`` groups each:
    ``gc_0.members`` contains ``gc_1#members`` contains ... down to
    ``gc_{depth-1}``, whose members are direct users.  A check
    ``Group:gc_0#members@user`` therefore needs ``depth`` containment hops —
    the shape where the closure index replaces a BFS level per hop with one
    binary search.  The graph is rewrite-free and narrow, so every node is
    clean and closure verdicts carry the whole workload (zero fallbacks).
    """
    rng = np.random.default_rng(seed)
    namespaces, errors = parse(SYNTH_OPL)
    assert not errors, errors
    manager = StaticNamespaceManager(namespaces)
    store = InMemoryTupleStore()

    users = [f"u{i}" for i in range(n_users)]
    groups: List[str] = []
    tuples: List[RelationTuple] = []
    for c in range(n_chains):
        chain = [f"g{c}_{d}" for d in range(depth)]
        groups.extend(chain)
        for d in range(depth - 1):
            tuples.append(RelationTuple(
                "Group", chain[d], "members",
                SubjectSet("Group", chain[d + 1], "members"),
            ))
        # users land in the deepest group of a random subset of chains
        for u in users:
            if rng.random() < 0.5:
                tuples.append(RelationTuple(
                    "Group", chain[-1], "members", SubjectID(u)))
    store.write_relation_tuples(*tuples)
    return SynthGraph(
        store=store, manager=manager, users=users, docs=[],
        folders=[], groups=groups,
    )


def deep_queries(
    graph: SynthGraph, n: int, *, depth: int = 12, seed: int = 1
) -> List[RelationTuple]:
    """Checks against chain roots: each needs ``depth`` containment hops."""
    rng = np.random.default_rng(seed)
    roots = [g for g in (graph.groups or []) if g.endswith("_0")]
    out = []
    for _ in range(n):
        g = roots[int(rng.integers(len(roots)))]
        u = graph.users[int(rng.integers(len(graph.users)))]
        out.append(RelationTuple("Group", g, "members", SubjectID(u)))
    return out


def build_synth_columnar(
    *,
    n_users: int = 1_200_000,
    n_groups: int = 25_000,
    n_folders: int = 500_000,
    n_docs: int = 6_500_000,
    fanout: int = 4,
    seed: int = 0,
) -> SynthGraph:
    """The 10M-tuple-scale synth graph, built columnar (VERDICT r2 #4).

    Same shape as `build_synth` (folder tree, group subject-sets, CSS+TTU
    view chains) but every tuple is generated directly as vectorized id
    columns into a `ColumnarTupleStore` — no per-tuple Python objects, so
    a 10M-tuple graph loads in seconds instead of minutes and the engine
    adopts the columns wholesale (`export_columns`).
    """
    from ketotpu.storage.columnar import ColumnarTupleStore

    rng = np.random.default_rng(seed)
    namespaces, errors = parse(SYNTH_OPL)
    assert not errors, errors
    manager = StaticNamespaceManager(namespaces)

    users = [f"u{i}" for i in range(n_users)]
    groups = [f"g{i}" for i in range(n_groups)]
    folders = [f"f{i}" for i in range(n_folders)]
    docs = [f"d{i}" for i in range(n_docs)]

    # deterministic dense id assignment, interners built in bulk
    from ketotpu.engine.vocab import Vocab

    v = Vocab()
    v.namespaces._ids = {"Group": 0, "Folder": 1, "Doc": 2}
    objs = {}
    for name in groups:
        objs[name] = len(objs)
    for name in folders:
        objs[name] = len(objs)
    for name in docs:
        objs[name] = len(objs)
    v.objects._ids = objs
    # "" is pre-interned at id 0 (Vocab __init__)
    R_EMPTY = v.relations.intern("")
    R_MEMBERS = v.relations.intern("members")
    R_PARENTS = v.relations.intern("parents")
    R_VIEWERS = v.relations.intern("viewers")
    R_OWNERS = v.relations.intern("owners")
    R_BANNED = v.relations.intern("banned")
    subs = {f"id:{u}": i for i, u in enumerate(users)}
    for g in groups:
        subs[f"set:Group:{g}#members"] = len(subs)
    for f in folders:
        subs[f"set:Folder:{f}#"] = len(subs)
    v.subjects._ids = subs

    U, G, F = n_users, n_groups, n_folders
    NS_G, NS_F, NS_D = 0, 1, 2
    OBJ_G, OBJ_F, OBJ_D = 0, G, G + F  # object-id bases per family
    SUB_GSET, SUB_FSET = U, U + G  # subject-id bases for set subjects

    segs = []

    def seg(ns, obj, rel, subj, is_set, s_ns, s_obj, s_rel):
        n = len(obj)
        segs.append({
            "ns": np.full(n, ns, np.int32),
            "obj": np.asarray(obj, np.int32),
            "rel": np.full(n, rel, np.int32),
            "subj": np.asarray(subj, np.int32),
            "is_set": np.full(n, is_set, np.int32),
            "s_ns": np.full(n, s_ns, np.int32) if np.isscalar(s_ns)
            else np.asarray(s_ns, np.int32),
            "s_obj": np.full(n, s_obj, np.int32) if np.isscalar(s_obj)
            else np.asarray(s_obj, np.int32),
            "s_rel": np.full(n, s_rel, np.int32),
        })

    # group membership: users spread over groups
    ui = np.arange(U, dtype=np.int64)
    seg(NS_G, OBJ_G + ui % G, R_MEMBERS, ui, 0, -1, -1, -1)
    # nested groups every 3rd
    gi = np.arange(1, G, 3, dtype=np.int64)
    seg(NS_G, OBJ_G + gi - 1, R_MEMBERS, SUB_GSET + gi, 1,
        NS_G, OBJ_G + gi, R_MEMBERS)
    # folder tree rooted at f0
    fi = np.arange(1, F, dtype=np.int64)
    parents = (fi - 1) // fanout
    seg(NS_F, OBJ_F + fi, R_PARENTS, SUB_FSET + parents, 1,
        NS_F, OBJ_F + parents, R_EMPTY)
    # folder viewers/owners: direct users and group sets
    f3 = np.arange(0, F, 3, dtype=np.int64)
    seg(NS_F, OBJ_F + f3, R_VIEWERS,
        rng.integers(U, size=len(f3)), 0, -1, -1, -1)
    f5 = np.arange(0, F, 5, dtype=np.int64)
    seg(NS_F, OBJ_F + f5, R_OWNERS,
        rng.integers(U, size=len(f5)), 0, -1, -1, -1)
    f4 = np.arange(0, F, 4, dtype=np.int64)
    g4 = rng.integers(G, size=len(f4))
    seg(NS_F, OBJ_F + f4, R_VIEWERS, SUB_GSET + g4, 1,
        NS_G, OBJ_G + g4, R_MEMBERS)
    # docs under folders with occasional direct grants
    di = np.arange(n_docs, dtype=np.int64)
    df = rng.integers(F, size=n_docs)
    seg(NS_D, OBJ_D + di, R_PARENTS, SUB_FSET + df, 1,
        NS_F, OBJ_F + df, R_EMPTY)
    d7 = np.arange(0, n_docs, 7, dtype=np.int64)
    seg(NS_D, OBJ_D + d7, R_VIEWERS,
        rng.integers(U, size=len(d7)), 0, -1, -1, -1)
    d11 = np.arange(0, n_docs, 11, dtype=np.int64)
    seg(NS_D, OBJ_D + d11, R_OWNERS,
        rng.integers(U, size=len(d11)), 0, -1, -1, -1)
    d13 = np.arange(0, n_docs, 13, dtype=np.int64)
    seg(NS_D, OBJ_D + d13, R_BANNED,
        rng.integers(U, size=len(d13)), 0, -1, -1, -1)

    cols = {
        k: np.concatenate([s[k] for s in segs])
        for k in ("ns", "obj", "rel", "subj", "is_set", "s_ns", "s_obj",
                  "s_rel")
    }
    store = ColumnarTupleStore(v)
    store.bulk_load_ids(cols)
    return SynthGraph(
        store=store, manager=manager, users=users, docs=docs,
        folders=folders, groups=groups,
    )
