"""Synthetic Drive-style permission graphs for benchmarks and dry runs.

Models the BASELINE benchmark shapes: a folder tree with viewer/owner
assignments (some through group subject-sets), documents under folders, and
`view` permissions that chain computed-userset + tuple-to-userset rewrites up
the tree (the "5-hop rewrites" workload).  Mirrors the reference's deep/wide
benchmark generators (internal/check/bench_test.go:56-133) in spirit, at
configurable scale.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from ketotpu.api.types import RelationTuple, SubjectID, SubjectSet
from ketotpu.opl.parser import parse
from ketotpu.storage.memory import InMemoryTupleStore
from ketotpu.storage.namespaces import StaticNamespaceManager

SYNTH_OPL = """
import { Namespace, SubjectSet, Context } from "@ory/keto-namespace-types"

class User implements Namespace {}

class Group implements Namespace {
  related: {
    members: (User | Group)[]
  }
}

class Folder implements Namespace {
  related: {
    parents: Folder[]
    viewers: (User | SubjectSet<Group, "members">)[]
    owners: (User | SubjectSet<Group, "members">)[]
  }
  permits = {
    own: (ctx: Context): boolean =>
      this.related.owners.includes(ctx.subject) ||
      this.related.parents.traverse((p) => p.permits.own(ctx)),
    view: (ctx: Context): boolean =>
      this.related.viewers.includes(ctx.subject) ||
      this.permits.own(ctx) ||
      this.related.parents.traverse((p) => p.permits.view(ctx)),
  }
}

class Doc implements Namespace {
  related: {
    parents: Folder[]
    viewers: (User | SubjectSet<Group, "members">)[]
    owners: (User | SubjectSet<Group, "members">)[]
  }
  permits = {
    view: (ctx: Context): boolean =>
      this.related.viewers.includes(ctx.subject) ||
      this.related.owners.includes(ctx.subject) ||
      this.related.parents.traverse((p) => p.permits.view(ctx)),
  }
}
"""


@dataclass
class SynthGraph:
    store: InMemoryTupleStore
    manager: StaticNamespaceManager
    users: List[str]
    docs: List[str]
    folders: List[str]


def build_synth(
    *,
    n_users: int = 100,
    n_groups: int = 10,
    n_folders: int = 50,
    n_docs: int = 200,
    fanout: int = 4,
    seed: int = 0,
) -> SynthGraph:
    """Folder tree of degree ``fanout``; docs attach to random folders;
    viewers/owners assigned directly and through groups."""
    rng = np.random.default_rng(seed)
    namespaces, errors = parse(SYNTH_OPL)
    assert not errors, errors
    manager = StaticNamespaceManager(namespaces)
    store = InMemoryTupleStore()

    users = [f"u{i}" for i in range(n_users)]
    groups = [f"g{i}" for i in range(n_groups)]
    folders = [f"f{i}" for i in range(n_folders)]
    docs = [f"d{i}" for i in range(n_docs)]
    tuples: List[RelationTuple] = []

    def t(ns, obj, rel, subj):
        tuples.append(RelationTuple(ns, obj, rel, subj))

    # group membership: users spread over groups; a few nested groups
    for i, u in enumerate(users):
        t("Group", groups[i % n_groups], "members", SubjectID(u))
    for i in range(1, n_groups, 3):
        t("Group", groups[i - 1], "members", SubjectSet("Group", groups[i], "members"))

    # folder tree rooted at f0
    for i in range(1, n_folders):
        t("Folder", folders[i], "parents", SubjectSet("Folder", folders[(i - 1) // fanout]))
    # scatter viewers/owners on folders: direct users and group sets
    for i, f in enumerate(folders):
        if i % 3 == 0:
            t("Folder", f, "viewers", SubjectID(users[int(rng.integers(n_users))]))
        if i % 5 == 0:
            t("Folder", f, "owners", SubjectID(users[int(rng.integers(n_users))]))
        if i % 4 == 0:
            t("Folder", f, "viewers",
              SubjectSet("Group", groups[int(rng.integers(n_groups))], "members"))

    # docs under folders with occasional direct grants
    for i, d in enumerate(docs):
        t("Doc", d, "parents", SubjectSet("Folder", folders[int(rng.integers(n_folders))]))
        if i % 7 == 0:
            t("Doc", d, "viewers", SubjectID(users[int(rng.integers(n_users))]))
        if i % 11 == 0:
            t("Doc", d, "owners", SubjectID(users[int(rng.integers(n_users))]))

    store.write_relation_tuples(*tuples)
    return SynthGraph(
        store=store, manager=manager, users=users, docs=docs, folders=folders
    )


def synth_queries(
    graph: SynthGraph, n: int, *, seed: int = 1
) -> List[RelationTuple]:
    """Mixed doc-view checks: random (doc, user) pairs."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        d = graph.docs[int(rng.integers(len(graph.docs)))]
        u = graph.users[int(rng.integers(len(graph.users)))]
        out.append(RelationTuple("Doc", d, "view", SubjectID(u)))
    return out
