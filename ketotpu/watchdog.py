"""Regression watchdog: push-on-regression over the pull-only diagnostics.

PR 6 (compile observatory, wave ledger, profiler) and PR 11 (trace
store, shadow plane) built deep diagnostic surfaces — but every one is
pull-only: an operator has to already suspect trouble to curl them.
This thread closes the loop.  Every ``observability.watchdog.interval_s``
it evaluates four rules over those surfaces:

* ``after_warm_compile`` — the compile observatory counted a backend
  compile after the engine declared itself warm (the BENCH_r05 cliff
  class);
* ``device_ms_drift`` — the wave ledger's device-ms p50 drifted more
  than ``drift_pct`` above a rolling baseline learned over the first
  ``baseline_waves`` waves (and re-learned after each incident);
* ``shadow_divergence`` — the shadow plane filed new divergence records
  since the last tick;
* ``burn_alarm`` — the SLO engine's fast-window burn rate crossed
  ``burn_threshold`` (error budget burning faster than N× sustainable).

A firing rule files a bounded incident record (``GET /debug/incidents``),
bumps ``keto_incidents_total{rule}``, and force-promotes the implicated
traces through the PR-11 :meth:`TraceStore.force_promote` hook — the
divergence's own trace ids when the shadow ledger names them, else the
slowest traceparents of the most recent waves — so the anatomy of the
regressing requests is preserved before the recent ring evicts them.
Level-triggered rules (drift, burn) are edge-filtered: one incident on
entering violation, re-armed only after the condition clears.

Config-gated (``auto_profile``), an incident also arms ONE automatic
profiler capture per ``profile_cooldown_s`` on a side thread —
``ProfilerDisabled``/``ProfilerBusy`` are swallowed; the watchdog never
throws, never blocks the serving path, and every rule evaluation is
wrapped so a diagnostics failure cannot kill the thread.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ketotpu.observability import parse_traceparent

INCIDENTS_METRIC = "keto_incidents_total"

RULES = (
    "after_warm_compile",
    "device_ms_drift",
    "shadow_divergence",
    "burn_alarm",
    "overload",
)

#: how many recent waves to mine for implicated traceparents when the
#: firing rule does not name trace ids itself
_IMPLICATE_WAVES = 4


class Watchdog:
    """Background rule evaluator + bounded incident log."""

    def __init__(
        self,
        registry,
        *,
        interval_s: float = 5.0,
        baseline_waves: int = 32,
        drift_pct: float = 75.0,
        incident_cap: int = 64,
        burn_threshold: float = 2.0,
        auto_profile: bool = False,
        profile_cooldown_s: float = 600.0,
        profile_seconds: float = 2.0,
    ):
        self._r = registry
        self.interval_s = max(0.25, float(interval_s))
        self.baseline_waves = max(1, int(baseline_waves))
        self.drift_pct = float(drift_pct)
        self.burn_threshold = float(burn_threshold)
        self.auto_profile = bool(auto_profile)
        self.profile_cooldown_s = float(profile_cooldown_s)
        self.profile_seconds = float(profile_seconds)
        self._lock = threading.Lock()
        self._incidents: deque = deque(maxlen=max(1, int(incident_cap)))
        self._next_id = 0
        self.ticks = 0
        # rule state
        self._primed = False
        self._seen_after_warm = 0
        self._seen_divergences = 0
        self._baseline_device_ms: Optional[float] = None
        self._baseline_samples = 0
        self._active: set = set()  # level-triggered rules currently firing
        self._last_profile: Optional[float] = None  # None = never captured
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        metrics = registry.metrics()
        if metrics is not None:
            # pre-register the vocabulary so `== 0` is provable on scrape
            for rule in RULES:
                metrics.counter(
                    INCIDENTS_METRIC, 0,
                    help="watchdog incidents filed by rule", rule=rule,
                )

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "Watchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="keto-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - diagnostics never crash
                pass

    # -- rule evaluation ------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> List[Dict]:
        """Evaluate every rule once; returns the incidents filed (tests
        drive this directly for determinism)."""
        t = time.time() if now is None else float(now)
        with self._lock:
            self.ticks += 1
            first = not self._primed
            self._primed = True
        if first:
            # adopt the current counter floors: the compile observatory is
            # process-global and the shadow ledger may predate this
            # watchdog — what happened before arming is history, not a
            # regression
            self._prime()
            return []
        filed: List[Dict] = []
        for rule in (
            self._rule_after_warm_compile,
            self._rule_device_ms_drift,
            self._rule_shadow_divergence,
            self._rule_burn_alarm,
            self._rule_overload,
        ):
            try:
                inc = rule(t)
            except Exception:  # noqa: BLE001 - one broken surface must
                inc = None     # not mute the other rules
            if inc is not None:
                filed.append(inc)
        return filed

    def _prime(self) -> None:
        try:
            self._seen_after_warm = int(
                self._r.compile_watch().snapshot().get(
                    "compiles_after_warm", 0
                )
            )
        except Exception:  # noqa: BLE001
            pass
        try:
            shadow = self._r.shadow()
            if shadow is not None:
                self._seen_divergences = int(
                    getattr(shadow, "divergences", 0)
                )
        except Exception:  # noqa: BLE001
            pass
        try:
            slo = self._r.slo()
            if slo is not None:
                slo.sample()
        except Exception:  # noqa: BLE001
            pass

    def _rule_after_warm_compile(self, now: float) -> Optional[Dict]:
        watch = self._r.compile_watch()
        snap = watch.snapshot()
        n = int(snap.get("compiles_after_warm", 0))
        if n <= self._seen_after_warm:
            return None
        fresh = [
            {k: e.get(k) for k in ("fn", "signature", "duration_ms", "ts")}
            for e in snap.get("log", []) if e.get("after_warm")
        ][-(n - self._seen_after_warm):]
        self._seen_after_warm = n
        return self._file(
            "after_warm_compile", now,
            detail={"compiles_after_warm": n, "compiles": fresh},
            trace_ids=self._recent_wave_traces(),
        )

    def _rule_device_ms_drift(self, now: float) -> Optional[Dict]:
        stats = self._r.wave_ledger().stats()
        if int(stats.get("waves_in_ring", 0)) < 1:
            return None
        p50 = float(stats.get("device_ms_p50", 0.0))
        if (self._baseline_device_ms is None
                or self._baseline_samples < self.baseline_waves):
            # still learning: fold the observation into the baseline
            b = self._baseline_device_ms
            self._baseline_device_ms = (
                p50 if b is None else 0.9 * b + 0.1 * p50
            )
            self._baseline_samples += int(stats.get("waves_in_ring", 0))
            return None
        baseline = self._baseline_device_ms
        threshold = baseline * (1.0 + self.drift_pct / 100.0)
        if p50 <= threshold or baseline <= 0.0:
            self._active.discard("device_ms_drift")
            # healthy: keep tracking the slow trend
            self._baseline_device_ms = 0.95 * baseline + 0.05 * p50
            return None
        if "device_ms_drift" in self._active:
            return None
        self._active.add("device_ms_drift")
        return self._file(
            "device_ms_drift", now,
            detail={
                "device_ms_p50": p50,
                "baseline_ms": round(baseline, 3),
                "drift_pct_observed": round(
                    (p50 / baseline - 1.0) * 100.0, 1
                ),
                "drift_pct_threshold": self.drift_pct,
            },
            trace_ids=self._recent_wave_traces(),
        )

    def _rule_shadow_divergence(self, now: float) -> Optional[Dict]:
        shadow = self._r.shadow()
        if shadow is None:
            return None
        n = int(getattr(shadow, "divergences", 0))
        if n <= self._seen_divergences:
            return None
        fresh = shadow.ledger()[-(n - self._seen_divergences):]
        self._seen_divergences = n
        tids = [r.get("trace_id") for r in fresh if r.get("trace_id")]
        return self._file(
            "shadow_divergence", now,
            detail={
                "divergences_total": n,
                "records": [
                    {k: r.get(k) for k in (
                        "tuple", "served", "oracle", "tier", "wave",
                        "trace_id",
                    )} for r in fresh
                ],
            },
            trace_ids=tids or self._recent_wave_traces(),
        )

    def _rule_burn_alarm(self, now: float) -> Optional[Dict]:
        slo = self._r.slo()
        if slo is None:
            return None
        slo.sample()
        burn = slo.max_burn("fast")
        if burn < self.burn_threshold:
            self._active.discard("burn_alarm")
            return None
        if "burn_alarm" in self._active:
            return None
        self._active.add("burn_alarm")
        return self._file(
            "burn_alarm", now,
            detail={
                "fast_burn": round(burn, 4),
                "threshold": self.burn_threshold,
                "fast": slo.window_report(slo.fast_window_s),
            },
            trace_ids=self._recent_wave_traces(),
        )

    def _rule_overload(self, now: float) -> Optional[Dict]:
        """Edge-triggered on the overload plane leaving stage 0: one
        incident per brownout episode, cleared when the ladder returns
        to normal."""
        ov = self._r.overload()
        if ov is None or ov.stage < 1:
            self._active.discard("overload")
            return None
        if "overload" in self._active:
            return None
        self._active.add("overload")
        snap = {}
        try:
            snap = ov.snapshot()
        except Exception:  # noqa: BLE001
            pass
        return self._file(
            "overload", now,
            detail={
                "stage": ov.stage,
                "stage_name": snap.get("stage_name", ""),
                "admission": snap.get("admission", {}),
                "signals": snap.get("signals", {}),
            },
            trace_ids=self._recent_wave_traces(),
        )

    # -- incident plumbing ----------------------------------------------------

    def _recent_wave_traces(self) -> List[str]:
        """Trace ids of the slowest members of the most recent waves —
        the implicated anatomy when a rule has no trace ids of its own."""
        tids: List[str] = []
        try:
            waves = self._r.wave_ledger().snapshot(_IMPLICATE_WAVES)
        except Exception:  # noqa: BLE001
            return tids
        for w in waves:
            for s in w.get("slowest") or []:
                parsed = parse_traceparent(s.get("traceparent"))
                if parsed and parsed[0] not in tids:
                    tids.append(parsed[0])
        return tids

    def _file(self, rule: str, now: float, *, detail: Dict,
              trace_ids: List[str]) -> Dict:
        promoted: List[str] = []
        try:
            store = self._r.trace_store()
        except Exception:  # noqa: BLE001
            store = None
        if store is not None:
            for tid in trace_ids:
                try:
                    if store.force_promote(tid, f"incident:{rule}"):
                        promoted.append(tid)
                except Exception:  # noqa: BLE001
                    pass
        with self._lock:
            self._next_id += 1
            incident = {
                "id": self._next_id,
                "rule": rule,
                "ts": round(now, 3),
                "detail": detail,
                "trace_ids": trace_ids,
                "promoted": promoted,
            }
            self._incidents.append(incident)
        metrics = self._r.metrics()
        if metrics is not None:
            metrics.counter(
                INCIDENTS_METRIC, 1,
                help="watchdog incidents filed by rule", rule=rule,
            )
        logger = None
        log = getattr(self._r, "logger", None)
        if callable(log):
            try:
                logger = log()
            except Exception:  # noqa: BLE001
                logger = None
        if logger is not None:
            logger.warning(
                "watchdog incident #%d rule=%s traces=%s detail=%s",
                incident["id"], rule, trace_ids, detail,
            )
        incident["profile"] = self._maybe_profile(now)
        return incident

    def _maybe_profile(self, now: float) -> str:
        if not self.auto_profile:
            return "disabled"
        with self._lock:
            if (self._last_profile is not None
                    and now - self._last_profile < self.profile_cooldown_s):
                return "cooldown"
            self._last_profile = now

        def _capture():
            from ketotpu.profiler import ProfilerBusy, ProfilerDisabled

            try:
                self._r.profiler().capture(self.profile_seconds)
            except (ProfilerDisabled, ProfilerBusy):
                pass
            except Exception:  # noqa: BLE001 - best-effort evidence only
                pass

        threading.Thread(
            target=_capture, name="keto-watchdog-profile", daemon=True
        ).start()
        return "armed"

    # -- read side ------------------------------------------------------------

    def incidents(self, n: int = 0) -> List[Dict]:
        """Newest-first incident records (``GET /debug/incidents``)."""
        with self._lock:
            out = [dict(i) for i in reversed(self._incidents)]
        return out[:n] if n > 0 else out

    def stats(self) -> Dict:
        with self._lock:
            return {
                "ticks": self.ticks,
                "incidents_filed": self._next_id,
                "incidents_held": len(self._incidents),
                "interval_s": self.interval_s,
                "burn_threshold": self.burn_threshold,
                "drift_pct": self.drift_pct,
                "baseline_device_ms": (
                    round(self._baseline_device_ms, 3)
                    if self._baseline_device_ms is not None else None
                ),
                "auto_profile": self.auto_profile,
                "active_rules": sorted(self._active),
            }
