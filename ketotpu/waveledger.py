"""Wave ledger: a lock-cheap ring of the last N dispatched waves.

The flight recorder (flightrec.py) answers "where did THIS request's
milliseconds go"; the ledger answers the dual question — "what did the
WAVE this request rode look like": how many slots, how long the window
held them, how long the device took, how many were answered by the
cache/singleflight/Leopard short-circuits instead of the BFS, and which
requests dragged the tail.  The coalescer records one entry per wave
(`CoalescingEngine._serve`), the device engines supply the counter and
phase deltas, and the two views cross-link both directions: flight
recorder entries already carry ``wave=``, and each ledger entry carries
the traceparents of its slowest member requests.

Served at ``GET /debug/waves`` on the metrics port and by
``keto-tpu status --debug``.  Recording happens on the single coalescer
worker thread, so the ring needs a lock only to keep ``snapshot`` (a
scrape-path read) consistent — the hot path takes it once per WAVE, not
per request.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Dict, List, Optional


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank (ceiling) percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = math.ceil(q * (len(sorted_vals) - 1))
    return sorted_vals[min(len(sorted_vals) - 1, max(0, idx))]


class WaveLedger:
    """Ring of per-wave dispatch records + monotonic wave-id source."""

    def __init__(self, capacity: int = 256):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._next_id = 0
        self.recorded = 0  # total waves ever recorded (ring evicts)

    def next_wave_id(self) -> int:
        """Monotonic wave id — the same id flight-recorder entries carry
        as ``wave=``, so the two debug views join on it."""
        with self._lock:
            self._next_id += 1
            return self._next_id

    def record(self, entry: Dict) -> None:
        """File one wave's record (called once per wave, coalescer thread)."""
        with self._lock:
            self._ring.append(dict(entry))
            self.recorded += 1

    def snapshot(self, n: Optional[int] = None,
                 wave: Optional[int] = None) -> List[Dict]:
        """Newest-first wave records; ``wave`` filters to one id."""
        with self._lock:
            out = [dict(e) for e in reversed(self._ring)]
        if wave is not None:
            out = [e for e in out if e.get("wave") == wave]
        if n is not None:
            out = out[: max(0, int(n))]
        return out

    def stats(self) -> Dict:
        """Occupancy/wait aggregates over the current ring — the serving
        bench's before/after baseline for the batching-efficiency work."""
        with self._lock:
            entries = list(self._ring)
            recorded = self.recorded
        sizes = sorted(float(e.get("size", 0)) for e in entries)
        waits = sorted(
            float(e.get("window_wait_ms_p50", 0.0)) for e in entries
        )
        devs = sorted(float(e.get("device_ms", 0.0)) for e in entries)
        n = len(entries)
        # fused tiered dispatch (engine/fused.py): ring-wide sums of the
        # per-wave deltas; `fused_waves == fused_d2h_fetches` IS the
        # single-fetch-per-wave invariant the serving bench asserts
        fused_waves = fused_d2h = 0
        fused_tiers: Dict[str, int] = {}
        # multi-host mesh: ring-wide sums of each wave's per-peer
        # shipped-row deltas — how much of the recent window crossed DCN
        peer_rows: Dict[str, int] = {}
        for e in entries:
            f = e.get("fused") or {}
            fused_waves += int(f.get("waves", 0))
            fused_d2h += int(f.get("d2h_fetches", 0))
            for t, d in (f.get("tiers") or {}).items():
                fused_tiers[t] = fused_tiers.get(t, 0) + int(d)
            for h, d in (e.get("peers") or {}).items():
                peer_rows[h] = peer_rows.get(h, 0) + int(d)
        return {
            "waves_recorded": recorded,
            "waves_in_ring": n,
            "wave_size_mean": round(sum(sizes) / n, 3) if n else 0.0,
            "wave_size_p50": _percentile(sizes, 0.50),
            "wave_size_p95": _percentile(sizes, 0.95),
            "window_wait_ms_p50": round(_percentile(waits, 0.50), 3),
            "window_wait_ms_p95": round(_percentile(waits, 0.95), 3),
            "device_ms_p50": round(_percentile(devs, 0.50), 3),
            "device_ms_p95": round(_percentile(devs, 0.95), 3),
            "fused_waves": fused_waves,
            "fused_d2h_fetches": fused_d2h,
            "fused_tier_rows": fused_tiers,
            "peer_rows": peer_rows,
        }
