#!/bin/sh
# Regenerate Python protobuf bindings from the vendored wire contract.
set -e
cd "$(dirname "$0")/.."
protoc -Iproto -I/usr/include \
  --python_out=ketotpu/proto \
  proto/ory/keto/relation_tuples/v1alpha2/*.proto \
  proto/ory/keto/opl/v1alpha1/*.proto \
  proto/health/v1/health.proto
