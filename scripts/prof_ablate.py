"""Ablate expand_phase at the dominant level shape: which gather family
dominates?  Times jitted variants with each family stubbed out.

Families:
  lookups  — _node_lookup calls (nt_ hash probes)
  members  — _member calls (mt_ hash probes)
  params   — f_css_* / f_ttu_* / f_direct_ok / f_expand_ok small-table rows
  children — arena child construction (edge gathers + aps indexing)
  pack     — hash-scatter dedup + compaction
"""

from __future__ import annotations

import sys
import time
from unittest import mock

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from ketotpu.engine import fastpath as fp  # noqa: E402
from ketotpu.engine import hashtab  # noqa: E402
from ketotpu.engine.tpu import DeviceCheckEngine  # noqa: E402
from ketotpu.utils.synth import build_synth, synth_queries  # noqa: E402

BATCH = 16384


def timeit(fn, n=3, warmup=1):
    for _ in range(warmup):
        jax.block_until_ready(fn())
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main():
    graph = build_synth(
        n_users=2000, n_groups=100, n_folders=2000, n_docs=20000, seed=0
    )
    eng = DeviceCheckEngine(
        graph.store, graph.manager, frontier=98304, arena=196608,
        max_batch=BATCH,
    )
    eng.snapshot()
    snap = eng.snapshot()
    g = eng._device_arrays
    print("Kc =", snap.flat.css_rel.shape[2], " Kt =", snap.flat.ttu_via.shape[2],
          " NS,R =", snap.flat.direct_ok.shape)

    queries = synth_queries(graph, BATCH, seed=7)
    enc = eng._encode(snap, queries, 0)
    err, general = eng._classify(snap, enc[0], enc[2])
    act = ~(err | general)
    sched = fp.level_schedule(BATCH, eng.frontier, eng.arena, eng.max_depth)

    # drive to level 2 (the bulge) and freeze that state
    s = fp.init_state(*enc, act, frontier=sched[0][0])
    s["f_depth"] = jnp.minimum(s["f_depth"], len(sched))
    for i in range(2):
        f, a = sched[i]
        nxt_f = sched[i + 1][0]
        children, qf, qo, qd = fp.expand_phase(g, s, arena=a, max_width=100)
        nxt, qo = fp.pack_phase(children, qf, qo, frontier=nxt_f,
                                ns_dim=g["f_direct_ok"].shape[0],
                                rel_dim=g["f_direct_ok"].shape[1])
        s = dict(nxt, q_found=qf, q_over=qo, q_dirty=qd, q_subj=s["q_subj"])
    s = jax.block_until_ready(jax.jit(lambda x: x)(s))
    f2, a2 = sched[2]
    nxt_f2 = sched[3][0]
    NS, R = g["f_direct_ok"].shape

    def full():
        c, qf, qo, qd = fp.expand_phase(g, s, arena=a2, max_width=100)
        nxt, qo = fp.pack_phase(c, qf, qo, frontier=nxt_f2, ns_dim=NS, rel_dim=R)
        return nxt, qf, qo, qd

    t_full = timeit(jax.jit(full))
    print(f"full level:            {t_full*1000:7.1f} ms")

    def expand_only():
        return fp.expand_phase(g, s, arena=a2, max_width=100)

    t_exp = timeit(jax.jit(expand_only))
    print(f"expand only:           {t_exp*1000:7.1f} ms  "
          f"(pack = {max(t_full-t_exp,0)*1000:.1f} by difference)")

    # stub node lookups: cheap arithmetic instead of hash probes
    def fake_node_lookup(g_, ns, obj, rel):
        num_rels = g_["f_direct_ok"].shape[1]
        return jnp.where(
            (ns >= 0) & (obj >= 0) & (rel >= 0),
            (ns * num_rels + rel + obj) % jnp.int32(1000), -1
        ).astype(jnp.int32)

    with mock.patch.object(fp, "_node_lookup", fake_node_lookup):
        t_nolook = timeit(jax.jit(expand_only))
    print(f"expand, no node-lookups: {t_nolook*1000:5.1f} ms  "
          f"(lookups = {(t_exp-t_nolook)*1000:.1f})")

    def fake_member(g_, node, subj):
        return (node + subj) % 7 == 0

    with mock.patch.object(fp, "_member", fake_member):
        t_nomem = timeit(jax.jit(expand_only))
    print(f"expand, no member probes: {t_nomem*1000:4.1f} ms  "
          f"(members = {(t_exp-t_nomem)*1000:.1f})")

    with mock.patch.object(fp, "_node_lookup", fake_node_lookup), \
         mock.patch.object(fp, "_member", fake_member):
        t_neither = timeit(jax.jit(expand_only))
    print(f"expand, neither:       {t_neither*1000:7.1f} ms")


if __name__ == "__main__":
    main()
