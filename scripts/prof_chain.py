"""Fused single-program vs chained async per-level dispatches.

prof_levels.py showed each level costs ~120-150ms *synced* but the
probe-only final level (no compute) still costs ~93ms — i.e. the tunnel
round-trip dominates per-level sync cost and per-level device compute is
only ~30-60ms.  Yet the fused 5-level program costs ~984ms — far above
compute + one RTT.  Hypothesis: chaining the levels as 5 separately
jitted dispatches (async, device-resident state, ONE final sync) beats
the single fused program.

Also sweeps batch size and probe depth.
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from ketotpu.engine import fastpath as fp  # noqa: E402
from ketotpu.engine.tpu import DeviceCheckEngine  # noqa: E402
from ketotpu.utils.synth import build_synth, synth_queries  # noqa: E402


def timeit(fn, n=3, warmup=1):
    for _ in range(warmup):
        jax.block_until_ready(fn())
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append(time.perf_counter() - t0)
    return min(ts)


@functools.partial(
    jax.jit, static_argnames=("frontier", "arena", "nxt_frontier",
                              "max_width", "probe_only"))
def one_level(g, s, *, frontier, arena, nxt_frontier, max_width, probe_only):
    NS, R = g["f_direct_ok"].shape
    children, q_found, q_over, q_dirty = fp.expand_phase(
        g, s, arena=arena, max_width=max_width, probe_only=probe_only
    )
    nxt, q_over = fp.pack_phase(
        children, q_found, q_over, frontier=nxt_frontier, ns_dim=NS, rel_dim=R
    )
    return dict(nxt, q_found=q_found, q_over=q_over, q_dirty=q_dirty,
                q_subj=s["q_subj"])


@functools.partial(jax.jit, static_argnames=("frontier",))
def init_packed(qpack, *, frontier):
    return fp._init_state(
        qpack[0], qpack[1], qpack[2], qpack[3],
        jnp.minimum(qpack[4], 5), qpack[5].astype(bool),
        frontier=frontier,
    )


@jax.jit
def verdict(s):
    return (
        s["q_found"].astype(jnp.uint8)
        | (s["q_over"].astype(jnp.uint8) << 1)
        | (s["q_dirty"].astype(jnp.uint8) << 2)
    )


def chained(g, qpack, sched, max_width):
    s = init_packed(qpack, frontier=sched[0][0])
    for i, (f, a) in enumerate(sched):
        nxt_f = sched[i + 1][0] if i + 1 < len(sched) else 1
        s = one_level(
            g, s, frontier=f, arena=a, nxt_frontier=nxt_f,
            max_width=max_width, probe_only=(i == len(sched) - 1),
        )
    return verdict(s)


def main():
    print(f"devices: {jax.devices()}")
    graph = build_synth(
        n_users=2000, n_groups=100, n_folders=2000, n_docs=20000, seed=0
    )
    for batch in (4096, 16384):
        eng = DeviceCheckEngine(
            graph.store, graph.manager,
            frontier=6 * batch, arena=12 * batch, max_batch=batch,
        )
        eng.snapshot()
        queries = synth_queries(graph, batch, seed=2)
        snap = eng.snapshot()
        enc = eng._encode(snap, queries, 0)
        err, general = eng._classify(snap, enc[0], enc[2])
        fast_active = ~(err | general)
        qpack = np.stack([*enc, fast_active.astype(np.int32)]).astype(np.int32)
        g = eng._device_arrays
        sched = fp.level_schedule(batch, eng.frontier, eng.arena, eng.max_depth)

        t_fused = timeit(lambda: fp.run_fast_packed(
            g, qpack, frontier=eng.frontier, arena=eng.arena,
            max_depth=eng.max_depth, max_width=eng.max_width))
        t_chain = timeit(lambda: chained(g, qpack, sched, eng.max_width))
        # sanity: same verdicts
        vf = np.asarray(fp.run_fast_packed(
            g, qpack, frontier=eng.frontier, arena=eng.arena,
            max_depth=eng.max_depth, max_width=eng.max_width)[0])
        vc = np.asarray(chained(g, qpack, sched, eng.max_width))
        assert np.array_equal(vf, vc), "verdict mismatch"
        print(f"batch={batch}: fused={t_fused*1000:8.1f} ms   "
              f"chained={t_chain*1000:8.1f} ms   "
              f"(chained {batch/t_chain:.0f} checks/s)")

        # two batches in flight: dispatch both chains, sync both
        def two():
            v1 = chained(g, qpack, sched, eng.max_width)
            v2 = chained(g, qpack, sched, eng.max_width)
            return v1, v2

        t_two = timeit(two)
        print(f"  two chained batches in flight: {t_two*1000:8.1f} ms "
              f"({2*batch/t_two:.0f} checks/s)")


if __name__ == "__main__":
    main()
