"""Intra-expand ablation: which part of child construction dominates?

Stages (cumulative, each jitted separately, DCE prevented by returning
the stage's arrays):
  s1: probes + seg_len + cumsum             (frontier-sized)
  s2: + arena_assign (scatter + max-scan)   (arena-sized scan)
  s3: + segment decomposition (cum_p gather, seg_idx, prev_cum)
  s4: + aps gathers (parent cols) + edge index math
  s5: + edge gathers + child cols (= full expand_phase)
Then pack_phase alone, and its hash-scatter vs compaction halves.
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from ketotpu.engine import fastpath as fp  # noqa: E402
from ketotpu.engine.xutil import arena_assign  # noqa: E402
from ketotpu.engine.tpu import DeviceCheckEngine  # noqa: E402
from ketotpu.utils.synth import build_synth, synth_queries  # noqa: E402

BATCH = 16384


def timeit(fn, n=3, warmup=1):
    for _ in range(warmup):
        jax.block_until_ready(fn())
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main():
    graph = build_synth(
        n_users=2000, n_groups=100, n_folders=2000, n_docs=20000, seed=0
    )
    eng = DeviceCheckEngine(
        graph.store, graph.manager, frontier=98304, arena=196608,
        max_batch=BATCH,
    )
    eng.snapshot()
    snap = eng.snapshot()
    g = eng._device_arrays
    queries = synth_queries(graph, BATCH, seed=7)
    enc = eng._encode(snap, queries, 0)
    err, general = eng._classify(snap, enc[0], enc[2])
    act = ~(err | general)
    sched = fp.level_schedule(BATCH, eng.frontier, eng.arena, eng.max_depth)
    s = fp.init_state(*enc, act, frontier=sched[0][0])
    s["f_depth"] = jnp.minimum(s["f_depth"], len(sched))
    for i in range(2):
        f, a = sched[i]
        nxt_f = sched[i + 1][0]
        children, qf, qo, qd = fp.expand_phase(g, s, arena=a, max_width=100)
        nxt, qo = fp.pack_phase(children, qf, qo, frontier=nxt_f,
                                ns_dim=4, rel_dim=8)
        s = dict(nxt, q_found=qf, q_over=qo, q_dirty=qd, q_subj=s["q_subj"])
    s = jax.block_until_ready(jax.jit(lambda x: x)(s))
    A = sched[2][1]
    F = s["f_qid"].shape[0]
    max_width = 100
    print(f"level2 shape: F={F} A={A}")

    def stage(upto):
        NS, R = g["f_direct_ok"].shape
        Kc = g["f_css_rel"].shape[2]
        Kt = g["f_ttu_via"].shape[2]
        Q = s["q_found"].shape[0]
        qid, ns, obj, rel = s["f_qid"], s["f_ns"], s["f_obj"], s["f_rel"]
        d, skip, force = s["f_depth"], s["f_skip"], s["f_force"]
        q_found, q_over, q_subj = s["q_found"], s["q_over"], s["q_subj"]
        qc = jnp.clip(qid, 0, Q - 1)
        live = (qid >= 0) & ~q_found[qc]
        subj = q_subj[qc]
        nsc = jnp.clip(ns, 0, NS - 1)
        relc = jnp.clip(rel, 0, R - 1)
        cfg = (ns >= 0) & (ns < NS) & (rel >= 0) & (rel < R)
        node = fp._node_lookup(g, ns, obj, rel)
        dok = jnp.where(cfg, g["f_direct_ok"][nsc, relc], True) & ~skip
        eok = jnp.where(cfg, g["f_expand_ok"][nsc, relc], True)
        self_member = fp._member(g, node, subj)
        found = live & self_member & ((dok & (d >= 2)) | force)
        css_rel = jnp.where(cfg[:, None], g["f_css_rel"][nsc, relc], -1)
        css_dec = g["f_css_dec"][nsc, relc]
        css_probe = g["f_css_probe"][nsc, relc]
        css_ok = live[:, None] & (css_rel >= 0) & (d[:, None] - css_dec >= 1)
        for k in range(Kc):
            cnode = fp._node_lookup(g, ns, obj, css_rel[:, k])
            found = found | (css_ok[:, k] & css_probe[:, k]
                             & fp._member(g, cnode, subj))
        q_found2 = q_found.at[qc].max(found)
        live2 = live & ~q_found2[qc]
        exp_read = live2 & eok & (d >= 2)
        exp_deg = jnp.where(exp_read, fp._row_deg(g, node), 0)
        css_need = (css_ok & live2[:, None]
                    & (d[:, None] - css_dec - 1 >= 1)).astype(jnp.int32)
        ttu_via = jnp.where(cfg[:, None], g["f_ttu_via"][nsc, relc], -1)
        ttu_tgt = g["f_ttu_tgt"][nsc, relc]
        ttu_dec = g["f_ttu_dec"][nsc, relc]
        ttu_ok = live2[:, None] & (ttu_via >= 0) & (d[:, None] - ttu_dec >= 2)
        ttu_node_cols = []
        ttu_deg_cols = []
        for k in range(Kt):
            tn = fp._node_lookup(g, ns, obj, ttu_via[:, k])
            ttu_node_cols.append(tn)
            ttu_deg_cols.append(jnp.where(ttu_ok[:, k], fp._row_deg(g, tn), 0))
        ttu_nodes = jnp.stack(ttu_node_cols, axis=1)
        seg_len = jnp.stack(
            [exp_deg] + [css_need[:, k] for k in range(Kc)] + ttu_deg_cols,
            axis=1)
        seg_cum = jnp.cumsum(seg_len, axis=1)
        counts = seg_cum[:, -1]
        if upto == 1:
            return q_found2, counts, ttu_nodes
        offsets, _total, ap, ao = arena_assign(counts, A)
        if upto == 2:
            return q_found2, offsets, ap, ao
        fits = offsets + counts <= A
        q_over2 = q_over.at[qc].max(live2 & (counts > 0) & ~fits)
        aps = jnp.clip(ap, 0, F - 1)
        src_ok = (ap >= 0) & fits[aps]
        cum_p = seg_cum[aps]
        S = 1 + Kc + Kt
        seg_idx = jnp.clip(
            jnp.sum((ao[:, None] >= cum_p).astype(jnp.int32), axis=1), 0, S - 1)
        prev_cum = jnp.where(
            seg_idx > 0,
            jnp.take_along_axis(
                cum_p, jnp.clip(seg_idx - 1, 0, S - 1)[:, None], 1)[:, 0],
            0)
        off = ao - prev_cum
        if upto == 3:
            return q_found2, q_over2, seg_idx, off, src_ok
        p_ns, p_obj, p_d = ns[aps], obj[aps], d[aps]
        p_qid = qid[aps]
        is_exp = src_ok & (seg_idx == 0)
        is_css = src_ok & (seg_idx >= 1) & (seg_idx <= Kc)
        css_k = jnp.clip(seg_idx - 1, 0, Kc - 1)
        is_ttu = src_ok & (seg_idx > Kc)
        ttu_k = jnp.clip(seg_idx - 1 - Kc, 0, Kt - 1)
        rp = g["row_ptr"]
        base_exp = rp[jnp.clip(node[aps], 0, rp.shape[0] - 2)]
        ttu_node_p = jnp.take_along_axis(ttu_nodes[aps], ttu_k[:, None], 1)[:, 0]
        base_ttu = rp[jnp.clip(ttu_node_p, 0, rp.shape[0] - 2)]
        eidx = jnp.clip(
            jnp.where(is_ttu, base_ttu, base_exp) + off, 0,
            g["edge_ns"].shape[0] - 1)
        if upto == 4:
            return q_found2, q_over2, eidx, is_exp, is_css, p_qid, p_d
        e_ns, e_obj, e_rel = (g["edge_ns"][eidx], g["edge_obj"][eidx],
                              g["edge_rel"][eidx])
        css_rel_p = jnp.take_along_axis(css_rel[aps], css_k[:, None], 1)[:, 0]
        css_dec_p = jnp.take_along_axis(css_dec[aps], css_k[:, None], 1)[:, 0]
        ttu_tgt_p = jnp.take_along_axis(ttu_tgt[aps], ttu_k[:, None], 1)[:, 0]
        ttu_dec_p = jnp.take_along_axis(ttu_dec[aps], ttu_k[:, None], 1)[:, 0]
        ch_ns = jnp.where(is_css, p_ns, e_ns)
        ch_obj = jnp.where(is_css, p_obj, e_obj)
        ch_rel = jnp.select([is_css, is_ttu], [css_rel_p, ttu_tgt_p], e_rel)
        ch_d = jnp.select([is_css, is_ttu],
                          [p_d - css_dec_p - 1, p_d - ttu_dec_p - 1], p_d - 1)
        ch_skip = is_exp | is_css
        ch_qid = jnp.where(src_ok, p_qid, -1)
        p_exp_deg = exp_deg[aps]
        trunc = is_exp & (p_exp_deg > max_width) & (off >= max_width - 1)
        ch_force = is_exp
        ch_d = jnp.where(trunc, 0, ch_d)
        alive = src_ok & (is_exp | (ch_d >= 1))
        alive = alive & ~q_found2[jnp.clip(ch_qid, 0, Q - 1)]
        return (q_found2, q_over2, ch_ns, ch_obj, ch_rel, ch_d, ch_skip,
                ch_qid, ch_force, alive)

    prev = 0.0
    for u in (1, 2, 3, 4, 5):
        t = timeit(jax.jit(lambda u=u: stage(u)))
        print(f"stage {u}: {t*1000:7.1f} ms  (delta {1000*(t-prev):+7.1f})")
        prev = t

    # pack: scatter-dedup half vs compaction half
    children, qf, qo, qd = jax.block_until_ready(
        jax.jit(lambda: fp.expand_phase(g, s, arena=A, max_width=100))())
    t_pack = timeit(jax.jit(lambda: fp.pack_phase(
        children, qf, qo, frontier=sched[3][0], ns_dim=4, rel_dim=8)))
    print(f"pack total: {t_pack*1000:7.1f} ms")


if __name__ == "__main__":
    main()
