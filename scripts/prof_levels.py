"""Per-level device profiling of the fast path on the real chip.

Answers VERDICT r2 #2a: where does the ~1s per 16,384-query batch go?
Times (a) end-to-end batch_check, (b) the fused dispatch alone, (c) each
level as its own dispatch at the schedule's sizes, (d) host-side encode,
(e) ablations (pack-only / expand-only) at the dominant level's shape.

Run on the ambient platform (the tunneled TPU under the driver):
    python scripts/prof_levels.py [batch]
"""

from __future__ import annotations

import sys
import time

import jax
import numpy as np

sys.path.insert(0, ".")

from ketotpu.engine import fastpath as fp  # noqa: E402
from ketotpu.engine.tpu import DeviceCheckEngine  # noqa: E402
from ketotpu.utils.synth import build_synth, synth_queries  # noqa: E402

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 16384


def timeit(fn, n=3, warmup=1):
    for _ in range(warmup):
        jax.block_until_ready(fn())
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main():
    print(f"devices: {jax.devices()}  batch={BATCH}")
    graph = build_synth(
        n_users=2000, n_groups=100, n_folders=2000, n_docs=20000, seed=0
    )
    eng = DeviceCheckEngine(
        graph.store, graph.manager,
        frontier=6 * BATCH, arena=12 * BATCH, max_batch=BATCH,
    )
    t0 = time.perf_counter()
    eng.snapshot()
    print(f"snapshot+upload: {time.perf_counter() - t0:.3f}s")
    queries = synth_queries(graph, BATCH, seed=2)

    # host encode cost
    t0 = time.perf_counter()
    snap = eng.snapshot()
    enc = eng._encode(snap, queries, 0)
    print(f"encode ({BATCH} queries): {time.perf_counter() - t0 :.3f}s")
    err, general = eng._classify(snap, enc[0], enc[2])
    print(f"err={err.sum()} general={general.sum()}")

    # end-to-end
    e2e = timeit(lambda: eng.batch_check(queries))
    print(f"end-to-end batch_check: {e2e*1000:.1f} ms  "
          f"({BATCH/e2e:.0f} checks/s)")

    # fused dispatch alone (device program only, packed I/O)
    fast_active = ~(err | general)
    qpack = np.stack([*enc, fast_active.astype(np.int32)]).astype(np.int32)
    g = eng._device_arrays

    def fused():
        return fp.run_fast_packed(
            g, qpack, frontier=eng.frontier, arena=eng.arena,
            max_depth=eng.max_depth, max_width=eng.max_width,
        )

    t_fused = timeit(fused)
    print(f"fused dispatch: {t_fused*1000:.1f} ms")

    # per-level: run the unfused step at each level's schedule shape
    sched = fp.level_schedule(BATCH, eng.frontier, eng.arena, eng.max_depth)
    print(f"schedule: {sched}")
    s = fp.init_state(*enc, fast_active, frontier=sched[0][0])
    import jax.numpy as jnp

    s["f_depth"] = jnp.minimum(s["f_depth"], len(sched))
    states = [s]
    for i, (f, a) in enumerate(sched):
        nxt_f = sched[i + 1][0] if i + 1 < len(sched) else 1
        last = i == len(sched) - 1

        def level(s=s, a=a, nxt_f=nxt_f, last=last):
            children, q_found, q_over, q_dirty = fp.expand_phase(
                g, s, arena=a, max_width=eng.max_width, probe_only=last
            )
            nxt, q_over = fp.pack_phase(
                children, q_found, q_over, frontier=nxt_f,
                ns_dim=g["f_direct_ok"].shape[0], rel_dim=g["f_direct_ok"].shape[1],
            )
            return dict(nxt, q_found=q_found, q_over=q_over,
                        q_dirty=q_dirty, q_subj=s["q_subj"])

        jlevel = jax.jit(level)
        t_lvl = timeit(jlevel)
        s = jax.block_until_ready(jlevel())
        live = int(np.sum(np.asarray(s["f_qid"]) >= 0))
        found = int(np.sum(np.asarray(s["q_found"])))
        print(f"level {i}: f={f} a={a} -> {t_lvl*1000:7.1f} ms   "
              f"next-frontier live={live}  found={found}")
        states.append(s)

    # ablation at the dominant level (level 1): expand vs pack
    s1 = states[1]
    f1, a1 = sched[1]

    def expand_only():
        return fp.expand_phase(g, s1, arena=a1, max_width=eng.max_width)

    je = jax.jit(expand_only)
    print(f"level1 expand_phase only: {timeit(je)*1000:.1f} ms")

    children, q_found, q_over, q_dirty = jax.block_until_ready(je())

    def pack_only():
        return fp.pack_phase(
            children, q_found, q_over, frontier=sched[2][0],
            ns_dim=g["f_direct_ok"].shape[0],
            rel_dim=g["f_direct_ok"].shape[1],
        )

    print(f"level1 pack_phase only:   {timeit(jax.jit(pack_only))*1000:.1f} ms")


if __name__ == "__main__":
    main()
