"""Cost model of the tunneled chip: RTT, upload, download, per-batch compute.

prof_chain.py showed repeated executions with IDENTICAL inputs can return
absurdly fast (0.2ms for a 4096-batch program) — the tunnel appears to
memoize — so every measurement here uses DISTINCT inputs per repetition.

Measures:
  rtt          — trivial jit (x+1 on int32[8]) with fresh input each rep
  upload       — device_put of an int32[6, B] query pack
  download     — np.asarray of a uint8[B] device verdict
  fused[B]     — the 5-level fused program, distinct query batch each rep
  pipeline x4  — 4 distinct batches dispatched back-to-back, one sync pass
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from ketotpu.engine import fastpath as fp  # noqa: E402
from ketotpu.engine.tpu import DeviceCheckEngine  # noqa: E402
from ketotpu.utils.synth import build_synth, synth_queries  # noqa: E402


def main():
    print(f"devices: {jax.devices()}")

    # RTT floor: tiny program, fresh input every rep
    tiny = jax.jit(lambda x: x + 1)
    xs = [np.full((8,), i, np.int32) for i in range(8)]
    jax.block_until_ready(tiny(xs[0]))
    ts = []
    for x in xs[1:]:
        t0 = time.perf_counter()
        jax.block_until_ready(tiny(x))
        ts.append(time.perf_counter() - t0)
    print(f"rtt floor (tiny jit, fresh input): min={min(ts)*1000:.1f} "
          f"med={sorted(ts)[len(ts)//2]*1000:.1f} ms")

    graph = build_synth(
        n_users=2000, n_groups=100, n_folders=2000, n_docs=20000, seed=0
    )
    eng = DeviceCheckEngine(
        graph.store, graph.manager,
        frontier=98304, arena=196608, max_batch=16384,
    )
    eng.snapshot()
    snap = eng.snapshot()
    g = eng._device_arrays

    def make_packs(batch, n):
        packs = []
        for i in range(n):
            qs = synth_queries(graph, batch, seed=100 + i)
            enc = eng._encode(snap, qs, 0)
            err, general = eng._classify(snap, enc[0], enc[2])
            act = ~(err | general)
            packs.append(np.stack([*enc, act.astype(np.int32)]).astype(np.int32))
        return packs

    # upload / download costs at 16k
    packs = make_packs(16384, 6)
    jax.block_until_ready(jax.device_put(packs[0]))
    t0 = time.perf_counter()
    for p in packs[1:]:
        jax.block_until_ready(jax.device_put(p))
    print(f"upload int32[6,16384]: {(time.perf_counter()-t0)/5*1000:.1f} ms avg")

    for batch in (2048, 4096, 8192, 16384):
        packs = make_packs(batch, 5)

        def run(p, mults=None):
            r, occ = fp.run_fast_packed(
                g, p, frontier=eng.frontier, arena=eng.arena,
                max_depth=eng.max_depth, max_width=eng.max_width,
                mults=mults)
            return r

        jax.block_until_ready(run(packs[0]))  # compile
        ts = []
        for p in packs[1:]:
            t0 = time.perf_counter()
            r = run(p)
            v = np.asarray(r)  # full sync incl. download
            ts.append(time.perf_counter() - t0)
        t1 = min(ts)
        print(f"fused batch={batch:6d}: min={t1*1000:8.1f} ms  "
              f"({batch/t1:8.0f} checks/s)")

        # pipelining: dispatch 4 distinct batches, then sync all
        packs4 = make_packs(batch, 5)[1:]
        t0 = time.perf_counter()
        handles = [run(p) for p in packs4]
        t_disp = time.perf_counter() - t0
        outs = [np.asarray(h) for h in handles]
        t_all = time.perf_counter() - t0
        print(f"  4 batches pipelined: dispatch={t_disp*1000:7.1f} ms  "
              f"total={t_all*1000:8.1f} ms  ({4*batch/t_all:8.0f} checks/s)")

        # demand-adaptive schedule: measure occupancy once, re-run sized
        _, occ = fp.run_fast_packed(
            g, packs[0], frontier=eng.frontier, arena=eng.arena,
            max_depth=eng.max_depth, max_width=eng.max_width)
        occ = np.asarray(occ).astype(np.float64)
        ratio = occ / max(occ[0], 1)
        mults = tuple(
            [1] + [max(1, min(fp.F_MULT[min(l, len(fp.F_MULT)-1)],
                              int(np.ceil(ratio[min(l, len(ratio)-1)] * 1.35))))
                   for l in range(1, eng.max_depth)])
        print(f"  occupancy ratios {np.round(ratio,2).tolist()} -> mults {mults}")
        jax.block_until_ready(run(packs[0], mults))
        ts = []
        for p in packs[1:]:
            t0 = time.perf_counter()
            np.asarray(run(p, mults))
            ts.append(time.perf_counter() - t0)
        t2 = min(ts)
        print(f"  adaptive fused: {t2*1000:8.1f} ms  ({batch/t2:8.0f} checks/s)")
        t0 = time.perf_counter()
        hs = [run(p, mults) for p in packs4]
        _ = [np.asarray(h) for h in hs]
        t_alla = time.perf_counter() - t0
        print(f"  adaptive 4 pipelined: {t_alla*1000:8.1f} ms  "
              f"({4*batch/t_alla:8.0f} checks/s)")


if __name__ == "__main__":
    main()
