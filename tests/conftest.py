"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before anything imports jax, hence the env mutation at module import
time (pytest imports conftest.py before collecting test modules).
"""

import os

# Force, don't setdefault: the ambient environment points JAX_PLATFORMS at
# real TPU hardware, and running the test matrix over that tunnel is both slow
# and single-device.  Benchmarks (bench.py) use the real chip; tests use a
# virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The env var alone does NOT win against the preinstalled TPU plugin in this
# jax build (verified: a subprocess with JAX_PLATFORMS=cpu still gets the
# axon TPU client); the config.update below does.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
