"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before anything imports jax, hence the env mutation at module import
time (pytest imports conftest.py before collecting test modules).
"""

import os

# Force, don't setdefault: the ambient environment points JAX_PLATFORMS at
# real TPU hardware, and running the test matrix over that tunnel is both slow
# and single-device.  Benchmarks (bench.py) use the real chip; tests use a
# virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_parallel_codegen_split_count" not in flags:
    # XLA:CPU's parallel LLVM codegen segfaults sporadically once a process
    # has compiled enough distinct programs (observed repeatedly in this
    # suite: SIGSEGV inside backend_compile_and_load, each program fine in
    # isolation).  Serializing codegen removes the raciness; the persistent
    # compile cache below keeps the single-threaded cost off re-runs.
    flags = (flags + " --xla_cpu_parallel_codegen_split_count=1").strip()
os.environ["XLA_FLAGS"] = flags

# Fused tiered dispatch defaults ON for serving (engine/fused.py), but a
# fused wave's one-program compile is several times a per-tier program's
# on XLA:CPU — across every daemon-booting test here that would blow the
# suite's compile budget (and raise the segfault-threshold program count).
# Tests exercise the unfused cascade unless they opt in explicitly; fused
# parity coverage lives in test_fused.py and the CI serve-northstar job.
os.environ.setdefault("KETO_ENGINE_FUSED_DISPATCH", "false")

# The env var alone does NOT win against the preinstalled TPU plugin in this
# jax build (verified: a subprocess with JAX_PLATFORMS=cpu still gets the
# axon TPU client); the config.update below does.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import importlib.util  # noqa: E402

if importlib.util.find_spec("xdist") is None:
    # pyproject's addopts carries the xdist flags (-n 4 --dist loadfile);
    # without the plugin installed pytest rejects them as unrecognized and
    # NOTHING can run.  Absorb them as no-ops so the suite degrades to a
    # single serial process (the codegen-split flag above is what actually
    # keeps that stable).
    def pytest_addoption(parser):
        group = parser.getgroup("xdist-fallback")
        # _addoption, not addoption: lowercase short options are reserved
        # in the public API (xdist registers -n the same way)
        group._addoption("-n", "--numprocesses", dest="numprocesses",
                         default=None, help="ignored (pytest-xdist absent)")
        group._addoption("--dist", dest="xdist_dist", default="no",
                         help="ignored (pytest-xdist absent)")

# NOTE on the persistent compilation cache: tempting for this suite's
# hundreds of slow XLA:CPU compiles, but writing cache entries for the
# shard_map/all_to_all mesh programs aborts inside XLA's executable
# serialization on this jaxlib (SIGABRT in put_executable_and_time,
# reproduced at tests/test_parallel.py scope) — leave it off.  The
# codegen-split flag above is the load-bearing stability fix.
