// Copyright © 2023 Ory Corp
// SPDX-License-Identifier: Apache-2.0

import {Namespace, Context} from '@ory/keto-namespace-types'

class User implements Namespace {}

class Project implements Namespace {
  related: {
    owner: User[]
    developer: User[]
  }

  permits = {
    isOwner: (ctx: Context) => this.related.owner.includes(ctx.subject),
    isOwnerOrDeveloper: (ctx: Context) =>
      this.related.owner.includes(ctx.subject) ||
      this.related.developer.includes(ctx.subject),
    writeCollaborator: (ctx: Context) =>
      this.permits.isOwner(ctx),
    readCollaborator: (ctx: Context) =>
      this.permits.isOwnerOrDeveloper(ctx),
    deleteProject: (ctx: Context) => this.permits.isOwner(ctx),
    writeProject: (ctx: Context) =>
      this.permits.isOwnerOrDeveloper(ctx),
    readProject: (ctx: Context) =>
      this.permits.isOwnerOrDeveloper(ctx),
  }
}
