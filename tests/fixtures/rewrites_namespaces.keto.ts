// Copyright © 2023 Ory Corp
// SPDX-License-Identifier: Apache-2.0

import { Namespace, SubjectSet, Context } from "@ory/keto-namespace-types"

class User implements Namespace {
  related: {
    manager: User[]
  }
}

class Group implements Namespace {
  related: {
    members: (User | Group)[]
  }
}

class Folder implements Namespace {
  related: {
    parents: (File | Folder)[]
    viewers: SubjectSet<Group, "members">[]
  }

  permits = {
    view: (ctx: Context): boolean =>
      this.related.viewers.includes(ctx.subject) ||
      this.related.parents.traverse((p) => p.permits.view(ctx)),
  }
}

class File implements Namespace {
  related: {
    parents: (File | Folder)[]
    viewers: (User | SubjectSet<Group, "members">)[]
    owners: (User | SubjectSet<Group, "members">)[]
  }

  // Some comment
  permits = {
    view: (ctx: Context): boolean =>
      this.related.parents.traverse((p) => p.permits.view(ctx)) ||
      this.related.viewers.includes(ctx.subject) ||
      this.related.owners.includes(ctx.subject),

    edit: (ctx: Context) => this.related.owners.includes(ctx.subject),
  }
}
