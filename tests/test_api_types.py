"""Tuple grammar / codec parity tests.

Mirrors the reference's codec behaviors: ketoapi/enc_string.go (round-trips,
optional parens, empty subject-set relation), enc_url_query.go (subject key
errors), and JSON field layout.
"""

import pytest

from ketotpu.api import (
    RelationQuery,
    RelationTuple,
    SubjectID,
    SubjectSet,
)
from ketotpu.api.types import BadRequestError, subject_from_string


class TestTupleGrammar:
    @pytest.mark.parametrize(
        "s,expected",
        [
            (
                "videos:/cats/1.mp4#view@alice",
                RelationTuple("videos", "/cats/1.mp4", "view", SubjectID("alice")),
            ),
            (
                "videos:/cats/1.mp4#view@videos:/cats#owner",
                RelationTuple(
                    "videos", "/cats/1.mp4", "view", SubjectSet("videos", "/cats", "owner")
                ),
            ),
            (
                "videos:/cats/1.mp4#view@(videos:/cats#owner)",
                RelationTuple(
                    "videos", "/cats/1.mp4", "view", SubjectSet("videos", "/cats", "owner")
                ),
            ),
            # subject set without relation => empty relation
            ("n:o#r@users:bob", RelationTuple("n", "o", "r", SubjectSet("users", "bob", ""))),
            # '@' in subject id is fine (first '@' splits)
            ("n:o#r@user@example.com", RelationTuple("n", "o", "r", SubjectID("user@example.com"))),
            # object may contain '#'? no -- first '#' splits. but ':' in object is fine
            ("n:o:with:colons#r@s", RelationTuple("n", "o:with:colons", "r", SubjectID("s"))),
        ],
    )
    def test_parse(self, s, expected):
        assert RelationTuple.from_string(s) == expected

    @pytest.mark.parametrize("s", ["no-colon", "ns:obj-no-hash", "ns:obj#rel-no-at"])
    def test_parse_errors(self, s):
        with pytest.raises(BadRequestError):
            RelationTuple.from_string(s)

    def test_roundtrip(self):
        for s in [
            "videos:/cats/1.mp4#view@alice",
            "videos:/cats/1.mp4#view@videos:/cats#owner",
            "n:o#r@user@example.com",
        ]:
            assert str(RelationTuple.from_string(s)) == s

    def test_subject_set_without_relation_str(self):
        assert str(SubjectSet("users", "bob", "")) == "users:bob"
        assert str(SubjectSet("users", "bob", "r")) == "users:bob#r"

    def test_subject_from_string(self):
        assert subject_from_string("alice") == SubjectID("alice")
        assert subject_from_string("a:b#c") == SubjectSet("a", "b", "c")
        assert subject_from_string("(a:b#c)") == SubjectSet("a", "b", "c")


class TestURLQuery:
    def test_dropped_subject_key(self):
        with pytest.raises(BadRequestError):
            RelationQuery.from_url_query({"subject": "x"})

    def test_duplicate_subject(self):
        with pytest.raises(BadRequestError):
            RelationQuery.from_url_query(
                {"subject_id": "x", "subject_set.namespace": "n"}
            )

    def test_incomplete_subject_set(self):
        with pytest.raises(BadRequestError):
            RelationQuery.from_url_query(
                {"subject_set.namespace": "n", "subject_set.object": "o"}
            )

    def test_no_subject_ok(self):
        q = RelationQuery.from_url_query({"namespace": "n", "object": "o"})
        assert q.namespace == "n" and q.object == "o"
        assert q.subject() is None

    def test_full_roundtrip(self):
        t = RelationTuple("n", "o", "r", SubjectSet("a", "b", "c"))
        assert RelationTuple.from_url_query(t.to_url_query()) == t
        t2 = RelationTuple("n", "o", "r", SubjectID("alice"))
        assert RelationTuple.from_url_query(t2.to_url_query()) == t2


class TestJSON:
    def test_subject_id_layout(self):
        t = RelationTuple("n", "o", "r", SubjectID("alice"))
        assert t.to_json() == {
            "namespace": "n",
            "object": "o",
            "relation": "r",
            "subject_id": "alice",
        }
        assert RelationTuple.from_json(t.to_json()) == t

    def test_subject_set_layout(self):
        t = RelationTuple("n", "o", "r", SubjectSet("a", "b", "c"))
        assert t.to_json() == {
            "namespace": "n",
            "object": "o",
            "relation": "r",
            "subject_set": {"namespace": "a", "object": "b", "relation": "c"},
        }
        assert RelationTuple.from_json(t.to_json()) == t


class TestUUIDMapper:
    def test_deterministic_and_reversible(self):
        import uuid

        from ketotpu.api.uuid_map import UUIDMapper

        nid = uuid.uuid4()
        m = UUIDMapper(nid)
        u1 = m.to_uuid("alice")
        assert m.to_uuid("alice") == u1
        assert UUIDMapper(nid).to_uuid("alice") == u1
        assert m.from_uuid(u1) == "alice"
        # parity with Go's uuid.NewV5(nid, value) == RFC4122 SHA1 name-based
        assert u1 == uuid.uuid5(nid, "alice")
