"""Batch front door (ISSUE 7): BatchCheck/BatchExpand over REST + gRPC,
per-item verdicts and error isolation, weighted admission, the shared
deadline budget's partial-results contract, keep-alive/pipelining on the
async front end, the framed worker wire, and the slow e2e leg against a
real ``serve --workers 2`` topology.
"""

import json
import os
import pathlib
import socket
import struct
import subprocess
import sys
import time
import urllib.error
import urllib.parse
import urllib.request

import grpc
import numpy as np
import pytest

from ketotpu import deadline
from ketotpu.api.proto_codec import tuple_to_proto
from ketotpu.api.types import RelationTuple, SubjectSet
from ketotpu.driver import Provider, Registry
from ketotpu.proto import batch_service_pb2 as bs
from ketotpu.proto import relation_tuples_pb2 as rts
from ketotpu.proto.services import CheckServiceStub, ExpandServiceStub
from ketotpu.sdk import BadRequestError, KetoClient
from ketotpu.server import serve_all
from ketotpu.server import wire

FIXTURES = pathlib.Path(__file__).parent / "fixtures"

TUPLES = [
    "Group:dev#members@bob",
    "Group:admin#members@alice",
    "Folder:keto#viewers@Group:dev#members",
    "File:keto/README.md#parents@Folder:keto",
]

# canonical query mix: direct hit, subject-set rewrite hit, two denies
CASES = [
    ("Group:dev#members@bob", True),
    ("File:keto/README.md#view@bob", True),
    ("File:keto/README.md#view@alice", False),
    ("File:keto/README.md#view@eve", False),
]


def _http(method, url, body=None, headers=None, timeout=30.0):
    req = urllib.request.Request(
        url, data=body, method=method, headers=headers or {}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


def _post_json(url, payload, headers=None, timeout=30.0):
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    return _http("POST", url, json.dumps(payload).encode(), hdrs,
                 timeout=timeout)


def _registry(extra=None):
    cfg = {
        "serve": {
            n: {"host": "127.0.0.1", "port": 0}
            for n in ("read", "write", "metrics", "opl")
        },
        "namespaces": {
            "location": str(FIXTURES / "rewrites_namespaces.keto.ts")
        },
        "engine": {
            "kind": "tpu", "frontier": 1024, "arena": 4096,
            "max_batch": 256, "coalesce_ms": 2,
            "mesh_devices": 0, "mesh_axis": "shard",
        },
        "log": {"request_log": False},
    }
    for key, val in (extra or {}).items():
        cfg.setdefault(key, {}).update(val)
    reg = Registry(Provider(cfg)).init()
    reg.store().write_relation_tuples(
        *[RelationTuple.from_string(s) for s in TUPLES]
    )
    return reg


@pytest.fixture(scope="module")
def server():
    srv = serve_all(_registry())
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def read(server):
    return "http://%s:%d" % tuple(server.addresses["read"])


def _singles(read, cases):
    out = []
    for s, _ in cases:
        t = RelationTuple.from_string(s)
        q = urllib.parse.urlencode({
            "namespace": t.namespace, "object": t.object,
            "relation": t.relation, "subject_id": str(t.subject),
        })
        status, body, _ = _http(
            "GET", f"{read}/relation-tuples/check/openapi?{q}"
        )
        assert status == 200, body
        out.append(json.loads(body)["allowed"])
    return out


class TestRestBatchFrontDoor:
    def test_parity_with_singles_zero_divergence(self, read):
        """The acceptance contract: the batch front door and the single
        check endpoint agree verdict-for-verdict (and against the same
        snaptoken, so the agreement is about one snapshot, not luck)."""
        singles = _singles(read, CASES)
        assert singles == [want for _, want in CASES]
        payload = {
            "tuples": [
                RelationTuple.from_string(s).to_json() for s, _ in CASES
            ],
        }
        status, body, _ = _post_json(
            f"{read}/relation-tuples/batch/check", payload
        )
        assert status == 200, body
        doc = json.loads(body)
        got = [r["allowed"] for r in doc["results"]]
        assert got == singles
        assert doc["snaptoken"]
        # pin the snapshot and re-run: still zero divergence
        payload["snaptoken"] = doc["snaptoken"]
        status, body, _ = _post_json(
            f"{read}/relation-tuples/batch/check", payload
        )
        assert status == 200, body
        assert [r["allowed"] for r in json.loads(body)["results"]] == singles

    def test_matches_legacy_batch_endpoint(self, read):
        """/relation-tuples/batch/check supersedes /check/batch; both
        must answer identically for all-good batches."""
        payload = {
            "tuples": [
                RelationTuple.from_string(s).to_json() for s, _ in CASES
            ],
        }
        st_new, body_new, _ = _post_json(
            f"{read}/relation-tuples/batch/check", payload
        )
        st_old, body_old, _ = _post_json(
            f"{read}/relation-tuples/check/batch", payload
        )
        assert st_new == st_old == 200
        new = [r["allowed"] for r in json.loads(body_new)["results"]]
        old = [r["allowed"] for r in json.loads(body_old)["results"]]
        assert new == old

    def test_per_item_error_isolation(self, read):
        """One bad tuple fails ITS slot only: the neighbors keep their
        verdicts, an unknown namespace stays allowed=false (single-check
        parity), and the batch itself returns 200."""
        payload = {
            "tuples": [
                RelationTuple.from_string(CASES[1][0]).to_json(),  # True
                {"namespace": "File", "object": "keto/README.md",
                 "relation": "nosuch", "subject_id": "bob"},        # 400
                {"namespace": "Nope", "object": "x", "relation": "y",
                 "subject_id": "alice"},                            # False
                {},                                                 # 400
                RelationTuple.from_string(CASES[3][0]).to_json(),  # False
            ],
        }
        status, body, _ = _post_json(
            f"{read}/relation-tuples/batch/check", payload
        )
        assert status == 200, body
        res = json.loads(body)["results"]
        assert res[0] == {"allowed": True}
        assert res[1]["status"] == 400 and "error" in res[1]
        assert res[2] == {"allowed": False}
        assert res[3]["status"] == 400 and "error" in res[3]
        assert res[4] == {"allowed": False}

    def test_batch_expand_per_item_trees(self, read):
        payload = {
            "subjects": [
                {"namespace": "Folder", "object": "keto",
                 "relation": "viewers"},
                {"namespace": "Folder", "object": "nope",
                 "relation": "viewers"},
            ],
        }
        status, body, _ = _post_json(
            f"{read}/relation-tuples/batch/expand", payload
        )
        assert status == 200, body
        doc = json.loads(body)
        assert doc["snaptoken"]
        first, second = doc["results"]
        assert "tree" in first and first["tree"]["children"]
        assert second["status"] == 404

    def test_malformed_body_is_a_400(self, read):
        status, body, _ = _post_json(
            f"{read}/relation-tuples/batch/check", {"nope": 1}
        )
        assert status == 400, body


class TestSharedDeadlineBudget:
    def test_partial_results_on_expiry(self, server):
        """ONE deadline budget for the whole batch: with the budget
        already burned, pre-resolved items keep their answers and every
        unanswered item comes back as a per-item 504 — the batch still
        returns instead of being dropped."""
        from ketotpu.server.handlers import CheckHandler

        r = server.registry
        handler = CheckHandler(r)
        items = [
            RelationTuple.from_string(CASES[1][0]),
            RelationTuple.from_string("Nope:x#y@alice"),  # pre-resolved
            RelationTuple.from_string(CASES[3][0]),
        ]
        with deadline.scope(1e-9):
            time.sleep(0.001)  # burn the budget before dispatch
            out = handler.batch_check_items(items, 8, r)
        assert out[1] == {"allowed": False}
        for res in (out[0], out[2]):
            assert res["status"] == 504, out
            assert "deadline" in res["error"]

    def test_fresh_budget_answers_everything(self, server):
        from ketotpu.server.handlers import CheckHandler

        r = server.registry
        handler = CheckHandler(r)
        items = [RelationTuple.from_string(s) for s, _ in CASES]
        with deadline.scope(30.0):
            out = handler.batch_check_items(items, 8, r)
        assert [res["allowed"] for res in out] == [w for _, w in CASES]


class TestWeightedAdmission:
    @pytest.fixture(scope="class")
    def tight_server(self):
        # overload plane pinned off: this class drives a deterministic
        # 2-unit budget, and the AIMD controller would grow it mid-test
        srv = serve_all(_registry({"limit": {"max_inflight": 2},
                                   "overload": {"enabled": False}}))
        yield srv
        srv.stop()

    def test_oversized_batch_runs_alone_sheds_under_load(self, tight_server):
        """Admission counts batches by ITEM weight.  An oversized batch
        is clamped to the whole budget so it can still run — but ONLY
        alone: with one unit already in flight the same batch sheds with
        the Retry-After hint intact, exactly like 8 concurrent singles
        would."""
        read = "http://%s:%d" % tuple(tight_server.addresses["read"])
        payload = {
            "tuples": [
                RelationTuple.from_string(CASES[1][0]).to_json()
                for _ in range(8)
            ],
        }
        # alone: the clamp admits the batch against the empty budget
        status, body, _ = _post_json(
            f"{read}/relation-tuples/batch/check", payload
        )
        assert status == 200, body
        # occupy one unit (a concurrent request in flight) and retry:
        # the batch's weighted admission no longer fits and it sheds
        ctl = tight_server.registry.admission()
        token = ctl.try_acquire()
        assert token
        try:
            status, body, headers = _post_json(
                f"{read}/relation-tuples/batch/check", payload
            )
            assert status == 429, body
            assert int(headers.get("Retry-After")) >= 1
        finally:
            ctl.release(token)
        # the limiter was never wedged by the shed: singles still run
        assert _singles(read, CASES[:1]) == [True]

    def test_shed_counter_carries_batch_transport(self, tight_server):
        metrics = "http://%s:%d" % tuple(tight_server.addresses["metrics"])
        _, text, _ = _http("GET", f"{metrics}/metrics/prometheus")
        assert ('keto_requests_shed_total'
                '{klass="batch",transport="batch"}') in text


class TestKeepAlivePipelining:
    def _read_response(self, f):
        status_line = f.readline()
        assert status_line, "connection closed mid-response"
        status = int(status_line.split()[1])
        length, keep = 0, True
        while True:
            line = f.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            name, _, val = line.decode().partition(":")
            if name.lower() == "content-length":
                length = int(val.strip())
            if name.lower() == "connection" and "close" in val.lower():
                keep = False
        body = f.read(length) if length else b""
        return status, body, keep

    def test_pipelined_requests_share_one_connection(self, server):
        """The async front end keeps the connection open and answers
        pipelined requests in order — two GETs written back-to-back in
        one segment yield two in-order responses on the same socket."""
        host, port = server.addresses["read"]
        with socket.create_connection((host, port), timeout=10) as s:
            req = (
                b"GET /health/alive HTTP/1.1\r\n"
                b"Host: t\r\n\r\n"
            )
            s.sendall(req + req)  # pipelined
            f = s.makefile("rb")
            st1, body1, keep1 = self._read_response(f)
            st2, body2, keep2 = self._read_response(f)
            assert (st1, st2) == (200, 200)
            assert keep1 and keep2
            # the connection is still live: a third request round-trips
            s.sendall(req)
            st3, _, _ = self._read_response(f)
            assert st3 == 200


class TestWireFrames:
    def test_roundtrip_preserves_meta_and_arrays(self):
        a, b = socket.socketpair()
        try:
            arrays = {
                "ids": np.arange(20, dtype=np.int32).reshape(5, 4),
                "ok": np.array([1, 0, 1], dtype=np.uint8),
            }
            meta = {"op": "check", "n": 3, "nested": {"k": [1, 2]}}
            sent = wire.send_frame(a, meta, arrays)
            got_meta, got_arrays, nread = wire.recv_frame(b.makefile("rb"))
            assert nread == sent
            got_meta.pop("_arrays", None)
            assert got_meta == meta
            for k, arr in arrays.items():
                assert got_arrays[k].dtype == arr.dtype
                assert np.array_equal(got_arrays[k], arr)
        finally:
            a.close()
            b.close()

    def test_meta_only_frame(self):
        a, b = socket.socketpair()
        try:
            wire.send_frame(a, {"op": "ping"})
            meta, arrays, _ = wire.recv_frame(b.makefile("rb"))
            assert meta == {"op": "ping"}
            assert arrays == {}
        finally:
            a.close()
            b.close()

    def test_shm_hop_moves_payload_off_the_socket(self):
        """Above the threshold the numpy payload rides a shared-memory
        segment: the socket carries only the frame header + meta, and
        the receiver reconstructs the arrays bit-for-bit."""
        a, b = socket.socketpair()
        ring, cache = wire.ShmRing(), wire.ShmCache()
        try:
            payload = np.arange(65536, dtype=np.int32).reshape(-1, 4)
            sent = wire.send_frame(
                a, {"op": "check"}, {"ids": payload},
                ring=ring, shm_threshold=1,
            )
            assert sent < payload.nbytes  # the bulk went via shm
            meta, arrays, _ = wire.recv_frame(
                b.makefile("rb"), shm_cache=cache
            )
            assert meta["op"] == "check"
            assert np.array_equal(arrays["ids"], payload)
        finally:
            cache.close()
            ring.close()
            a.close()
            b.close()

    def test_oversized_header_is_a_wire_error(self):
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack("!II", wire.MAX_META + 1, 0))
            with pytest.raises(wire.WireError):
                wire.recv_frame(b.makefile("rb"))
        finally:
            a.close()
            b.close()

    def test_truncated_frame_is_a_wire_error(self):
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack("!II", 64, 0) + b'{"op"')
            a.close()  # EOF mid-meta
            with pytest.raises(wire.WireError):
                wire.recv_frame(b.makefile("rb"))
        finally:
            b.close()

    def test_clean_eof_returns_none(self):
        a, b = socket.socketpair()
        a.close()
        try:
            assert wire.recv_frame(b.makefile("rb")) is None
        finally:
            b.close()


class TestOwnerWireRoundTrips:
    @pytest.mark.slow  # the 4096-wide dispatch pays XLA:CPU compiles
    def test_batch_is_one_round_trip(self, tmp_path):
        """ISSUE acceptance: wire round-trips per 4096-item batch must
        be <= the worker count.  A worker-side RemoteCheckEngine packs
        the WHOLE batch into one frame, so the count is exactly 1 per
        worker regardless of batch size."""
        from ketotpu.server.workers import EngineHostServer, RemoteCheckEngine

        owner = Registry(Provider({
            "dsn": f"sqlite://{tmp_path}/wire.db",
            "namespaces": {
                "location": str(FIXTURES / "rewrites_namespaces.keto.ts")
            },
            "engine": {"kind": "tpu", "frontier": 2048, "arena": 8192,
                       "max_batch": 4096, "mesh_devices": 0,
                       "mesh_axis": "shard"},
        }))
        owner.store().migrate_up()
        owner.store().write_relation_tuples(
            *[RelationTuple.from_string(s) for s in TUPLES]
        )
        owner.init()
        sock_path = str(tmp_path / "engine.sock")
        host = EngineHostServer(owner, sock_path).start()
        try:
            remote = RemoteCheckEngine(sock_path)
            calls = []
            orig = remote._call

            def counted(meta, arrays=None):
                calls.append(meta.get("op"))
                return orig(meta, arrays)

            remote._call = counted
            batch = [
                RelationTuple.from_string(
                    f"File:keto/README.md#view@user{i}"
                )
                for i in range(4095)
            ] + [RelationTuple.from_string(CASES[1][0])]
            verdicts = remote.batch_check(batch)
            assert len(verdicts) == 4096
            assert verdicts[-1] is True
            assert not any(verdicts[:-1])
            assert calls == ["check"], calls
        finally:
            host.stop()


class TestGrpcBatch:
    def test_batch_check_per_item_verdicts(self, server):
        addr = "%s:%d" % tuple(server.addresses["read"])
        protos = [
            tuple_to_proto(RelationTuple.from_string(s)) for s, _ in CASES
        ]
        bad = rts.RelationTuple()
        bad.CopyFrom(protos[1])
        bad.relation = "nosuch"
        with grpc.insecure_channel(addr) as ch:
            resp = CheckServiceStub(ch).BatchCheck(
                bs.BatchCheckRequest(tuples=protos + [bad])
            )
        assert resp.snaptoken
        got = [item.allowed for item in resp.results[: len(CASES)]]
        assert got == [want for _, want in CASES]
        assert all(not item.error for item in resp.results[: len(CASES)])
        assert resp.results[-1].status == 400
        assert resp.results[-1].error

    def test_batch_expand_per_item_trees(self, server):
        addr = "%s:%d" % tuple(server.addresses["read"])
        req = bs.BatchExpandRequest(max_depth=8)
        req.subjects.add(
            namespace="Folder", object="keto", relation="viewers"
        )
        req.subjects.add(
            namespace="Folder", object="nope", relation="viewers"
        )
        with grpc.insecure_channel(addr) as ch:
            resp = ExpandServiceStub(ch).BatchExpand(req)
        assert resp.snaptoken
        assert resp.results[0].tree.children
        assert resp.results[1].status == 404


class TestSdkBatch:
    def test_batch_check_and_results(self, server, read):
        c = KetoClient(read)
        tuples = [RelationTuple.from_string(s) for s, _ in CASES]
        assert c.batch_check(tuples) == [want for _, want in CASES]
        # canonical strings are accepted too (same forms as the CLI jsonl)
        assert c.batch_check([s for s, _ in CASES]) == [
            want for _, want in CASES
        ]
        res = c.batch_check_results(
            [t.to_json() for t in tuples]
            + [{"namespace": "File", "object": "x",
                "relation": "nosuch", "subject_id": "z"}]
        )
        assert [r.get("allowed") for r in res[: len(CASES)]] == [
            want for _, want in CASES
        ]
        assert res[-1]["status"] == 400
        # a typed error item surfaces as the matching typed exception
        with pytest.raises(BadRequestError):
            c.batch_check(
                tuples + [RelationTuple.from_string("File:x#nosuch@z")]
            )

    def test_batch_expand_trees_and_none(self, server, read):
        c = KetoClient(read)
        trees = c.batch_expand([
            SubjectSet("Folder", "keto", "viewers"),
            SubjectSet("Folder", "nope", "viewers"),
        ])
        assert trees[0] is not None and trees[0].children
        assert trees[1] is None


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_batch_front_door_through_worker_topology(tmp_path):
    """Slow e2e: boot ``serve --workers 2`` (workers answer over the
    framed owner wire) and run the batch front door against it — the
    verdicts must match the single-check endpoint item-for-item, and a
    4096-item batch must come back whole."""
    db = tmp_path / "batch.db"
    seed = Registry(Provider({"dsn": f"sqlite://{db}"}))
    seed.store().migrate_up()
    seed.store().write_relation_tuples(
        *[RelationTuple.from_string(s) for s in TUPLES]
    )

    ports = {n: _free_port() for n in ("read", "write", "metrics", "opl")}
    config = {
        "dsn": f"sqlite://{db}",
        "serve": {
            n: {"host": "127.0.0.1", "port": p} for n, p in ports.items()
        },
        "namespaces": {
            "location": str(FIXTURES / "rewrites_namespaces.keto.ts")
        },
        "engine": {"kind": "tpu", "frontier": 2048, "arena": 8192,
                   "max_batch": 1024, "mesh_devices": 0,
                   "mesh_axis": "shard"},
        "log": {"request_log": False},
    }
    cfg_path = tmp_path / "batch.json"
    cfg_path.write_text(json.dumps(config))

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ketotpu.cli", "serve",
         "-c", str(cfg_path), "--workers", "2"],
        env=env, cwd=str(pathlib.Path(__file__).parent.parent),
    )
    read = f"http://127.0.0.1:{ports['read']}"
    metrics = f"http://127.0.0.1:{ports['metrics']}"
    try:
        ready_by = time.monotonic() + 180.0
        while True:
            assert proc.poll() is None, "serve --workers died during boot"
            try:
                if _http("GET", f"{metrics}/health/ready",
                         timeout=2.0)[0] == 200:
                    break
            except OSError:
                pass
            assert time.monotonic() < ready_by, "topology never became ready"
            time.sleep(0.5)

        singles = _singles(read, CASES)
        payload = {
            "tuples": [
                RelationTuple.from_string(s).to_json() for s, _ in CASES
            ],
        }
        for _ in range(3):  # repeat: the worker's vocab mirror warms up
            status, body, _ = _post_json(
                f"{read}/relation-tuples/batch/check", payload
            )
            assert status == 200, body
            got = [r["allowed"] for r in json.loads(body)["results"]]
            assert got == singles

        def big_batch(n):
            return {
                "tuples": [
                    {"namespace": "File", "object": "keto/README.md",
                     "relation": "view", "subject_id": f"user{i}"}
                    for i in range(n - 1)
                ] + [RelationTuple.from_string(CASES[1][0]).to_json()],
            }

        # warm the wide device shape OUTSIDE the acceptance request: the
        # first Q=1024 dispatch pays an XLA compile measured in seconds
        status, body, _ = _post_json(
            f"{read}/relation-tuples/batch/check", big_batch(1024),
            timeout=300.0,
        )
        assert status == 200, body
        status, body, _ = _post_json(
            f"{read}/relation-tuples/batch/check", big_batch(4096),
            timeout=300.0,
        )
        assert status == 200, body
        res = json.loads(body)["results"]
        assert len(res) == 4096
        assert res[-1] == {"allowed": True}
        assert not any(r["allowed"] for r in res[:-1])

        # the framed wire's byte counters are live on whichever worker
        # answers the scrape
        _, text, _ = _http("GET", f"{metrics}/metrics/prometheus")
        assert "keto_batch_requests_total" in text
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=30)
