"""Hot-spot shield tests (ketotpu/cache/): snapshot-versioned result
cache, singleflight dedup, count-min hot-key detection, and the
randomized write-storm parity suite.

The contract under test is Zanzibar §3.2.5 translated to snaptokens: a
cached verdict served under ANY consistency mode must be bit-identical
to what a cache-bypassed check would answer at the same snaptoken — the
cache may only trade latency, never freshness beyond the mode's own
contract.  The storm legs interleave Transact writes/deletes with cached
checks across all three modes (default / at-least-as-fresh / latest) and
compare every cached verdict against the ``X-Keto-Cache: bypass`` path;
the slow leg replays the storm against a real ``serve --workers 2``
topology where the worker caches are fed by owner cursor piggybacks.
"""

import json
import os
import pathlib
import subprocess
import sys
import threading
import time

import pytest

from ketotpu import deadline
from ketotpu.api.types import (
    DeadlineExceededError,
    RelationTuple,
)
from ketotpu.cache import (
    HotSpotSketch,
    ResultCache,
    SingleFlight,
    check_key,
    expand_key,
    pretty_key,
)
from ketotpu.cache import context as cache_context
from ketotpu.consistency.tokens import Snaptoken
from ketotpu.driver import Provider, Registry
from ketotpu.utils.synth import build_synth, synth_queries

FIXTURES = pathlib.Path(__file__).parent / "fixtures"

T = RelationTuple.from_string
K = check_key(T("Doc:d1#view@u1"), 0)


# -- hot-spot sketch ----------------------------------------------------------


class TestHotSpotSketch:
    def test_counts_rise_and_estimate_does_not_increment(self):
        s = HotSpotSketch()
        for _ in range(5):
            s.observe(K)
        assert s.estimate(K) >= 5
        before = s.estimate(K)
        s.estimate(K)
        assert s.estimate(K) == before

    def test_top_orders_hottest_first(self):
        s = HotSpotSketch(top_k=4)
        keys = [check_key(T(f"Doc:d{i}#view@u1"), 0) for i in range(6)]
        for i, k in enumerate(keys):
            for _ in range(i + 1):
                s.observe(k)
        top = s.top()
        assert len(top) <= 4
        counts = [c for _, c in top]
        assert counts == sorted(counts, reverse=True)
        assert top[0][0] == keys[-1]

    def test_observe_many_matches_sequential(self):
        a, b = HotSpotSketch(), HotSpotSketch()
        keys = [check_key(T(f"Doc:d{i % 7}#view@u{i % 3}"), 0)
                for i in range(100)]
        for k in keys:
            a.observe(k)
        b.observe_many(keys)
        for k in set(keys):
            assert a.estimate(k) == b.estimate(k)

    def test_decay_halves_counts(self):
        s = HotSpotSketch(decay_every=64)
        for _ in range(63):
            s.observe(K)
        high = s.estimate(K)
        s.observe(K)  # crosses the decay boundary
        assert s.estimate(K) <= high // 2 + 1


# -- singleflight -------------------------------------------------------------


class TestSingleFlight:
    def test_concurrent_identical_calls_collapse(self):
        sf = SingleFlight()
        gate = threading.Event()
        calls = []

        def fn():
            gate.wait(5.0)
            calls.append(1)
            return 42

        results = []
        threads = [
            threading.Thread(
                target=lambda: results.append(sf.do("k", fn)))
            for _ in range(8)
        ]
        for t in threads:
            t.start()
        time.sleep(0.1)  # let every follower park
        gate.set()
        for t in threads:
            t.join()
        assert [v for v, _ in results] == [42] * 8
        assert len(calls) == 1
        assert sum(1 for _, led in results if led) == 1
        assert sf.collapsed == 7

    def test_leader_exception_propagates_to_followers(self):
        sf = SingleFlight()
        gate = threading.Event()

        def fn():
            gate.wait(5.0)
            raise ValueError("boom")

        errors = []

        def run():
            try:
                sf.do("k", fn)
            except ValueError as e:
                errors.append(e)

        threads = [threading.Thread(target=run) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        gate.set()
        for t in threads:
            t.join()
        assert len(errors) == 4
        assert len({id(e) for e in errors}) == 1  # same exception object

    def test_follower_deadline_detaches_without_cancelling_leader(self):
        sf = SingleFlight()
        release = threading.Event()
        leader_done = threading.Event()

        def fn():
            release.wait(5.0)
            leader_done.set()
            return "late"

        leader = threading.Thread(target=lambda: sf.do("k", fn))
        leader.start()
        time.sleep(0.05)
        with pytest.raises(DeadlineExceededError):
            with deadline.scope(0.05):
                sf.do("k", lambda: "never")
        assert not leader_done.is_set()  # follower left, leader still going
        release.set()
        leader.join()
        assert leader_done.is_set()

    def test_sequential_calls_do_not_collapse(self):
        sf = SingleFlight()
        v1, led1 = sf.do("k", lambda: 1)
        v2, led2 = sf.do("k", lambda: 2)
        assert (v1, led1) == (1, True)
        assert (v2, led2) == (2, True)  # fresh flight, not a stale read
        assert sf.collapsed == 0


# -- result cache -------------------------------------------------------------


class TestResultCache:
    def test_roundtrip_and_hit_stats(self):
        rc = ResultCache()
        assert rc.lookup(K) is None
        rc.insert(K, True, 5)
        hit = rc.lookup(K)
        assert hit is not None and hit.value is True and hit.cursor == 5
        assert rc.stats()["hits"] == 1 and rc.stats()["misses"] == 1

    def test_default_mode_respects_fence(self):
        rc = ResultCache()
        rc.insert(K, True, 5)
        rc.advance_fence(9)
        assert rc.lookup(K) is None  # entry predates the fence
        rc.insert(K, True, 9)
        assert rc.lookup(K) is not None

    def test_namespace_fence_evicts_lazily(self):
        g = build_synth(n_users=4, n_groups=2, n_folders=2, n_docs=4)
        rc = ResultCache()
        rc.attach_store(g.store)
        head = g.store.log_head
        doc_key = check_key(T("Doc:d1#view@u1"), 0)
        grp_key = check_key(T("Group:g0#members@u1"), 0)
        rc.insert(doc_key, True, head)
        rc.insert(grp_key, True, head)
        g.store.transact_relation_tuples(
            [T("Doc:d1#viewers@u3")], []
        )
        # both miss default mode (the global fence advanced), but only
        # the Doc entry is EVICTED — the Group entry survives and still
        # serves an at-least-as-fresh request with an older token
        assert rc.lookup(doc_key) is None
        assert rc.lookup(grp_key) is None
        assert rc.evictions == 1
        with cache_context.scope(token=Snaptoken(version=1, cursor=head)):
            assert rc.lookup(grp_key) is not None
        with cache_context.scope(token=Snaptoken(version=1, cursor=head)):
            assert rc.lookup(doc_key) is None  # fenced out for good

    def test_lru_bounded(self):
        rc = ResultCache(max_entries=8, shards=1)
        for i in range(20):
            rc.insert(check_key(T(f"Doc:d{i}#view@u1"), 0), True, 1)
        assert len(rc) <= 8
        assert rc.evictions >= 12

    def test_never_replaces_fresher_with_staler(self):
        rc = ResultCache()
        rc.insert(K, True, 9)
        assert rc.insert(K, False, 5) is False
        assert rc.lookup(K).value is True

    def test_token_mode_uses_satisfies_cursor(self):
        rc = ResultCache()
        rc.insert(K, True, 5)
        with cache_context.scope(token=Snaptoken(version=1, cursor=5)):
            assert rc.lookup(K) is not None
        with cache_context.scope(token=Snaptoken(version=1, cursor=6)):
            assert rc.lookup(K) is None  # entry is staler than the token
        # legacy version-only tokens can never be proven fresh by a cursor
        with cache_context.scope(token=Snaptoken(version=1, cursor=-1)):
            assert rc.lookup(K) is None

    def test_latest_mode_floor(self):
        rc = ResultCache()
        rc.insert(K, True, 5)
        with cache_context.scope(floor=5):
            assert rc.lookup(K) is not None
        with cache_context.scope(floor=6):
            assert rc.lookup(K) is None

    def test_bypass_blocks_lookup_and_insert(self):
        rc = ResultCache()
        with cache_context.scope(bypass=True):
            assert rc.insert(K, True, 5) is False
            assert rc.lookup(K) is None
        assert len(rc) == 0
        rc.insert(K, True, 5)
        with cache_context.scope(bypass=True):
            assert rc.lookup(K) is None
        assert rc.hits == 0

    def test_nested_scope_keeps_outer_bypass(self):
        with cache_context.scope(bypass=True):
            with cache_context.scope(token=Snaptoken(version=1, cursor=1)):
                assert cache_context.bypassed()
        assert not cache_context.bypassed()

    def test_hot_threshold_gates_admission(self):
        rc = ResultCache(hot_threshold=3)
        assert rc.insert(K, True, 1) is False  # cold key: not admitted
        for _ in range(4):
            rc.lookup(K)
        assert rc.insert(K, True, 1) is True  # probes made it hot

    def test_changelog_overflow_fences_everything(self):
        g = build_synth(n_users=4, n_groups=2, n_folders=2, n_docs=4)
        rc = ResultCache()
        rc.attach_store(g.store)
        rc.insert(K, True, g.store.log_head)
        if not hasattr(g.store, "_log_cap"):
            pytest.skip("store exposes no changelog capacity")
        g.store._log_cap = 4  # force an overflow cheaply
        for i in range(8):
            g.store.transact_relation_tuples(
                [T(f"Doc:d0#viewers@burst{i}")], []
            )
        assert rc.lookup(K) is None

    def test_hot_keys_view(self):
        rc = ResultCache()
        for _ in range(5):
            rc.lookup(K)
        hot = rc.hot_keys()
        assert hot and hot[0]["key"] == pretty_key(K)
        assert hot[0]["count"] >= 5

    def test_expand_key_distinct_from_check_key(self):
        from ketotpu.api.types import SubjectSet

        s = SubjectSet("Doc", "d1", "view")
        assert expand_key(s, 0) != check_key(T("Doc:d1#view@u1"), 0)


# -- write-storm parity (fast legs) -------------------------------------------


def _storm_registry(**cache_overrides):
    g = build_synth(n_users=40, n_groups=8, n_folders=16, n_docs=60)
    cfg = {
        "engine": {"kind": "tpu", "frontier": 1024, "arena": 4096,
                   "max_batch": 256, "coalesce_ms": 1},
        "cache": dict({"enabled": True}, **cache_overrides),
        "log": {"request_log": False},
    }
    r = Registry(Provider(cfg), store=g.store, namespace_manager=g.manager)
    return g, r


def _run_storm(g, r, *, rounds=6, sample=8, seed=7):
    """Interleave random viewer grants/revokes with checks in all three
    consistency modes; every cached verdict must equal the bypassed one
    asked back-to-back (no write lands between the pair)."""
    import numpy as np

    from ketotpu.server.handlers import CheckHandler, RelationTupleHandler

    rng = np.random.default_rng(seed)
    check = CheckHandler(r)
    tuples = RelationTupleHandler(r)
    granted = []
    # a small query pool revisited every round so the cache actually
    # serves (the whole point of the shield is repeat traffic)
    pool = synth_queries(g, 24, seed=seed)

    for rnd in range(rounds):
        writes, deletes = [], []
        for _ in range(int(rng.integers(1, 4))):
            q = pool[int(rng.integers(len(pool)))]
            t = RelationTuple("Doc", q.object, "viewers", q.subject)
            writes.append(t)
            granted.append(t)
        if granted and rng.random() < 0.5:
            deletes.append(granted.pop(int(rng.integers(len(granted)))))
        tuples.transact_core(writes, deletes)
        token = check.snaptoken()

        idx = rng.choice(len(pool), size=sample, replace=False)
        for i in idx:
            q = pool[int(i)]
            for mode in ("default", "token", "latest"):
                kw = {}
                if mode == "token":
                    kw["snaptoken"] = token
                elif mode == "latest":
                    kw["latest"] = True
                cached = check.check_rest(q, 0, {}, **kw)
                bypass = check.check_rest(
                    q, 0, {"x-keto-cache": "bypass"}, **kw
                )
                assert cached == bypass, (
                    f"round {rnd} mode {mode}: cached={cached} "
                    f"bypass={bypass} for {q}"
                )


def test_write_storm_parity_all_modes():
    g, r = _storm_registry()
    _run_storm(g, r)
    rc = r.result_cache()
    assert rc is not None
    # ISSUE acceptance: the shield observably served traffic
    assert rc.hits > 0, rc.stats()
    assert r.metrics().get_counter(
        "keto_cache_hits_total", op="check"
    ) > 0


def test_write_storm_parity_strict_staleness():
    # max_staleness_ms=0: every probe re-syncs the fence from the
    # changelog — the tightest default-mode contract
    g, r = _storm_registry(max_staleness_ms=0)
    _run_storm(g, r, rounds=4, seed=11)
    assert r.result_cache().hits > 0


def test_cache_disabled_still_correct():
    g, r = _storm_registry(enabled=False)
    assert r.result_cache() is None
    _run_storm(g, r, rounds=2, seed=13)


def test_concurrent_identical_checks_collapse_through_handler():
    # acceptance: keto_singleflight_collapsed_total observably nonzero —
    # a cold-key herd through the full handler path collapses onto one
    # batch slot in the coalescer
    g, r = _storm_registry()
    from ketotpu.server.handlers import CheckHandler

    check = CheckHandler(r)
    q = synth_queries(g, 1, seed=17)[0]
    want = []

    def run():
        want.append(check.check_rest(q, 0, {}))

    threads = [threading.Thread(target=run) for _ in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(set(want)) == 1
    collapsed = r.metrics().get_counter("keto_singleflight_collapsed_total")
    engine = r.check_engine()
    assert engine.singleflight_collapsed > 0
    assert collapsed and collapsed > 0


def test_bypass_header_skips_cache_end_to_end():
    g, r = _storm_registry()
    from ketotpu.server.handlers import CheckHandler

    check = CheckHandler(r)
    q = synth_queries(g, 1, seed=19)[0]
    check.check_rest(q, 0, {})  # warm
    rc = r.result_cache()
    hits_before = rc.hits
    for _ in range(3):
        check.check_rest(q, 0, {"x-keto-cache": "bypass"})
    assert rc.hits == hits_before


# -- write-storm parity (slow leg: serve --workers 2) -------------------------


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _http(method, url, body=None, headers=None, timeout=30.0):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url, data=body, method=method, headers=headers or {}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


@pytest.mark.slow
def test_write_storm_parity_worker_topology(tmp_path):
    """The storm against a real ``serve --workers 2`` boot: worker-local
    caches fed by owner cursor piggybacks must stay bit-identical to the
    bypassed path in every mode, and the shield's counters must be
    observably nonzero on the metrics surface."""
    db = tmp_path / "cache.db"
    seed = Registry(Provider({"dsn": f"sqlite://{db}"}))
    seed.store().migrate_up()

    ports = {n: _free_port() for n in ("read", "write", "metrics", "opl")}
    config = {
        "dsn": f"sqlite://{db}",
        "serve": {
            n: {"host": "127.0.0.1", "port": p} for n, p in ports.items()
        },
        "namespaces": {
            "location": str(FIXTURES / "rewrites_namespaces.keto.ts")
        },
        "engine": {"kind": "tpu", "frontier": 512, "arena": 2048,
                   "max_batch": 128, "mesh_devices": 0,
                   "mesh_axis": "shard"},
        "consistency": {"barrier_timeout_ms": 5000},
        "cache": {"enabled": True, "max_staleness_ms": 50},
        "log": {"request_log": False},
    }
    cfg_path = tmp_path / "cache.json"
    cfg_path.write_text(json.dumps(config))

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ketotpu.cli", "serve",
         "-c", str(cfg_path), "--workers", "2"],
        env=env, cwd=str(pathlib.Path(__file__).parent.parent),
    )
    read = f"http://127.0.0.1:{ports['read']}"
    write = f"http://127.0.0.1:{ports['write']}"
    metrics = f"http://127.0.0.1:{ports['metrics']}"
    try:
        ready_by = time.monotonic() + 180.0
        while True:
            assert proc.poll() is None, "serve --workers died during boot"
            try:
                if _http("GET", f"{metrics}/health/ready",
                         timeout=2.0)[0] == 200:
                    break
            except OSError:
                pass
            assert time.monotonic() < ready_by, "topology never became ready"
            time.sleep(0.5)

        def check_url(i, mode, token=None):
            url = (f"{read}/relation-tuples/check/openapi?namespace=File"
                   f"&object=s{i}&relation=owners&subject_id=user{i}")
            if mode == "token":
                url += f"&snaptoken={token}"
            elif mode == "latest":
                url += "&latest=true"
            return url

        for rnd in range(6):
            t = RelationTuple.from_string(f"File:s{rnd}#owners@user{rnd}")
            status, _, headers = _http(
                "PUT", f"{write}/admin/relation-tuples",
                json.dumps(t.to_json()).encode(),
                {"Content-Type": "application/json"},
            )
            assert status == 201, f"write {rnd} failed"
            token = headers.get("X-Keto-Snaptoken")
            assert token
            # revisit every object written so far, all three modes,
            # cached vs bypassed back-to-back (twice, so repeat traffic
            # actually lands in and serves from the worker caches)
            for i in range(rnd + 1):
                for mode in ("default", "token", "latest"):
                    for _ in range(2):
                        s1, b1, _ = _http(
                            "GET", check_url(i, mode, token))
                        s2, b2, _ = _http(
                            "GET", check_url(i, mode, token),
                            headers={"X-Keto-Cache": "bypass"},
                        )
                        assert s1 == 200 and s2 == 200, (s1, b1, s2, b2)
                        a1 = json.loads(b1)["allowed"]
                        a2 = json.loads(b2)["allowed"]
                        assert a1 == a2 == True, (  # noqa: E712
                            f"round {rnd} obj {i} mode {mode}: "
                            f"cached={a1} bypass={a2}"
                        )

        _, prom, _ = _http("GET", f"{metrics}/metrics/prometheus")
        hits = [
            line for line in prom.splitlines()
            if line.startswith("keto_cache_hits_total")
        ]
        assert hits, "keto_cache_hits_total absent from metrics"
        assert any(float(line.rsplit(" ", 1)[1]) > 0 for line in hits), hits
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)
