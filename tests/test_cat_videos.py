"""Acceptance test: the cat-videos example end to end (BASELINE config #1).

Boots the daemon from the vendored `keto.yml` (legacy literal-namespace
config flavor), loads the example's relation-tuple JSON files through the
CLI transport, and checks the example's documented outcomes over REST —
including the `*` wildcard subject tuple.
"""

import json
import pathlib
import urllib.error
import urllib.parse
import urllib.request

import pytest

from ketotpu import cli
from ketotpu.api.types import RelationTuple
from ketotpu.driver import Provider, Registry
from ketotpu.server import serve_all

CAT_VIDEOS = pathlib.Path(__file__).parent / "fixtures" / "cat-videos"


@pytest.fixture(scope="module")
def server():
    cfg = Provider(
        {
            "serve": {
                n: {"host": "127.0.0.1", "port": 0}
                for n in ("read", "write", "metrics", "opl")
            },
            "engine": {"kind": "oracle"},
        },
        config_file=str(CAT_VIDEOS / "keto.yml"),
    )
    assert cfg.namespaces_config() == [{"id": 0, "name": "videos"}]
    srv = serve_all(Registry(cfg).init())
    yield srv
    srv.stop()


def _get(url):
    try:
        with urllib.request.urlopen(url) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_load_tuples_via_cli_and_check_via_rest(server):
    write = "%s:%d" % tuple(server.addresses["write"])
    read = "http://%s:%d" % tuple(server.addresses["read"])
    rc = cli.main(
        [
            "relation-tuple", "create",
            str(CAT_VIDEOS / "relation-tuples"),
            "--write-remote", write,
            # the client defaults to TLS like the reference; the test
            # daemon is plaintext
            "--insecure-disable-transport-security",
        ]
    )
    assert rc == 0

    cases = [
        ("videos:/cats/1.mp4#view@*", True),  # public wildcard subject
        ("videos:/cats/1.mp4#owner@cat lady", True),  # via /cats#owner
        ("videos:/cats/2.mp4#view@cat lady", True),  # owner subject-set chain
        ("videos:/cats/2.mp4#view@dog lady", False),
    ]
    for case, want in cases:
        t = RelationTuple.from_string(case)
        q = urllib.parse.urlencode(t.to_url_query())
        status, body = _get(f"{read}/relation-tuples/check/openapi?{q}")
        assert status == 200, body
        assert json.loads(body)["allowed"] is want, case


def test_wildcard_is_literal_not_glob(server):
    # '*' is a plain subject string at this version, not a glob: only
    # tuples that literally contain it match
    read = "http://%s:%d" % tuple(server.addresses["read"])
    t = RelationTuple.from_string("videos:/cats/2.mp4#view@*")
    q = urllib.parse.urlencode(t.to_url_query())
    status, body = _get(f"{read}/relation-tuples/check/openapi?{q}")
    assert json.loads(body)["allowed"] is False
