"""Chaos suite: fault injection, deadline propagation, and load shedding.

Zanzibar's availability contract is "answer inside the deadline or say
you can't" — never hang, never wedge a serving thread forever.  This
suite drives the fault plan in ketotpu/faults.py through every layer
that makes that promise:

* unit: the deadline budget carrier, the fault plan, admission control;
* engine: coalescer slot waits bounded by the budget, backlog shedding,
  device-dispatch errors falling back to the CPU oracle with correct
  verdicts and a degraded health surface;
* worker RPC: connection desync discard, capped-backoff reconnect
  riding out an owner restart, budget forwarding over the unix socket;
* e2e: a wedged engine answers 504/DEADLINE_EXCEEDED fast instead of
  hanging, admission sheds with 429/RESOURCE_EXHAUSTED + Retry-After,
  health Watch streams status flips, and a mixed check/expand storm
  under an active fault plan completes with zero hung requests and
  oracle-correct verdicts (the slow variant runs the full 500-request
  acceptance storm against ``serve --workers 2`` subprocesses).
"""

import json
import os
import pathlib
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import grpc
import pytest

from ketotpu import deadline, faults
from ketotpu.api.types import (
    BadRequestError,
    DeadlineExceededError,
    KetoAPIError,
    RelationTuple,
    TooManyRequestsError,
)
from ketotpu.driver import Provider, Registry
from ketotpu.proto import check_service_pb2 as cs
from ketotpu.proto import health_pb2
from ketotpu.proto.services import CheckServiceStub, _stub_class
from ketotpu.server import serve_all

FIXTURES = pathlib.Path(__file__).parent / "fixtures"

SEED_TUPLES = [
    "Group:admin#members@alice",
    "Group:dev#members@bob",
    "Folder:keto#viewers@Group:dev#members",
    "File:keto/README.md#parents@Folder:keto",
    "File:private#owners@alice",
]

# (tuple string, expected verdict) — must stay correct under any fault
# plan: non-shed answers are either right or an explicit error
CASES = [
    ("File:keto/README.md#view@bob", True),
    ("File:keto/README.md#view@alice", False),
    ("Folder:keto#view@bob", True),
    ("File:private#view@alice", True),
    ("File:private#view@bob", False),
    ("File:nonexistent#view@bob", False),
]


@pytest.fixture(autouse=True)
def _no_fault_leak(monkeypatch):
    """Every test starts and ends on an inert fault plan.

    Ambient KETO_FAULT_* variables (the CI chaos job sets some) are
    scrubbed for the in-process tests — each test configures exactly the
    plan it asserts against; the subprocess storm passes its own env.
    """
    for k in list(os.environ):
        if k.startswith("KETO_FAULT_"):
            monkeypatch.delenv(k)
    faults.reset()
    yield
    faults.reset()


def _http(method, url, body=None, headers=None, timeout=30.0):
    req = urllib.request.Request(
        url, data=body, method=method, headers=headers or {}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


def _check_url(addr, case):
    q = urllib.parse.urlencode(
        RelationTuple.from_string(case).to_url_query()
    )
    return f"{addr}/relation-tuples/check/openapi?{q}"


# -- deadline module ---------------------------------------------------------


class TestDeadline:
    def test_no_scope_is_passthrough(self):
        assert deadline.current() is None
        assert deadline.remaining() is None
        assert deadline.deadline_ms() is None
        deadline.check()  # no budget, never raises

    def test_scope_binds_and_restores(self):
        with deadline.scope(5.0):
            left = deadline.remaining()
            assert left is not None and 4.0 < left <= 5.0
            assert 4000 < deadline.deadline_ms() <= 5000
        assert deadline.remaining() is None

    def test_nested_scope_keeps_tighter_deadline(self):
        with deadline.scope(5.0):
            outer = deadline.current()
            with deadline.scope(60.0):  # looser: must NOT extend
                assert deadline.current() == outer
            with deadline.scope(0.5):  # tighter: shrinks
                assert deadline.current() < outer
            assert deadline.current() == outer

    def test_none_and_absurd_scopes_are_passthrough(self):
        with deadline.scope(None):
            assert deadline.remaining() is None
        # gRPC reports a huge time_remaining() for deadline-less calls;
        # feeding it into Event.wait() would overflow _PyTime_t
        with deadline.scope(1e9):
            assert deadline.remaining() is None

    def test_check_raises_after_expiry(self):
        with deadline.scope(0.005):
            time.sleep(0.02)
            assert deadline.remaining() <= 0
            assert deadline.deadline_ms() == 0  # clamped, not negative
            with pytest.raises(DeadlineExceededError):
                deadline.check("unit test")

    def test_parse_timeout_formats(self):
        assert deadline.parse_timeout(None) is None
        assert deadline.parse_timeout("") is None
        assert deadline.parse_timeout("50ms") == pytest.approx(0.05)
        assert deadline.parse_timeout("1.5s") == pytest.approx(1.5)
        assert deadline.parse_timeout("2") == pytest.approx(2.0)
        assert deadline.parse_timeout(0.25) == pytest.approx(0.25)

    def test_parse_timeout_rejects_garbage(self):
        for bad in ("soon", "ms", "-1s", "0"):
            with pytest.raises(BadRequestError):
                deadline.parse_timeout(bad)


# -- fault plan --------------------------------------------------------------


class TestFaultPlan:
    def test_inactive_plan_is_a_noop(self):
        assert not faults.plan().active
        faults.inject("device_dispatch")  # must not raise or sleep
        assert faults.should("socket_drop") is False

    def test_device_error_injection_counts(self):
        p = faults.configure(device_error_rate=1.0)
        with pytest.raises(faults.FaultInjected):
            faults.inject("device_dispatch")
        assert p.injected["device_error"] == 1

    def test_latency_rate_defaults_to_always(self):
        p = faults.FaultPlan(latency_ms=5.0)
        assert p.latency_rate == 1.0
        assert faults.FaultPlan(latency_ms=5.0, latency_rate=0.25).latency_rate == 0.25

    def test_seeded_rolls_are_deterministic(self):
        a = faults.FaultPlan(device_error_rate=0.5, seed=7)
        b = faults.FaultPlan(device_error_rate=0.5, seed=7)
        assert [a._roll(0.5) for _ in range(32)] == [
            b._roll(0.5) for _ in range(32)
        ]

    def test_from_env_reads_knobs(self):
        p = faults.FaultPlan.from_env({
            "KETO_FAULT_DEVICE_ERROR_RATE": "0.2",
            "KETO_FAULT_LATENCY_MS": "50",
            "KETO_FAULT_SEED": "3",
        })
        assert p.device_error_rate == 0.2
        assert p.latency_ms == 50.0 and p.latency_rate == 1.0
        assert p.active

    def test_configure_from_config_block(self):
        cfg = Provider({"faults": {"device_stall_ms": 7.0}})
        faults.configure_from_config(cfg)
        assert faults.plan().device_stall_ms == 7.0

    def test_env_wins_over_config(self, monkeypatch):
        monkeypatch.setenv("KETO_FAULT_SOCKET_DROP_RATE", "0.5")
        faults.reset()
        cfg = Provider({"faults": {"device_stall_ms": 7.0}})
        faults.configure_from_config(cfg)
        assert faults.plan().socket_drop_rate == 0.5
        assert faults.plan().device_stall_ms == 0.0


# -- admission control -------------------------------------------------------


class TestAdmission:
    def test_bounded_acquire_release(self):
        from ketotpu.server.admission import AdmissionController

        ctl = AdmissionController(2)
        assert ctl.enabled
        assert ctl.try_acquire() and ctl.try_acquire()
        assert not ctl.try_acquire()  # at the limit: shed
        assert ctl.shed == 1
        ctl.release()
        assert ctl.try_acquire()

    def test_zero_limit_disables(self):
        from ketotpu.server.admission import AdmissionController

        ctl = AdmissionController(0)
        assert not ctl.enabled
        assert all(ctl.try_acquire() for _ in range(1000))
        assert ctl.shed == 0


# -- coalescer deadlines and shedding ---------------------------------------


class _BlockingEngine:
    """Stub inner engine: batch_check blocks on an event (a wedged device)."""

    def __init__(self):
        self.release = threading.Event()
        self.entered = threading.Event()

    def batch_check(self, queries, rest_depth=0):
        self.entered.set()
        self.release.wait(30.0)
        return [True] * len(queries)

    def check_is_member(self, r, rest_depth=0):
        return self.batch_check([r], rest_depth)[0]


class TestCoalescerDeadline:
    def test_default_timeout_bounds_slot_wait(self):
        from ketotpu.engine.coalesce import CoalescingEngine

        inner = _BlockingEngine()
        eng = CoalescingEngine(inner, window=0.001, default_timeout=0.05)
        try:
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceededError):
                eng.check_is_member(RelationTuple.from_string("n:o#r@s"))
            assert time.monotonic() - t0 < 2.0
            assert eng.deadline_exceeded == 1
        finally:
            inner.release.set()
            eng.close()

    def test_request_deadline_tighter_than_default(self):
        from ketotpu.engine.coalesce import CoalescingEngine

        inner = _BlockingEngine()
        eng = CoalescingEngine(inner, window=0.001, default_timeout=30.0)
        try:
            t0 = time.monotonic()
            with deadline.scope(0.05):
                with pytest.raises(DeadlineExceededError):
                    eng.check_is_member(RelationTuple.from_string("n:o#r@s"))
            assert time.monotonic() - t0 < 2.0
        finally:
            inner.release.set()
            eng.close()

    def test_expired_budget_rejected_before_enqueue(self):
        from ketotpu.engine.coalesce import CoalescingEngine

        inner = _BlockingEngine()
        eng = CoalescingEngine(inner, window=0.001)
        try:
            with deadline.scope(0.001):
                time.sleep(0.01)
                with pytest.raises(DeadlineExceededError):
                    eng.check_is_member(RelationTuple.from_string("n:o#r@s"))
            assert not inner.entered.is_set()  # never reached the device
        finally:
            inner.release.set()
            eng.close()

    def test_backlog_full_sheds(self):
        from ketotpu.engine.coalesce import CoalescingEngine

        inner = _BlockingEngine()
        # pipeline=False: with double-buffering on, the collector cuts the
        # filler slots into a staged wave (emptying _pending) before the
        # shed probe runs, so the probe queues and times out instead of
        # shedding.  The single-threaded path keeps the backlog observable
        # while the worker is wedged inside the inner engine.
        eng = CoalescingEngine(inner, window=0.001, max_pending=2,
                               default_timeout=10.0, pipeline=False)
        threads = []
        try:
            # occupy the wave worker inside the blocked inner engine
            t = threading.Thread(
                target=lambda: eng.check_is_member(
                    RelationTuple.from_string("n:o#r@w")
                ),
                daemon=True,
            )
            t.start()
            threads.append(t)
            assert inner.entered.wait(5.0)
            # with the worker stuck, fill the backlog to max_pending...
            for i in range(2):
                ti = threading.Thread(
                    target=lambda i=i: eng.check_is_member(
                        RelationTuple.from_string(f"n:o{i}#r@s")
                    ),
                    daemon=True,
                )
                ti.start()
                threads.append(ti)
            for _ in range(100):
                with eng._lock:
                    if len(eng._pending) >= 2:
                        break
                time.sleep(0.01)
            # ...and the next caller is shed instead of queued
            with pytest.raises(TooManyRequestsError):
                eng.check_is_member(RelationTuple.from_string("n:o#r@shed"))
            assert eng.shed == 1
        finally:
            inner.release.set()
            eng.close()
            for t in threads:
                t.join(timeout=5.0)


# -- device faults fall back to the oracle ----------------------------------


class TestDeviceFaultFallback:
    def test_injected_device_errors_keep_verdicts_correct(self):
        reg = Registry(Provider({
            "namespaces": {
                "location": str(FIXTURES / "rewrites_namespaces.keto.ts")
            },
            "engine": {"kind": "tpu", "frontier": 512, "arena": 1024,
                       "max_batch": 128, "mesh_devices": 0,
                       "mesh_axis": "shard"},
        }))
        reg.store().write_relation_tuples(
            *[RelationTuple.from_string(s) for s in SEED_TUPLES]
        )
        reg.init()
        eng = reg.check_engine()
        dev = getattr(eng, "inner", eng)
        assert not dev.is_degraded()
        faults.configure(device_error_rate=1.0, seed=11)
        queries = [RelationTuple.from_string(c) for c, _ in CASES]
        got = eng.batch_check(queries)
        assert got == [want for _, want in CASES]
        # the engine took failures, served on the oracle, and says so
        assert dev.device_failures > 0
        assert dev.fallbacks >= len(CASES)
        assert dev.is_degraded()
        health = reg.health()
        assert str(health.get("engine", "")).startswith("degraded")
        # recovery: with the fault lifted the device serves again and the
        # degraded flag decays once the window passes
        faults.reset()
        dev.degraded_window = 0.05
        time.sleep(0.1)
        assert eng.batch_check(queries) == [want for _, want in CASES]
        assert not dev.is_degraded()
        assert "engine" not in reg.health()


# -- worker RPC: desync, reconnect backoff, budget forwarding ----------------


def _oracle_host(tmp_path, name):
    owner = Registry(Provider({
        "dsn": f"sqlite://{tmp_path}/{name}.db",
        "namespaces": {
            "location": str(FIXTURES / "rewrites_namespaces.keto.ts")
        },
        "engine": {"kind": "oracle"},
    }))
    owner.store().migrate_up()
    owner.store().write_relation_tuples(
        *[RelationTuple.from_string(s) for s in SEED_TUPLES]
    )
    return owner


class TestRemoteEngineChaos:
    def test_timeout_discards_connection_and_raises_deadline(self, tmp_path):
        from ketotpu.server.workers import _Conn

        # a server that accepts but never answers: the classic desync —
        # after a timed-out exchange the connection MUST be discarded,
        # or the next call would read this request's late response
        path = str(tmp_path / "mute.sock")
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)
        srv.listen(1)
        try:
            conn = _Conn(path)
            with pytest.raises(TimeoutError):
                conn.call({"op": "ping"}, timeout=0.05)
            assert conn.broken
            with pytest.raises(ConnectionError):
                conn.call({"op": "ping"}, timeout=0.05)
        finally:
            srv.close()

    def test_garbage_response_discards_connection(self, tmp_path):
        from ketotpu.server.workers import _Conn

        path = str(tmp_path / "garbage.sock")
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)
        srv.listen(1)

        def answer_garbage():
            peer, _ = srv.accept()
            peer.recv(4096)
            peer.sendall(b"not json at all\n")
            peer.close()

        t = threading.Thread(target=answer_garbage, daemon=True)
        t.start()
        try:
            conn = _Conn(path)
            with pytest.raises(ValueError):
                conn.call({"op": "ping"}, timeout=2.0)
            assert conn.broken  # stream desynced: never reuse
        finally:
            srv.close()
            t.join(timeout=5.0)

    def test_typed_error_keeps_connection(self, tmp_path):
        from ketotpu.server.workers import EngineHostServer, RemoteCheckEngine

        owner = _oracle_host(tmp_path, "typed")
        sock = str(tmp_path / "typed.sock")
        host = EngineHostServer(owner, sock).start()
        try:
            remote = RemoteCheckEngine(sock)
            with pytest.raises(KetoAPIError) as ei:
                remote.check(RelationTuple.from_string("Folder:f#nosuch@a"))
            assert ei.value.status_code == 400
            # the exchange completed; the pooled connection still works
            assert remote._conn().broken is False
            assert remote.check(
                RelationTuple.from_string("Folder:keto#view@bob")
            ) is True
            assert remote.reconnects == 0
        finally:
            host.stop()

    def test_injected_socket_drops_retry_through(self, tmp_path):
        from ketotpu.server.workers import EngineHostServer, RemoteCheckEngine

        owner = _oracle_host(tmp_path, "drops")
        sock = str(tmp_path / "drops.sock")
        host = EngineHostServer(owner, sock).start()
        try:
            faults.configure(socket_drop_rate=0.5, seed=5)
            remote = RemoteCheckEngine(sock)
            q = RelationTuple.from_string("Folder:keto#view@bob")
            # P(5 consecutive drops) = 3% per call; 12 calls make a
            # failure astronomically unlikely while guaranteeing several
            # drop->backoff->reconnect cycles at rate 0.5
            assert all(remote.check(q) for _ in range(12))
            assert faults.plan().injected.get("socket_drop", 0) > 0
            assert remote.reconnects > 0
        finally:
            host.stop()

    def test_permanent_drop_exhausts_retries(self, tmp_path):
        from ketotpu.server.workers import EngineHostServer, RemoteCheckEngine

        owner = _oracle_host(tmp_path, "dead")
        sock = str(tmp_path / "dead.sock")
        host = EngineHostServer(owner, sock).start()
        host.stop()  # owner is gone and stays gone
        faults.reset()
        remote = RemoteCheckEngine(sock)
        t0 = time.monotonic()
        with pytest.raises(ConnectionError):
            remote.check(RelationTuple.from_string("Folder:keto#view@bob"))
        # capped backoff: fails in bounded time, not a hang
        assert time.monotonic() - t0 < 5.0

    def test_backoff_rides_out_owner_restart(self, tmp_path):
        from ketotpu.server.workers import EngineHostServer, RemoteCheckEngine

        owner = _oracle_host(tmp_path, "restart")
        sock = str(tmp_path / "restart.sock")
        host = EngineHostServer(owner, sock).start()
        host.stop()
        restarted = {}

        def bring_back():
            time.sleep(0.05)
            restarted["host"] = EngineHostServer(owner, sock).start()

        t = threading.Thread(target=bring_back, daemon=True)
        t.start()
        try:
            remote = RemoteCheckEngine(sock)
            remote.retry_attempts = 10  # generous for slow CI
            assert remote.check(
                RelationTuple.from_string("Folder:keto#view@bob")
            ) is True
            assert remote.reconnects > 0
        finally:
            t.join(timeout=5.0)
            if "host" in restarted:
                restarted["host"].stop()

    def test_deadline_forwarded_over_the_socket(self, tmp_path):
        from ketotpu.server.workers import EngineHostServer, RemoteCheckEngine

        owner = _oracle_host(tmp_path, "fwd")
        sock = str(tmp_path / "fwd.sock")
        host = EngineHostServer(owner, sock).start()
        try:
            # spike the owner handler past the caller's budget: the worker
            # must answer DEADLINE_EXCEEDED, not wait out the spike
            faults.configure(latency_ms=500.0)
            remote = RemoteCheckEngine(sock)
            t0 = time.monotonic()
            with deadline.scope(0.05):
                with pytest.raises(DeadlineExceededError):
                    remote.check(
                        RelationTuple.from_string("Folder:keto#view@bob")
                    )
            assert time.monotonic() - t0 < 2.0
        finally:
            host.stop()

    def test_expired_budget_fails_before_the_wire(self, tmp_path):
        from ketotpu.server.workers import RemoteCheckEngine

        remote = RemoteCheckEngine(str(tmp_path / "never.sock"))
        with deadline.scope(0.001):
            time.sleep(0.01)
            with pytest.raises(DeadlineExceededError):
                remote.check(RelationTuple.from_string("n:o#r@s"))


class TestWorkerSupervisor:
    def test_respawns_dead_worker_with_degraded_state(self):
        from ketotpu.server.workers import WorkerSupervisor

        def spawn(i):
            # worker 0 dies instantly once; everyone else idles
            if i == 0 and not spawned[0]:
                spawned[0] = True
                return subprocess.Popen([sys.executable, "-c", "pass"])
            return subprocess.Popen([sys.executable, "-c",
                                     "import time; time.sleep(30)"])

        spawned = [False]
        sup = WorkerSupervisor(spawn, 2, backoff_base=0.05, backoff_cap=0.1)
        sup.start()
        try:
            deadline_at = time.monotonic() + 10.0
            degraded_seen = False
            while time.monotonic() < deadline_at:
                assert sup.poll() is None
                state = sup.state()
                if state.startswith("degraded"):
                    degraded_seen = True
                if sup.respawns and state == "ok":
                    break
                time.sleep(0.02)
            assert degraded_seen, "death must surface as degraded"
            assert sup.respawns == 1
            assert sup.state() == "ok"
        finally:
            sup.terminate()

    def test_rapid_deaths_give_up(self):
        from ketotpu.server.workers import WorkerSupervisor

        sup = WorkerSupervisor(
            lambda i: subprocess.Popen([sys.executable, "-c", "exit(3)"]),
            1, max_rapid_deaths=3, backoff_base=0.01, backoff_cap=0.02,
        )
        sup.start()
        try:
            rc = None
            deadline_at = time.monotonic() + 15.0
            while rc is None and time.monotonic() < deadline_at:
                rc = sup.poll()
                time.sleep(0.02)
            assert rc == 1, "flapping worker must make the supervisor give up"
        finally:
            sup.terminate()


# -- e2e: daemon under faults ------------------------------------------------


@pytest.fixture(scope="module")
def chaos_server():
    cfg = Provider({
        "serve": {
            n: {"host": "127.0.0.1", "port": 0}
            for n in ("read", "write", "metrics", "opl")
        },
        "namespaces": {
            "location": str(FIXTURES / "rewrites_namespaces.keto.ts")
        },
        "engine": {"kind": "tpu", "frontier": 512, "arena": 2048,
                   "max_batch": 128, "mesh_devices": 0,
                   "mesh_axis": "shard"},
        "limit": {"request_timeout_ms": 10000},
    })
    reg = Registry(cfg).init()
    srv = serve_all(reg)
    reg.store().write_relation_tuples(
        *[RelationTuple.from_string(s) for s in SEED_TUPLES]
    )
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def read_addr(chaos_server):
    return "http://%s:%d" % tuple(chaos_server.addresses["read"])


@pytest.fixture(scope="module")
def metrics_addr(chaos_server):
    return "http://%s:%d" % tuple(chaos_server.addresses["metrics"])


class TestAdmissionE2E:
    def test_rest_shed_answers_429_with_retry_after(
        self, chaos_server, read_addr, metrics_addr
    ):
        ctl = chaos_server.registry.admission()
        # saturate far past any value the AIMD controller could grow the
        # limit to mid-test: next arrival is shed
        ctl.inflight = 10**9
        try:
            status, body, headers = _http(
                "GET", _check_url(read_addr, CASES[0][0])
            )
            assert status == 429, body
            # load-derived hint: a positive integer, jittered per response
            assert int(headers.get("Retry-After")) >= 1
            assert json.loads(body)["error"]["code"] == 429
            # health stays exempt so probes see through the shed
            astatus, _, _ = _http("GET", f"{read_addr}/health/alive")
            assert astatus == 200
        finally:
            ctl.inflight = 0
        # and a normal request flows again
        status, body, _ = _http("GET", _check_url(read_addr, CASES[0][0]))
        assert status == 200 and json.loads(body)["allowed"] is True
        # shed accounting reaches the scrape surface
        _, text, _ = _http("GET", f"{metrics_addr}/metrics/prometheus")
        assert "keto_requests_shed_total" in text
        assert 'transport="rest"' in text

    def test_grpc_shed_answers_resource_exhausted(
        self, chaos_server, read_addr
    ):
        from ketotpu.api.proto_codec import tuple_to_proto

        ctl = chaos_server.registry.admission()
        addr = "%s:%d" % tuple(chaos_server.addresses["read"])
        with grpc.insecure_channel(addr) as ch:
            stub = CheckServiceStub(ch)
            req = cs.CheckRequest(
                tuple=tuple_to_proto(RelationTuple.from_string(CASES[0][0]))
            )
            assert stub.Check(req).allowed is True  # channel warm
            ctl.inflight = 10**9
            try:
                with pytest.raises(grpc.RpcError) as ei:
                    stub.Check(req)
                assert ei.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
                # cooperative retry hint rides the trailing metadata
                trailing = dict(ei.value.trailing_metadata() or ())
                assert int(trailing.get("retry-after", "0")) >= 1
                # health service is exempt: probes still answered
                health = _stub_class("grpc.health.v1.Health")(ch)
                resp = health.Check(health_pb2.HealthCheckRequest())
                assert resp.status == health_pb2.HealthCheckResponse.SERVING
            finally:
                ctl.inflight = 0
            assert stub.Check(req).allowed is True


class TestHealthDegraded:
    def test_degraded_readiness_still_serves(self, chaos_server, metrics_addr):
        reg = chaos_server.registry
        reg.readiness_checks["workers"] = (
            lambda: "degraded: respawning worker(s) 1"
        )
        try:
            status, body, _ = _http("GET", f"{metrics_addr}/health/ready")
            assert status == 200
            payload = json.loads(body)
            assert payload["status"] == "degraded"
            assert "workers" in payload["degraded"]
            # gRPC keeps the binary protocol: degraded is still SERVING
            addr = "%s:%d" % tuple(chaos_server.addresses["read"])
            with grpc.insecure_channel(addr) as ch:
                health = _stub_class("grpc.health.v1.Health")(ch)
                resp = health.Check(health_pb2.HealthCheckRequest())
                assert resp.status == health_pb2.HealthCheckResponse.SERVING
        finally:
            del reg.readiness_checks["workers"]
        status, body, _ = _http("GET", f"{metrics_addr}/health/ready")
        assert status == 200 and json.loads(body)["status"] == "ok"

    def test_watch_streams_status_changes(self, chaos_server):
        reg = chaos_server.registry
        addr = "%s:%d" % tuple(chaos_server.addresses["read"])
        with grpc.insecure_channel(addr) as ch:
            health = _stub_class("grpc.health.v1.Health")(ch)
            stream = health.Watch(health_pb2.HealthCheckRequest(), timeout=15)
            try:
                first = next(stream)
                assert first.status == health_pb2.HealthCheckResponse.SERVING

                def down():
                    raise RuntimeError("db gone")

                reg.readiness_checks["chaos_db"] = down
                try:
                    assert (
                        next(stream).status
                        == health_pb2.HealthCheckResponse.NOT_SERVING
                    )
                finally:
                    del reg.readiness_checks["chaos_db"]
                assert (
                    next(stream).status
                    == health_pb2.HealthCheckResponse.SERVING
                )
            finally:
                stream.cancel()


class TestDeadlineE2E:
    def test_malformed_timeout_header_is_a_client_error(self, read_addr):
        status, body, _ = _http(
            "GET", _check_url(read_addr, CASES[0][0]),
            headers={"X-Request-Timeout": "whenever"},
        )
        assert status == 400, body

    def test_generous_timeout_header_passes_through(self, read_addr):
        status, body, _ = _http(
            "GET", _check_url(read_addr, CASES[0][0]),
            headers={"X-Request-Timeout": "10s"},
        )
        assert status == 200 and json.loads(body)["allowed"] is True


def test_wedged_engine_answers_deadline_exceeded_fast():
    """Acceptance: a 50ms-deadline check against an engine wedged by an
    injected 5s dispatch stall returns 504 (REST) / DEADLINE_EXCEEDED
    (gRPC) quickly, and the stage histogram records the deadline."""
    from ketotpu.api.proto_codec import tuple_to_proto

    cfg = Provider({
        "serve": {
            n: {"host": "127.0.0.1", "port": 0}
            for n in ("read", "write", "metrics", "opl")
        },
        "namespaces": {
            "location": str(FIXTURES / "rewrites_namespaces.keto.ts")
        },
        "engine": {"kind": "tpu", "frontier": 512, "arena": 2048,
                   "max_batch": 128, "mesh_devices": 0,
                   "mesh_axis": "shard"},
    })
    reg = Registry(cfg).init()
    srv = serve_all(reg)
    try:
        reg.store().write_relation_tuples(
            *[RelationTuple.from_string(s) for s in SEED_TUPLES]
        )
        read = "http://%s:%d" % tuple(srv.addresses["read"])
        # warm the serving path (first dispatch compiles) BEFORE wedging
        status, body, _ = _http("GET", _check_url(read, CASES[0][0]))
        assert status == 200, body
        faults.configure(device_stall_ms=5000.0)

        # the warm pass cached this exact verdict — bypass the hot-spot
        # shield so the check actually reaches the wedged device
        t0 = time.monotonic()
        status, body, _ = _http(
            "GET", _check_url(read, CASES[0][0]),
            headers={"X-Request-Timeout": "50ms",
                     "X-Keto-Cache": "bypass"},
        )
        rest_elapsed = time.monotonic() - t0
        assert status == 504, body
        assert json.loads(body)["error"]["code"] == 504
        # acceptance bound is 200ms; allow headroom for CI scheduling
        assert rest_elapsed < 1.0, f"504 took {rest_elapsed:.3f}s"

        addr = "%s:%d" % tuple(srv.addresses["read"])
        with grpc.insecure_channel(addr) as ch:
            stub = CheckServiceStub(ch)
            req = cs.CheckRequest(
                tuple=tuple_to_proto(RelationTuple.from_string(CASES[0][0]))
            )
            t0 = time.monotonic()
            with pytest.raises(grpc.RpcError) as ei:
                stub.Check(req, timeout=0.05,
                           metadata=(("x-keto-cache", "bypass"),))
            grpc_elapsed = time.monotonic() - t0
            assert ei.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
            assert grpc_elapsed < 1.0, f"took {grpc_elapsed:.3f}s"

        metrics = "http://%s:%d" % tuple(srv.addresses["metrics"])
        _, text, _ = _http("GET", f"{metrics}/metrics/prometheus")
        assert "keto_rpc_stage_seconds" in text
        assert 'stage="deadline"' in text
    finally:
        faults.reset()
        srv.stop()


class TestStormInProcess:
    def test_mixed_storm_under_faults_resolves_everything(
        self, chaos_server, read_addr
    ):
        """Tier-1-sized storm: 80 mixed check/expand requests across 8
        threads under an active fault plan (device errors + latency
        spikes).  Every request must resolve within its deadline with an
        oracle-correct verdict or an explicit shed/deadline status."""
        faults.configure(device_error_rate=0.2, latency_ms=5.0,
                         latency_rate=0.3, seed=42)
        expand_url = (
            f"{read_addr}/relation-tuples/expand?"
            "namespace=Folder&object=keto&relation=viewers&max-depth=3"
        )
        results = []
        lock = threading.Lock()

        def one(i):
            case, want = CASES[i % len(CASES)]
            try:
                if i % 5 == 4:
                    status, body, _ = _http(
                        "GET", expand_url,
                        headers={"X-Request-Timeout": "5s"}, timeout=10.0,
                    )
                    ok = status in (200, 429, 504)
                else:
                    status, body, _ = _http(
                        "GET", _check_url(read_addr, case),
                        headers={"X-Request-Timeout": "5s"}, timeout=10.0,
                    )
                    ok = status in (429, 504) or (
                        status == 200
                        and json.loads(body)["allowed"] is want
                    )
                with lock:
                    results.append((i, status, ok))
            except Exception as e:  # noqa: BLE001 - a hang IS the failure
                with lock:
                    results.append((i, f"exc:{e}", False))

        n = 80
        threads = [
            threading.Thread(target=one, args=(i,), daemon=True)
            for i in range(n)
        ]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        assert time.monotonic() - t0 < 60.0, "storm wall-clock blew up"
        assert len(results) == n, "every request must resolve (zero hangs)"
        bad = [r for r in results if not r[2]]
        assert not bad, f"wrong verdicts/statuses: {bad[:10]}"


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_acceptance_storm_against_worker_topology(tmp_path):
    """The ISSUE's acceptance storm: 500 mixed check/expand requests
    against ``serve --workers 2`` under device-error rate 0.2, socket
    drops 0.1, and 50ms latency spikes.  Zero hung RPCs: every request
    resolves within its deadline or is shed; non-shed verdicts match
    the oracle."""
    db = tmp_path / "storm.db"
    seed_reg = Registry(Provider({"dsn": f"sqlite://{db}"}))
    seed_reg.store().migrate_up()
    seed_reg.store().write_relation_tuples(
        *[RelationTuple.from_string(s) for s in SEED_TUPLES]
    )

    ports = {n: _free_port() for n in ("read", "write", "metrics", "opl")}
    config = {
        "dsn": f"sqlite://{db}",
        "serve": {
            n: {"host": "127.0.0.1", "port": p} for n, p in ports.items()
        },
        "namespaces": {
            "location": str(FIXTURES / "rewrites_namespaces.keto.ts")
        },
        "engine": {"kind": "tpu", "frontier": 512, "arena": 2048,
                   "max_batch": 128, "mesh_devices": 0,
                   "mesh_axis": "shard"},
        "log": {"request_log": False},
    }
    cfg_path = tmp_path / "storm.json"
    cfg_path.write_text(json.dumps(config))

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "KETO_FAULT_DEVICE_ERROR_RATE": "0.2",
        "KETO_FAULT_SOCKET_DROP_RATE": "0.1",
        "KETO_FAULT_LATENCY_MS": "50",
        "KETO_FAULT_LATENCY_RATE": "0.2",
        "KETO_FAULT_SEED": "1234",
    })
    proc = subprocess.Popen(
        [sys.executable, "-m", "ketotpu.cli", "serve",
         "-c", str(cfg_path), "--workers", "2"],
        env=env, cwd=str(pathlib.Path(__file__).parent.parent),
    )
    read = f"http://127.0.0.1:{ports['read']}"
    metrics = f"http://127.0.0.1:{ports['metrics']}"
    try:
        ready_by = time.monotonic() + 180.0
        while True:
            assert proc.poll() is None, "serve --workers died during boot"
            try:
                status, _, _ = _http(
                    "GET", f"{metrics}/health/ready", timeout=2.0
                )
                if status == 200:
                    break
            except OSError:
                pass
            assert time.monotonic() < ready_by, "topology never became ready"
            time.sleep(0.5)

        expand_url = (
            f"{read}/relation-tuples/expand?"
            "namespace=Folder&object=keto&relation=viewers&max-depth=3"
        )
        results = []
        lock = threading.Lock()

        def one(i):
            case, want = CASES[i % len(CASES)]
            t0 = time.monotonic()
            try:
                if i % 5 == 4:
                    status, body, _ = _http(
                        "GET", expand_url,
                        headers={"X-Request-Timeout": "10s"}, timeout=20.0,
                    )
                    ok = status in (200, 429, 503, 504)
                else:
                    status, body, _ = _http(
                        "GET", _check_url(read, case),
                        headers={"X-Request-Timeout": "10s"}, timeout=20.0,
                    )
                    # non-shed verdicts MUST match the oracle; sheds and
                    # deadline hits are explicit, bounded answers
                    ok = status in (429, 503, 504) or (
                        status == 200
                        and json.loads(body)["allowed"] is want
                    )
                with lock:
                    results.append((i, status, time.monotonic() - t0, ok))
            except Exception as e:  # noqa: BLE001 - a hang IS the failure
                with lock:
                    results.append(
                        (i, f"exc:{e}", time.monotonic() - t0, False)
                    )

        n = 500
        threads = []
        for batch in range(0, n, 16):
            batch_threads = [
                threading.Thread(target=one, args=(i,), daemon=True)
                for i in range(batch, min(batch + 16, n))
            ]
            for t in batch_threads:
                t.start()
            threads.extend(batch_threads)
            for t in batch_threads:
                t.join(timeout=30.0)
        assert len(results) == n, (
            f"only {len(results)}/{n} requests resolved — hung RPCs"
        )
        bad = [r for r in results if not r[3]]
        assert not bad, f"wrong verdicts/statuses: {bad[:10]}"
        # bounded tails: no request ran past its deadline + overhead
        slow_tail = [r for r in results if r[2] > 15.0]
        assert not slow_tail, f"unbounded tail: {slow_tail[:10]}"
        # the fault plan actually fired (rates are high enough that a
        # fault-free run is impossible at n=500)
        statuses = {r[1] for r in results}
        assert statuses & {200, 429, 503, 504}
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


# -- ISSUE 10: shard-device fault storm against the mesh engine --------------


class TestMeshShardFaultStorm:
    """A shard-device fault mid-storm must not stop the wave: checks keep
    answering exactly (surviving replicas / host oracle), the per-shard
    fallback gauge moves ONLY on the faulted shard, and dropping the
    plan restores zero-fallback serving with the victim gauge at zero."""

    def test_storm_keeps_answering_and_recovers(self):
        import numpy as np

        from ketotpu.parallel import MeshCheckEngine
        from ketotpu.parallel.graphshard import shard_of_np
        from ketotpu.utils.synth import build_synth, synth_queries

        graph = build_synth(n_users=128, n_groups=8, n_folders=64,
                            n_docs=256, seed=7)
        eng = MeshCheckEngine(
            graph.store, graph.manager, mesh_devices=8,
            frontier=1024, arena=4096, max_batch=512,
        )
        warm = synth_queries(graph, 128, seed=51)
        assert eng.batch_check(warm) == [
            eng.oracle.check_is_member(q) for q in warm
        ]

        rounds = [synth_queries(graph, 64, seed=100 + r) for r in range(6)]
        wants = [
            [eng.oracle.check_is_member(q) for q in qs] for qs in rounds
        ]
        v = eng._vocab
        flat = [q for qs in rounds for q in qs]
        owners = shard_of_np(
            np.array([v.namespaces.lookup(q.namespace) for q in flat]),
            np.array([v.objects.lookup(q.object) for q in flat]), 8,
        )
        victim = int(np.bincount(owners, minlength=8).argmax())
        fb0 = np.array([r["fallbacks"] for r in eng.shard_stats()])

        mismatches = []

        def fire(qs, want):
            got = eng.batch_check(qs)
            if got != want:
                mismatches.append((got, want))

        faults.configure(shard_error_rate=1.0, shard_id=victim)
        try:
            threads = [
                threading.Thread(target=fire, args=(qs, w), daemon=True)
                for qs, w in zip(rounds, wants)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180.0)
            assert not any(t.is_alive() for t in threads), "storm wedged"
            assert not mismatches, mismatches[:2]
            assert eng.mesh_stats()["shards_down"] == 1
            delta = np.array(
                [r["fallbacks"] for r in eng.shard_stats()]
            ) - fb0
            assert delta[victim] > 0, "faulted shard took no fallbacks"
            assert all(
                int(d) == 0 for i, d in enumerate(delta) if i != victim
            ), f"healthy shards took fallbacks: {delta.tolist()}"
        finally:
            faults.reset()

        # recovery: the next dispatch polls the lifted plan, re-ships the
        # shard, zeroes its gauge — and serving is fallback-free again
        fb1 = np.array([r["fallbacks"] for r in eng.shard_stats()])
        post = synth_queries(graph, 64, seed=200)
        assert eng.batch_check(post) == [
            eng.oracle.check_is_member(q) for q in post
        ]
        assert not eng._shard_down.any()
        stats = eng.shard_stats()
        assert stats[victim]["fallbacks"] == 0
        assert eng.mesh_stats()["shard_recoveries"] >= 1
        after = np.array([r["fallbacks"] for r in stats])
        assert all(
            int(after[i] - fb1[i]) == 0 for i in range(8) if i != victim
        ), "recovered serving must add no fallbacks on healthy shards"


# -- shadow-verification plane under chaos -----------------------------------


class TestShadowZeroDivergenceUnderChaos:
    """The always-on shadow plane must stay at exactly zero divergence
    while the system is being actively hurt: shard/device faults push
    checks onto the oracle fallback (same verdicts, different tier) and
    write storms race the sampler (the same-snapshot guard skips raced
    samples instead of misfiling them as divergences)."""

    def _server(self):
        cfg = Provider({
            "serve": {
                n: {"host": "127.0.0.1", "port": 0}
                for n in ("read", "write", "metrics", "opl")
            },
            "namespaces": {
                "location": str(FIXTURES / "rewrites_namespaces.keto.ts")
            },
            "engine": {"kind": "tpu", "frontier": 512, "arena": 2048,
                       "max_batch": 128},
            # shadow EVERY check so the storm itself is the sample set
            "observability": {"shadow": {"sample_rate": 1}},
            "log": {"request_log": False},
        })
        reg = Registry(cfg).init()
        reg.store().write_relation_tuples(
            *[RelationTuple.from_string(s) for s in SEED_TUPLES]
        )
        return serve_all(reg)

    def _storm(self, read, n, threads=6):
        results = []
        lock = threading.Lock()

        def one(i):
            case, want = CASES[i % len(CASES)]
            try:
                status, body, _ = _http(
                    "GET", _check_url(read, case),
                    headers={"X-Request-Timeout": "10s"}, timeout=20.0,
                )
                ok = status in (429, 503, 504) or (
                    status == 200 and json.loads(body)["allowed"] is want
                )
                with lock:
                    results.append((i, status, ok))
            except Exception as e:  # noqa: BLE001 - a hang IS the failure
                with lock:
                    results.append((i, f"exc:{e}", False))

        for base in range(0, n, threads):
            batch = [
                threading.Thread(target=one, args=(i,), daemon=True)
                for i in range(base, min(base + threads, n))
            ]
            for t in batch:
                t.start()
            for t in batch:
                t.join(timeout=60.0)
        assert len(results) == n, "every request must resolve (zero hangs)"
        bad = [r for r in results if not r[2]]
        assert not bad, f"wrong verdicts/statuses: {bad[:10]}"

    def _assert_clean(self, srv):
        sh = srv.registry.shadow()
        assert sh is not None
        assert sh.drain(timeout=120.0), "shadow replay queue never drained"
        st = sh.stats()
        assert st["divergences"] == 0, sh.ledger()
        assert sh.ledger() == []
        m = srv.registry.metrics()
        assert m.get_counter("keto_shadow_divergence_total") == 0
        return st

    def test_device_fault_storm_zero_divergence(self):
        """Device/shard dispatch faults mid-storm: verdicts keep matching
        the oracle (fallback tier), so the shadow plane — sampling every
        one of them — scores agreement across the board."""
        srv = self._server()
        read = "http://%s:%d" % tuple(srv.addresses["read"])
        try:
            status, body, _ = _http("GET", _check_url(read, CASES[0][0]))
            assert status == 200, body  # warm before hurting the device
            faults.configure(device_error_rate=0.4, latency_ms=2.0,
                             latency_rate=0.2, shard_error_rate=1.0,
                             shard_id=0, seed=9)
            try:
                self._storm(read, n=48)
            finally:
                faults.reset()
            st = self._assert_clean(srv)
            # the storm's checks were actually scored (store is quiet:
            # nothing to go stale against)
            assert st["checks"] >= 40, st
        finally:
            faults.reset()
            srv.stop()

    def test_write_storm_zero_false_divergence(self):
        """A write storm racing the sampler: raced samples are skipped by
        the same-snapshot guard (counted, not scored) and the scored rest
        diverges exactly zero times — no false positives from snapshot
        skew."""
        srv = self._server()
        read = "http://%s:%d" % tuple(srv.addresses["read"])
        reg = srv.registry
        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                # unrelated tuples: log_head churns, CASES verdicts don't
                reg.store().write_relation_tuples(
                    RelationTuple.from_string(f"File:junk{i}#owners@nobody")
                )
                i += 1
                time.sleep(0.002)

        w = threading.Thread(target=writer, daemon=True)
        try:
            status, body, _ = _http("GET", _check_url(read, CASES[0][0]))
            assert status == 200, body
            w.start()
            self._storm(read, n=60)
            stop.set()
            w.join(timeout=30.0)
            st = self._assert_clean(srv)
            # the plane did real work under the storm: samples were taken,
            # and every one was either scored clean or skipped as stale
            assert st["checks"] + st["skipped"] >= 50, st
        finally:
            stop.set()
            srv.stop()


# -- ISSUE 14: whole-host kill mid-storm against the 2-host mesh -------------


class TestMeshHostKillStorm:
    """Killing one of two owner hosts mid-storm must not stop (or skew)
    a single wave: heartbeat loss marks every shard the dead peer owns
    down AT ONCE, its rows degrade to cross-host replicas or the host
    oracle, verdicts stay bit-identical throughout (zero divergence),
    the fallback attribution moves ONLY on the dead peer — local shard
    gauges stay flat — and the returning peer rejoins warm."""

    @pytest.mark.slow
    def test_host_kill_mid_storm_zero_divergence(self):
        from ketotpu.parallel import HostLink, MeshCheckEngine
        from ketotpu.utils.synth import build_synth, synth_queries

        graph = build_synth(n_users=128, n_groups=8, n_folders=64,
                            n_docs=256, seed=9)
        links = [
            HostLink(
                h, ["127.0.0.1:0", "127.0.0.1:0"], "chaos-secret",
                heartbeat_ms=100, miss_budget=2, rpc_timeout_ms=180000,
            )
            for h in range(2)
        ]
        a0, a1 = links[0].bind(), links[1].bind()
        links[0].set_peer_addr(1, a1)
        links[1].set_peer_addr(0, a0)
        engs = [
            MeshCheckEngine(
                graph.store, graph.manager, mesh_devices=4,
                frontier=1024, arena=4096, max_batch=512,
                hostlink=links[h],
            )
            for h in range(2)
        ]
        try:
            # warm both hosts locally (XLA compile) before the storm
            warm = synth_queries(graph, 96, seed=61)
            for e in (engs[1], engs[0]):
                e._peer_serve_check(warm, 0)
            for l in links:
                l.heartbeat_now()

            rounds = [
                synth_queries(graph, 64, seed=300 + r) for r in range(8)
            ]
            wants = [
                [engs[0].oracle.check_is_member(q) for q in qs]
                for qs in rounds
            ]
            # absorb first-shape compiles on both sides of the lane so
            # the storm below runs at steady state
            assert engs[0].batch_check(rounds[0]) == wants[0]
            shard_fb0 = int(engs[0]._shard_fallbacks.sum())
            mismatches = []

            def fire(qs, want):
                got = engs[0].batch_check(qs)
                if got != want:
                    mismatches.append((got, want))

            threads = [
                threading.Thread(target=fire, args=(qs, w), daemon=True)
                for qs, w in zip(rounds, wants)
            ]
            for t in threads:
                t.start()
            # kill host 1 mid-storm: its PeerLink goes silent (frames
            # unanswered, heartbeats stop) exactly like a dead process
            time.sleep(0.2)
            faults.configure(peer_down=1)
            for _ in range(links[0].miss_budget):
                links[0].heartbeat_now()
            assert links[0].peer_down(1)
            for t in threads:
                t.join(timeout=300.0)
            assert not any(t.is_alive() for t in threads), "storm wedged"
            assert not mismatches, mismatches[:2]  # zero divergence
            assert engs[0].mesh_stats()["hosts_down"] == 1
            # every degraded verdict is attributed to the dead PEER;
            # the local shard fallback gauges must not move at all
            assert int(engs[0]._peer_fallbacks[1]) > 0
            assert int(engs[0]._shard_fallbacks.sum()) == shard_fb0

            # recovery: clearing the fault and answering one beat marks
            # the host up; rows route cross-host again, still exact
            faults.reset()
            rec0 = links[0].peer_recoveries
            links[0].heartbeat_now()
            assert not links[0].peer_down(1)
            assert links[0].peer_recoveries == rec0 + 1
            routed0 = int(engs[0].peer_route_counts()[1])
            assert engs[0].batch_check(rounds[0]) == wants[0]
            assert int(engs[0].peer_route_counts()[1]) > routed0
            assert engs[0].mesh_stats()["hosts_down"] == 0
        finally:
            faults.reset()
            for e in engs:
                e.close()


# -- ISSUE 17: overload control -----------------------------------------------


class TestWorkerWireBreaker:
    """The worker-wire circuit breaker: injected owner wedges (no
    response frame = transport failure) trip the lane open, open means
    fail-FAST instead of burning the reconnect schedule, and the
    half-open probe closes it the moment the owner answers again."""

    def _remote(self, sock):
        from ketotpu.server.workers import RemoteCheckEngine

        return RemoteCheckEngine(sock, breaker_config={
            "window_s": 10.0, "min_volume": 4,
            "failure_ratio": 0.5, "cooldown_s": 0.3,
        })

    def test_breaker_trips_fails_fast_and_recovers(self, tmp_path):
        from ketotpu.server.workers import EngineHostServer

        owner = _oracle_host(tmp_path, "breaker")
        sock = str(tmp_path / "breaker.sock")
        host = EngineHostServer(owner, sock).start()
        q = RelationTuple.from_string("Folder:keto#view@bob")
        try:
            remote = self._remote(sock)
            assert remote.check(q) is True  # healthy wire, warm pool
            assert remote.breaker.state == "closed"

            # owner wedges: every exchange dies with no response frame
            faults.configure(worker_error_rate=1.0, seed=3)
            with pytest.raises(ConnectionError):
                remote.check(q)
            assert remote.breaker.state == "open"
            assert remote.breaker.trips == 1

            # open = fail fast: no connect, no backoff burn
            t0 = time.monotonic()
            with pytest.raises(ConnectionError) as ei:
                remote.check(q)
            assert time.monotonic() - t0 < 0.1
            assert "circuit breaker open" in str(ei.value)

            # owner heals; past the cooldown one probe closes the lane
            faults.reset()
            time.sleep(0.35)
            assert remote.check(q) is True
            assert remote.breaker.state == "closed"
            # and it stays closed for ordinary traffic
            assert all(remote.check(q) for _ in range(4))
        finally:
            faults.reset()
            host.stop()

    def test_typed_errors_never_trip_the_breaker(self, tmp_path):
        from ketotpu.server.workers import EngineHostServer

        owner = _oracle_host(tmp_path, "typedbrk")
        sock = str(tmp_path / "typedbrk.sock")
        host = EngineHostServer(owner, sock).start()
        try:
            remote = self._remote(sock)
            # typed errors are COMPLETED exchanges on a healthy wire:
            # a burst of client errors must not open the lane
            for _ in range(8):
                with pytest.raises(KetoAPIError):
                    remote.check(
                        RelationTuple.from_string("Folder:f#nosuch@a")
                    )
            assert remote.breaker.state == "closed"
            assert remote.breaker.trips == 0
        finally:
            host.stop()


@pytest.mark.slow
class TestOverloadStorm:
    """The ISSUE 17 acceptance storm: a sustained 2x-capacity mixed
    flood with misbehaving clients (retry-storm fault: the SDK ignores
    Retry-After and its retry budget).  The plane must shed batch before
    interactive, keep answering interactive checks throughout, escalate
    the brownout ladder, give exact verdicts on everything it admits
    (zero shadow divergence), and converge back to normal service once
    the flood stops."""

    def test_two_x_flood_sheds_batch_first_and_converges(self):
        from ketotpu.sdk import KetoClient
        from ketotpu.server.admission import CLASS_BATCH, CLASS_INTERACTIVE

        cfg = Provider({
            "serve": {
                n: {"host": "127.0.0.1", "port": 0}
                for n in ("read", "write", "metrics", "opl")
            },
            "namespaces": {
                "location": str(FIXTURES / "rewrites_namespaces.keto.ts")
            },
            "engine": {"kind": "tpu", "frontier": 512, "arena": 2048,
                       "max_batch": 128},
            # a deliberately small serving capacity so a laptop-sized
            # flood is genuinely 2x+: the AIMD limit lives in [4, 16]
            "limit": {"max_inflight": 8, "request_timeout_ms": 10000},
            "observability": {"shadow": {"sample_rate": 1}},
            "overload": {"floor": 4, "ceiling": 16, "increase": 4,
                         "interval_ms": 100, "hold_ms": 400},
            "log": {"request_log": False},
        })
        reg = Registry(cfg).init()
        srv = serve_all(reg)
        reg.store().write_relation_tuples(
            *[RelationTuple.from_string(s) for s in SEED_TUPLES]
        )
        read = "http://%s:%d" % tuple(srv.addresses["read"])
        try:
            # warm: absorb first-shape compiles before offering load.
            # a cold compile can outlive the 10s request budget (the
            # waiting caller gets 504 while the wave finishes compiling
            # on the worker), so retry until the cache is hot
            status, body = 0, b""
            for _ in range(6):
                status, body, _ = _http(
                    "GET", _check_url(read, CASES[0][0]), timeout=20.0
                )
                if status == 200:
                    break
            assert status == 200, body
            _post_batch = lambda: _http(
                "POST", f"{read}/relation-tuples/batch/check",
                json.dumps({"tuples": [
                    RelationTuple.from_string(c).to_json()
                    for c, _ in CASES[:4] * 2
                ]}).encode(),
                {"Content-Type": "application/json"}, timeout=20.0,
            )
            for _ in range(6):
                status, _, _ = _post_batch()
                if status == 200:
                    break
            assert status == 200

            # misbehaving clients: retries ignore the budget + hint
            faults.configure(retry_storm_rate=1.0, seed=17)
            stop_at = time.monotonic() + 3.0
            lock = threading.Lock()
            inter = {"ok": 0, "shed": 0, "wrong": 0, "hung": 0}
            batch = {"ok": 0, "shed": 0, "hung": 0}

            def interactive_client(i):
                cli = KetoClient(read, max_retries=2, timeout=20.0)
                j = 0
                while time.monotonic() < stop_at:
                    case, want = CASES[(i + j) % len(CASES)]
                    j += 1
                    t = RelationTuple.from_string(case)
                    try:
                        got = cli.check_tuple(t)
                        with lock:
                            if got is want:
                                inter["ok"] += 1
                            else:
                                inter["wrong"] += 1
                    except Exception as e:  # noqa: BLE001
                        name = type(e).__name__
                        with lock:
                            if "429" in str(e) or "503" in str(e):
                                inter["shed"] += 1
                            elif name in ("SDKError",):
                                inter["shed"] += 1
                            else:
                                inter["hung"] += 1

            def batch_client():
                while time.monotonic() < stop_at:
                    try:
                        status, _, _ = _post_batch()
                        with lock:
                            if status == 200:
                                batch["ok"] += 1
                            elif status in (429, 503):
                                batch["shed"] += 1
                            else:
                                batch["hung"] += 1
                    except Exception:  # noqa: BLE001
                        with lock:
                            batch["hung"] += 1

            threads = [
                threading.Thread(
                    target=interactive_client, args=(i,), daemon=True)
                for i in range(12)
            ] + [
                threading.Thread(target=batch_client, daemon=True)
                for _ in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60.0)
            assert not any(t.is_alive() for t in threads), "storm wedged"
            faults.reset()

            ctl = reg.admission()
            ov = reg.overload()
            # the flood actually overloaded the plane...
            assert ctl.shed > 0, "storm never hit capacity"
            # ...and every admitted verdict was exact
            assert inter["wrong"] == 0
            assert inter["hung"] == 0 and batch["hung"] == 0
            # interactive goodput survived the whole storm
            assert inter["ok"] > 0, (inter, batch)
            # shed ordering: batch sheds, interactive keeps landing —
            # proportionally batch must shed at least as hard
            shed_by = ctl.shed_by_class
            assert shed_by[CLASS_BATCH] > 0, (shed_by, batch)
            inter_tries = inter["ok"] + inter["shed"]
            batch_tries = batch["ok"] + batch["shed"]
            if inter_tries and batch_tries:
                assert (batch["shed"] / batch_tries
                        >= inter["shed"] / inter_tries - 0.05), (
                    inter, batch)
            # the storm was observable: limit + stage published
            m = reg.metrics()
            assert m.get_gauge("keto_admission_limit") >= 1.0
            assert m.counter_total("keto_requests_shed_total") > 0

            # convergence: flood gone, ladder steps down (hold 400ms per
            # stage), interactive flows again without client retries
            deadline_at = time.monotonic() + 15.0
            cli = KetoClient(read, max_retries=0, timeout=10.0)
            last = None
            while time.monotonic() < deadline_at:
                try:
                    assert cli.check_tuple(
                        RelationTuple.from_string(CASES[0][0])
                    ) is CASES[0][1]
                    last = "ok"
                    break
                except Exception as e:  # noqa: BLE001
                    last = e
                    time.sleep(0.2)
            assert last == "ok", f"storm never converged: {last}"
            assert ov is not None and ov.stage <= 1

            # zero divergence: the shadow plane scored the admitted
            # checks and found nothing
            sh = reg.shadow()
            assert sh is not None
            assert sh.drain(timeout=120.0), "shadow queue never drained"
            assert sh.stats()["divergences"] == 0, sh.ledger()
            assert m.get_counter("keto_shadow_divergence_total") == 0
        finally:
            faults.reset()
            srv.stop()
