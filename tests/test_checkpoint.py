"""Snapshot checkpoint/resume tests (SURVEY §5.4): persisted projections
restore bit-identically, and stale/mismatched checkpoints are refused."""

import dataclasses

import numpy as np
import pytest

from ketotpu.api.types import RelationTuple
from ketotpu.engine import checkpoint as ckpt
from ketotpu.engine.snapshot import Snapshot
from ketotpu.engine.tpu import DeviceCheckEngine
from ketotpu.utils.synth import build_synth, synth_queries

T = RelationTuple.from_string


@pytest.fixture(scope="module")
def graph():
    return build_synth(n_users=64, n_groups=8, n_folders=32, n_docs=128)


def _engine(graph):
    return DeviceCheckEngine(
        graph.store, graph.manager, frontier=2048, arena=4096, max_batch=512
    )


def test_roundtrip_bit_identical(graph, tmp_path):
    eng = _engine(graph)
    snap = eng.snapshot()
    path = str(tmp_path / "snap.npz")
    eng.save_checkpoint(path)
    loaded = ckpt.load_snapshot(path)
    for f in dataclasses.fields(Snapshot):
        a, b = getattr(snap, f.name), getattr(loaded, f.name)
        if isinstance(a, np.ndarray):
            assert a.dtype == b.dtype and (a == b).all(), f.name
        elif isinstance(a, int):
            assert a == b, f.name
    assert snap.node_tab.keys() == loaded.node_tab.keys()
    for k in snap.node_tab:
        assert (snap.node_tab[k] == loaded.node_tab[k]).all(), k
    for name in ("namespaces", "objects", "relations", "subjects"):
        assert getattr(snap.vocab, name).strings() == \
            getattr(loaded.vocab, name).strings()


def test_resume_skips_projection_and_answers_identically(graph, tmp_path):
    eng = _engine(graph)
    qs = synth_queries(graph, 200, seed=3)
    want = eng.batch_check(qs)
    path = str(tmp_path / "snap.npz")
    eng.save_checkpoint(path)

    fresh = _engine(graph)
    assert fresh.load_checkpoint(path) is True
    assert fresh.rebuilds == 0  # projection skipped
    assert fresh.batch_check(qs) == want
    assert fresh.rebuilds == 0
    # writes after resume still reach the device (overlay path intact)
    graph.store.write_relation_tuples(T("Group:g0#members@resumed"))
    assert fresh.batch_check(
        [T("Group:g0#members@resumed")]
    ) == [True]


def test_stale_store_version_is_refused(graph, tmp_path):
    eng = _engine(graph)
    path = str(tmp_path / "snap.npz")
    eng.save_checkpoint(path)
    graph.store.write_relation_tuples(T("Group:g1#members@late"))
    fresh = _engine(graph)
    assert fresh.load_checkpoint(path) is False
    # and the fallback projection sees the late write
    assert fresh.batch_check([T("Group:g1#members@late")]) == [True]


def test_config_fingerprint_mismatch_is_refused(graph, tmp_path):
    from ketotpu.opl.parser import parse
    from ketotpu.storage.namespaces import StaticNamespaceManager

    eng = _engine(graph)
    path = str(tmp_path / "snap.npz")
    eng.save_checkpoint(path)
    namespaces, errors = parse("class Other implements Namespace {}")
    assert not errors
    other = DeviceCheckEngine(
        graph.store, StaticNamespaceManager(namespaces),
        frontier=2048, arena=4096,
    )
    assert other.load_checkpoint(path) is False


def test_format_mismatch_is_refused(graph, tmp_path, monkeypatch):
    eng = _engine(graph)
    path = str(tmp_path / "snap.npz")
    eng.save_checkpoint(path)
    monkeypatch.setattr(ckpt, "SNAPSHOT_FORMAT", ckpt.SNAPSHOT_FORMAT + 1)
    with pytest.raises(ckpt.SnapshotFormatError):
        ckpt.load_snapshot(path)
    fresh = _engine(graph)
    assert fresh.load_checkpoint(path) is False  # graceful refusal


def test_registry_boot_checkpoint_cycle(tmp_path):
    """engine.checkpoint config: first boot saves, second boot resumes."""
    from ketotpu.driver import Provider, Registry

    path = tmp_path / "proj.npz"
    db = tmp_path / "keto.db"

    def boot():
        reg = Registry(Provider({
            "dsn": f"sqlite://{db}",
            "namespaces": [{"id": 0, "name": "doc", "relations": ["viewers"]}],
            "engine": {
                "kind": "tpu", "frontier": 512, "arena": 1024,
                "max_batch": 256, "checkpoint": str(path),
            },
        }))
        if not db.exists() or True:
            reg.store().migrate_up()
        return reg.init()

    reg1 = boot()
    reg1.store().write_relation_tuples(T("doc:d#viewers@alice"))
    assert reg1.check_engine().batch_check([T("doc:d#viewers@alice")]) == [True]
    # persist the current projection for the next boot
    reg1.check_engine().save_checkpoint(str(path))
    reg1.store().close()

    reg2 = boot()
    eng2 = reg2.check_engine()
    assert eng2.rebuilds == 0  # resumed, not re-projected
    assert eng2.batch_check(
        [T("doc:d#viewers@alice"), T("doc:d#viewers@eve")]
    ) == [True, False]


def test_resume_preserves_overlay_safety_metadata(tmp_path):
    """A resumed snapshot must keep dyn_pairs: an insert that creates a NEW
    relation-level subject-set pair cannot be folded into the overlay (the
    taint classification could be stale) — it must force a rebuild."""
    from ketotpu.opl.parser import parse
    from ketotpu.storage.memory import InMemoryTupleStore
    from ketotpu.storage.namespaces import StaticNamespaceManager

    namespaces, errors = parse(
        "class User implements Namespace {}\n"
        "class Group implements Namespace {\n"
        "  related: { members: (User | Group)[] }\n"
        "}\n"
        "class Doc implements Namespace {\n"
        "  related: { viewers: (User | SubjectSet<Group, \"members\">)[] }\n"
        "  permits = { view: (ctx) => "
        "this.related.viewers.includes(ctx.subject) }\n"
        "}"
    )
    assert not errors
    manager = StaticNamespaceManager(namespaces)
    store = InMemoryTupleStore()
    store.write_relation_tuples(T("Doc:d#viewers@alice"))
    eng = DeviceCheckEngine(store, manager, frontier=512, arena=1024)
    path = str(tmp_path / "snap.npz")
    eng.save_checkpoint(path)

    fresh = DeviceCheckEngine(store, manager, frontier=512, arena=1024)
    assert fresh.load_checkpoint(path) is True
    assert fresh._snap.dyn_pairs == eng._snap.dyn_pairs
    # this subject-set insert creates a relation-level pair absent from the
    # base snapshot: must trigger a full rebuild, not an overlay apply
    store.write_relation_tuples(T("Doc:d#viewers@Group:g#members"))
    store.write_relation_tuples(T("Group:g#members@bob"))
    assert fresh.batch_check([T("Doc:d#view@bob")]) == [True]
    assert fresh.rebuilds >= 1
